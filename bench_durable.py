#!/usr/bin/env python3
"""Durable-fabric bench: full-fleet kill + checkpoint restore, and
snapshot-hydrated provisioning vs wholesale Sync (ISSUE 16).

Two phases against a quorum-replicated PS fabric, every server carrying
a :class:`brpc_tpu.durable.CheckpointStore`:

- **fleet kill**: a single exact-ledger writer streams acked batches;
  MID-load the ENTIRE fleet is closed (nothing survives in memory).
  Fresh servers attach the same stores, replay base + delta chain, and
  the restored tables must equal the seed tables minus exactly one
  ``GRAD_VALUE`` per acked occurrence — the one write in flight at the
  kill is the ONLY permitted ambiguity (it was never acked, so either
  applied-or-not is a legal outcome, checked per shard).  The
  wall-clock from kill to first served lookup is the measured
  recovery-time bound.
- **provisioning**: a new backup seeded the OLD way (wholesale Sync:
  the live primary ships its whole table) vs the NEW way
  (``durable.hydrate_replica`` seeds from the store; the primary ships
  only the delta tail), plus a 1→2 split whose destinations hydrate
  via ``durable.hydrate_destination`` — the source-side bytes shipped
  are read off the obs counters and the hydrated paths must be
  measurably cheaper on the live source.

Emits ONE JSON line and refreshes BENCH_durable.json.  Degrades to
{"skipped": ...} without the native core.
"""

import json
import os
import shutil
import tempfile
import threading
import time

ROOT = os.path.dirname(os.path.abspath(__file__))

# Process-global fiber pool: this scenario runs up to ~10 servers whose
# handlers hold a worker through quorum ack barriers.
os.environ.setdefault("BRT_WORKERS", "16")

VOCAB, DIM = 1024, 16
NSHARDS, REPLICAS = 2, 2
WRITE_BATCH = 32
SEED = 23
KILL_AFTER_BATCHES = 40
RECOVERY_BOUND_S = 10.0


def main() -> int:  # noqa: C901 — one scenario, phases inline
    try:
        from brpc_tpu import rpc
        if not rpc.native_core_available():
            print(json.dumps({"skipped": "native core unavailable"}))
            return 0
    except Exception as e:  # noqa: BLE001 — bench must degrade, not die
        print(json.dumps({"skipped": f"{type(e).__name__}: {e}"[:200]}))
        return 0
    import numpy as np

    from brpc_tpu import durable, obs, press, resilience
    from brpc_tpu.durable import CheckpointStore
    from brpc_tpu.naming import PartitionScheme, ReplicaSet
    from brpc_tpu.ps_remote import PsShardServer, RemoteEmbedding
    from brpc_tpu.reshard import MigrationDriver

    obs.set_enabled(True)
    t_bench0 = time.monotonic()
    GRAD = press.GRAD_VALUE
    rows_per = VOCAB // NSHARDS
    ckpt_root = tempfile.mkdtemp(prefix="bench_durable_")

    def counter(name):
        return int(obs.counter(name).get_value())

    def spawn_fleet():
        """NSHARDS x REPLICAS quorum fleet, one store per server."""
        servers, stores, sets = [], [], []
        for s in range(NSHARDS):
            row, srow = [], []
            for r in range(REPLICAS):
                sv = PsShardServer(VOCAB, DIM, s, NSHARDS, lr=1.0,
                                   seed=SEED)
                st = CheckpointStore(
                    os.path.join(ckpt_root, f"shard{s}-rep{r}"))
                row.append(sv)
                srow.append(st)
            servers.append(row)
            stores.append(srow)
            sets.append(ReplicaSet(tuple(sv.address for sv in row),
                                   primary=0))
        return servers, stores, sets

    out = {}
    ok = True
    servers = stores = []
    emb = emb2 = emb3 = drv = None
    extra = []
    try:
        # -- phase 1: acked load, then kill the ENTIRE fleet --------------
        servers, stores, sets = spawn_fleet()
        init_tables = np.concatenate(
            [servers[s][0].table.copy() for s in range(NSHARDS)])
        for s in range(NSHARDS):
            for r in range(REPLICAS):
                servers[s][r].attach_checkpoint(stores[s][r])
                servers[s][r].configure_replication(sets[s], r)
        sc = PartitionScheme(0, tuple(sets))
        emb = RemoteEmbedding([sc], VOCAB, DIM, timeout_ms=2000,
                              retry=resilience.RetryPolicy(
                                  max_attempts=2,
                                  backoff=resilience.Backoff(
                                      base_ms=1, max_ms=10),
                                  attempt_timeout_ms=800))

        counts = np.zeros(VOCAB, np.int64)      # acked occurrences
        acked = [0]
        failed_ids = [None]                     # the in-flight batch
        stop = threading.Event()

        def writer():
            wrng = np.random.default_rng(SEED + 1)
            while not stop.is_set():
                ids = wrng.integers(0, VOCAB,
                                    WRITE_BATCH).astype(np.int32)
                grads = np.full((WRITE_BATCH, DIM), GRAD, np.float32)
                try:
                    emb.apply_gradients(ids, grads)
                except Exception:  # noqa: BLE001 — the fleet died
                    failed_ids[0] = ids
                    return
                np.add.at(counts, ids, 1)
                acked[0] += 1

        wt = threading.Thread(target=writer, daemon=True)
        wt.start()
        while acked[0] < KILL_AFTER_BATCHES and wt.is_alive():
            time.sleep(0.01)

        # the kill: every server in the fleet closes MID-load; nothing
        # survives in process memory, only the checkpoint stores
        t_kill = time.monotonic()
        for row in servers:
            for sv in row:
                sv.close()
        wt.join(timeout=15)
        stop.set()
        acked_batches = acked[0]

        # -- restore: fresh servers, same stores --------------------------
        servers2, stores2, sets2 = [], [], []
        for s in range(NSHARDS):
            row, srow = [], []
            for r in range(REPLICAS):
                sv = PsShardServer(VOCAB, DIM, s, NSHARDS, lr=1.0,
                                   seed=SEED)
                st = CheckpointStore(
                    os.path.join(ckpt_root, f"shard{s}-rep{r}"))
                sv.attach_checkpoint(st)        # replay base + deltas
                row.append(sv)
                srow.append(st)
            servers2.append(row)
            stores2.append(srow)
            sets2.append(ReplicaSet(tuple(sv.address for sv in row),
                                    primary=0))
        hyd0 = counter("ps_replica_hydrates")
        for s in range(NSHARDS):
            for r in range(REPLICAS):
                servers2[s][r].configure_replication(sets2[s], r)
        sc2 = PartitionScheme(0, tuple(sets2))
        emb2 = RemoteEmbedding([sc2], VOCAB, DIM, timeout_ms=5000,
                               retry=resilience.RetryPolicy(
                                   max_attempts=4,
                                   backoff=resilience.Backoff(
                                       base_ms=2, max_ms=50),
                                   attempt_timeout_ms=1000))
        emb2.lookup(np.arange(8, dtype=np.int32))   # first served read
        recovery_s = time.monotonic() - t_kill

        # the restored fleet keeps taking acked writes
        post_ids = np.arange(WRITE_BATCH, dtype=np.int32)
        emb2.apply_gradients(post_ids, np.full((WRITE_BATCH, DIM),
                                               GRAD, np.float32))
        np.add.at(counts, post_ids, 1)

        # -- the exact ledger (order-free replay: GRAD is a power of
        # two, so per-id subtraction is exact in any order) --------------
        expect = init_tables.copy()
        for step in range(int(counts.max())):
            expect[counts > step] -= np.float32(GRAD)
        ledger_exact = True
        ambiguous_applied = []
        for s in range(NSHARDS):
            got = servers2[s][0].table
            base = expect[s * rows_per:(s + 1) * rows_per]
            cands = [("without_inflight", base)]
            if failed_ids[0] is not None:
                # the unacked in-flight batch may legally have landed
                alt = base.copy()
                sel = failed_ids[0][(failed_ids[0] >= s * rows_per)
                                    & (failed_ids[0] <
                                       (s + 1) * rows_per)] \
                    - s * rows_per
                if sel.size:
                    np.subtract.at(
                        alt, sel,
                        np.full((sel.size, DIM), GRAD, np.float32))
                    cands.append(("with_inflight", alt))
            hit = next((name for name, c in cands
                        if np.array_equal(got, c)), None)
            ambiguous_applied.append(hit)
            ledger_exact &= hit is not None
        # every backup reconnected through the hydrate path (its gen is
        # inside its primary's delta window after restore)
        restore_hydrates = counter("ps_replica_hydrates") - hyd0

        phase1 = {
            "acked_batches": acked_batches,
            "recovery_s": round(recovery_s, 3),
            "ledger_exact": bool(ledger_exact),
            "inflight_batch_outcome": ambiguous_applied,
            "restore_deltas": counter("ps_ckpt_restore_deltas"),
            "restores": counter("ps_ckpt_restores"),
            "restore_hydrates": restore_hydrates,
        }
        ok &= ledger_exact and recovery_s <= RECOVERY_BOUND_S

        # -- phase 2a: new backup — wholesale Sync vs hydrated seed -------
        prim = servers2[0][0]
        store0 = stores2[0][0]
        table_bytes = rows_per * DIM * 4
        b1 = PsShardServer(VOCAB, DIM, 0, NSHARDS, lr=1.0, seed=SEED)
        extra.append(b1)
        rs3 = ReplicaSet((prim.address, servers2[0][1].address,
                          b1.address), primary=0)
        b1.configure_replication(rs3, 2)
        servers2[0][1].configure_replication(rs3, 1)
        sync_b0 = counter("ps_replica_sync_bytes")
        prim.configure_replication(rs3, 0)
        # b1 was never seeded -> the hydrate guard refuses -> wholesale
        t0 = time.monotonic()
        while (counter("ps_replica_sync_bytes") == sync_b0
               and time.monotonic() - t0 < 15):
            time.sleep(0.02)
        wholesale_bytes = counter("ps_replica_sync_bytes") - sync_b0

        b2 = PsShardServer(VOCAB, DIM, 0, NSHARDS, lr=1.0, seed=SEED)
        extra.append(b2)
        rs4 = ReplicaSet((prim.address, servers2[0][1].address,
                          b1.address, b2.address), primary=0)
        b2.configure_replication(rs4, 3)
        # seed the NEW backup from the checkpoint store, off the
        # primary's serving path, then let the primary ship the tail
        durable.hydrate_replica(store0, b2.address)
        sync_b1 = counter("ps_replica_sync_bytes")
        tail_b0 = counter("ps_replica_hydrate_tail_bytes")
        hyd1 = counter("ps_replica_hydrates")
        servers2[0][1].configure_replication(rs4, 1)
        b1.configure_replication(rs4, 2)
        prim.configure_replication(rs4, 0)
        t0 = time.monotonic()
        while (counter("ps_replica_hydrates") - hyd1 < 3
               and time.monotonic() - t0 < 15):
            time.sleep(0.02)
        prim.flush_replication()
        hydrate_tail_bytes = (counter("ps_replica_hydrate_tail_bytes")
                              - tail_b0)
        hydrate_sync_bytes = counter("ps_replica_sync_bytes") - sync_b1
        replica_converged = bool(
            np.array_equal(prim.table, b2.table))

        phase2a = {
            "table_bytes": table_bytes,
            "wholesale_source_bytes": wholesale_bytes,
            "hydrate_source_tail_bytes": hydrate_tail_bytes,
            "hydrate_wholesale_fallbacks_bytes": hydrate_sync_bytes,
            "converged": replica_converged,
        }

        # -- phase 2b: 1->2 split, destinations hydrated from the store ---
        src = PsShardServer(VOCAB, DIM, 0, 1, lr=1.0, seed=SEED + 9,
                            stream=True)
        extra.append(src)
        src_store = CheckpointStore(os.path.join(ckpt_root, "split-src"))
        src.attach_checkpoint(src_store)
        sc_src = PartitionScheme(0, (ReplicaSet.of(src.address),))
        emb3 = RemoteEmbedding([sc_src], VOCAB, DIM, timeout_ms=5000)
        ids = np.arange(VOCAB, dtype=np.int32)
        for _ in range(8):
            emb3.apply_gradients(ids, np.full((VOCAB, DIM), GRAD,
                                              np.float32))
        src.attach_checkpoint(src_store, recover=False)  # re-base
        # the tail: SMALL batches spread across both halves — the whole
        # point of hydrate-first is that the source only ships these
        tail_ids = (np.arange(WRITE_BATCH, dtype=np.int32)
                    * (VOCAB // WRITE_BATCH))
        for _ in range(2):
            emb3.apply_gradients(tail_ids,
                                 np.full((WRITE_BATCH, DIM), GRAD,
                                         np.float32))
        dst = [PsShardServer(VOCAB, DIM, s, 2, lr=1.0, seed=SEED + 9,
                             stream=True, importing=True,
                             scheme_version=1) for s in range(2)]
        extra.extend(dst)
        half = VOCAB // 2
        for s, sv in enumerate(dst):
            durable.hydrate_destination(src_store, sv.address, 1,
                                        src.address, 0, s * half, half)
        sc_dst = PartitionScheme(1, tuple(ReplicaSet.of(sv.address)
                                          for sv in dst))
        mig_syncs0 = counter("ps_migrate_syncs_out")
        mig_sync_b0 = counter("ps_migrate_sync_bytes")
        mig_tail_b0 = counter("ps_migrate_hydrate_tail_bytes")
        drv = MigrationDriver(sc_src, sc_dst, VOCAB)
        drv.start()
        drv.wait_caught_up(deadline_s=30)
        drv.cutover()
        emb3.close()
        split_wholesale_syncs = (counter("ps_migrate_syncs_out")
                                 - mig_syncs0)
        split_sync_bytes = counter("ps_migrate_sync_bytes") - mig_sync_b0
        split_tail_bytes = (counter("ps_migrate_hydrate_tail_bytes")
                            - mig_tail_b0)
        split_exact = bool(np.array_equal(
            np.concatenate([sv.table for sv in dst]),
            src.table))

        phase2b = {
            "src_table_bytes": VOCAB * DIM * 4,
            "wholesale_range_syncs": split_wholesale_syncs,
            "wholesale_source_bytes": split_sync_bytes,
            "hydrate_source_tail_bytes": split_tail_bytes,
            "hydrates": counter("ps_migrate_hydrates"),
            "split_exact": split_exact,
        }

        criteria = {
            "fleet_kill_lossless_ledger": bool(ledger_exact),
            "recovery_under_bound_s": bool(
                recovery_s <= RECOVERY_BOUND_S),
            "replica_hydrate_cheaper_on_source": bool(
                replica_converged
                and hydrate_tail_bytes + hydrate_sync_bytes
                < wholesale_bytes),
            "split_hydrate_no_wholesale_sync": bool(
                split_exact and split_wholesale_syncs == 0
                and split_tail_bytes < VOCAB * DIM * 4),
        }
        out = {
            "metric": "durable_recovery_time",
            "value": round(recovery_s, 3),
            "unit": "s",
            "recovery_bound_s": RECOVERY_BOUND_S,
            "fleet": f"{NSHARDS}x{REPLICAS}",
            "fleet_kill": phase1,
            "replica_provisioning": phase2a,
            "split_provisioning": phase2b,
            "ckpt": {
                "snapshots": counter("ps_ckpt_snapshots"),
                "deltas": counter("ps_ckpt_deltas"),
                "compactions": counter("ps_ckpt_compactions"),
                "snapshot_bytes": counter("ps_ckpt_snapshot_bytes"),
                "delta_bytes": counter("ps_ckpt_delta_bytes"),
            },
            "criteria": criteria,
            "wall_s": round(time.monotonic() - t_bench0, 2),
        }
        out["ok"] = bool(ok and all(criteria.values()))
    finally:
        if drv is not None:
            try:
                drv.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        for e in (emb, emb2, emb3):
            if e is not None:
                try:
                    e.close()
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
        for group in (servers, locals().get("servers2") or []):
            for row in group:
                for sv in row:
                    try:
                        sv.close()
                    except Exception:  # noqa: BLE001 — already dead
                        pass
        for sv in extra:
            try:
                sv.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        shutil.rmtree(ckpt_root, ignore_errors=True)

    with open(os.path.join(ROOT, "BENCH_durable.json"), "w",
              encoding="utf-8") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
