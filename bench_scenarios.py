#!/usr/bin/env python3
"""Scenario SLO matrix: the overload-control acceptance workload.

Drives the press harness (brpc_tpu.press — seeded zipf skew, read/write
mix, open-loop bursts) against one GIL-bound Python-read shard server —
the honest 1-core capacity model this container can measure — across
the overload-control config matrix:

  limiter ∈ {none, constant, auto} × deadline stamping ∈ {off, on}

and reports, per scenario × config: availability, p50/p99 sojourn of
SUCCESSES (open-loop — measured from scheduled arrival, so queueing is
not hidden), and GOODPUT (in-deadline successes/sec).  The headline
criterion: under the burst-overload scenario the auto limiter +
deadline shedding must hold goodput ≥ 1.5× the bare config and keep
the p99 of successes bounded, while the steady scenarios stay ≥ 0.99
available.  Also proves trace record/replay determinism (the
rpc_press/rpc_replay contract).

Emits ONE JSON line and refreshes BENCH_scenarios.json.  Degrades to
{"skipped": ...} without the native core.
"""

import json
import os
import struct
import threading
import time

ROOT = os.path.dirname(os.path.abspath(__file__))

# Heavy per-request geometry: the per-lookup gather (256 rows x 512
# dims) is the GIL-bound work unit, so the SERVER queue — not the
# in-process client — is the bottleneck the scenarios exercise (a
# 1-core container serves client and server from the same core; tiny
# requests would measure the pacer, not overload control).
VOCAB, DIM, BATCH = 16384, 512, 256
DEADLINE_MS = 100.0
SEED = 11


def _calibrate(rpc, PsShardServer, seconds: float = 0.6) -> float:
    """Closed-loop 4-thread lookup throughput against a bare server:
    the capacity unit every scenario rate is expressed in."""
    import numpy as np
    srv = PsShardServer(VOCAB, DIM, 0, 1)
    ch = rpc.Channel(srv.address, timeout_ms=2000)
    rng = np.random.default_rng(SEED)
    req = struct.pack("<i", BATCH) + np.sort(
        rng.integers(0, VOCAB, BATCH)).astype(np.int32).tobytes()
    stop = time.monotonic() + seconds
    counts = [0] * 4

    def loop(i: int) -> None:
        while time.monotonic() < stop:
            ch.call("Ps", "Lookup", req)
            counts[i] += 1

    ts = [threading.Thread(target=loop, args=(i,)) for i in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    ch.close()
    srv.close()
    return sum(counts) / seconds


def main() -> int:
    try:
        from brpc_tpu import rpc
        if not rpc.native_core_available():
            print(json.dumps({"skipped": "native core unavailable"}))
            return 0
    except Exception as e:  # noqa: BLE001 — bench must degrade, not die
        print(json.dumps({"skipped": f"{type(e).__name__}: {e}"[:200]}))
        return 0
    import numpy as np

    from brpc_tpu import obs, press
    from brpc_tpu.limiter import AutoOptions, ServerLimiter
    from brpc_tpu.ps_remote import PsShardServer

    cap = _calibrate(rpc, PsShardServer)

    scenarios = {
        # comfortably under capacity: every config must hold SLO here
        "steady": press.Scenario(
            name="steady", duration_s=2.5, qps=0.40 * cap, batch=BATCH,
            read_fraction=0.9, seed=SEED),
        # hot-key skew at moderate load (the embedding-traffic reality)
        "zipf_hot": press.Scenario(
            name="zipf_hot", duration_s=2.5, qps=0.45 * cap,
            batch=BATCH, read_fraction=0.9, zipf_s=1.2, seed=SEED),
        # past-capacity spikes: 2x capacity for 0.5s of every 1.25s —
        # each burst leaves ~half a second of backlog, so an unshed
        # server never recovers before the next burst lands
        "burst_overload": press.Scenario(
            name="burst_overload", duration_s=4.0, qps=0.30 * cap,
            batch=BATCH, read_fraction=0.9, burst_qps=2.0 * cap,
            burst_every_s=1.25, burst_len_s=0.5, seed=SEED),
    }

    # fast auto-limiter windows: the bench lives for seconds, not the
    # reference's 50s remeasure epochs (which never fire here)
    auto_opts = AutoOptions(initial_limit=8, min_limit=2,
                            window_us=250_000, min_samples=8,
                            max_samples=100)

    def make_server(limiter_kind: str) -> PsShardServer:
        if limiter_kind == "none":
            return PsShardServer(VOCAB, DIM, 0, 1)
        if limiter_kind == "constant":
            return PsShardServer(VOCAB, DIM, 0, 1, limiter="constant:3")
        lim = ServerLimiter("auto", options=auto_opts,
                            methods=PsShardServer.LIMITED_METHODS,
                            counter_prefix="ps")
        srv = PsShardServer(VOCAB, DIM, 0, 1)
        srv.limiter = lim
        srv.server.set_concurrency_limiter(lim)
        return srv

    configs = [(lk, stamp) for lk in ("none", "constant", "auto")
               for stamp in (False, True)]

    matrix: dict = {}
    for sc_name, sc in scenarios.items():
        ops = press.build_ops(sc, VOCAB)
        row: dict = {"ops": len(ops)}
        for limiter_kind, stamp in configs:
            cfg = limiter_kind + ("+deadline" if stamp else "")
            srv = make_server(limiter_kind)
            shed0 = obs.counter("ps_shed").get_value()
            drop0 = obs.counter("ps_deadline_drops").get_value()
            rep = press.run_press(srv.address, ops, DIM,
                                  deadline_ms=DEADLINE_MS,
                                  stamp_deadline=stamp, collectors=6,
                                  retry_on_limit=2)
            rep["server_shed"] = obs.counter("ps_shed").get_value() - shed0
            rep["server_deadline_drops"] = \
                obs.counter("ps_deadline_drops").get_value() - drop0
            if srv.limiter is not None:
                rep["limiter"] = srv.limiter.snapshot()
            row[cfg] = rep
            srv.close()
            time.sleep(0.25)   # drain abandoned handler work (GIL)
        matrix[sc_name] = row

    # record/replay determinism: the burst trace round-trips exactly
    burst_ops = press.build_ops(scenarios["burst_overload"], VOCAB)
    trace_path = os.path.join(ROOT, "cpp", "build", "press_burst.trace")
    press.save_trace(trace_path, burst_ops, seed=SEED, vocab=VOCAB,
                     dim=DIM)
    _, replayed = press.load_trace(trace_path)
    replay_match = len(replayed) == len(burst_ops) and all(
        a.t_us == b.t_us and a.op == b.op and np.array_equal(a.ids,
                                                             b.ids)
        for a, b in zip(burst_ops, replayed))
    os.remove(trace_path)

    burst = matrix["burst_overload"]
    bare_goodput = max(burst["none"]["goodput_qps"], 0.1)
    best = burst["auto+deadline"]
    goodput_ratio = round(best["goodput_qps"] / bare_goodput, 2)
    steady_avail_ok = all(
        matrix[s]["auto+deadline"]["availability"] >= 0.99
        for s in ("steady", "zipf_hot"))
    # "p99 bounded, no collapse": sojourn is open-loop (measured from
    # the SCHEDULED arrival, so the pacer's own burst catch-up lag is
    # included, deliberately) — successes under the recommended config
    # must stay within 2x the deadline budget, against the unshed
    # config's unbounded queue growth
    p99_bounded = best["p99_ms"] <= DEADLINE_MS * 2.0
    out = {
        "metric": "scenario_slo_matrix",
        "capacity_qps": round(cap, 1),
        "deadline_ms": DEADLINE_MS,
        "scenarios": matrix,
        "replay_match": replay_match,
        "burst_goodput_ratio_auto_deadline_over_bare": goodput_ratio,
        "criteria": {
            "goodput_ratio_ge_1p5": goodput_ratio >= 1.5,
            "steady_availability_ge_0p99": steady_avail_ok,
            "burst_p99_bounded": p99_bounded,
            "replay_match": replay_match,
        },
    }
    out["ok"] = all(out["criteria"].values())
    with open(os.path.join(ROOT, "BENCH_scenarios.json"), "w",
              encoding="utf-8") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
