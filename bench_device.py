#!/usr/bin/env python3
"""Device-tier bench: the TPU north-star numbers (BASELINE.md:19-22).

Run BY bench.py in a deadline-guarded subprocess (a wedged tunnel blocks
device init forever — the parent enforces the deadline, this child just
measures). Prints ONE JSON object:
  h2d_gbps / d2h_gbps   — zero-copy staging through the registered block
                          pool (cpp/device/pjrt_device.cc), the RDMA-verbs
                          analog path;
  ps_lookup_qps         — device-resident PS shard: embedding rows served
                          from HBM via compiled gather;
  step_time_ms / achieved_tflops / mxu_utilization
                        — single-chip compiled train step, sized to be
                          matmul-bound (hidden 2048, seq 1024 — a tiny
                          config is overhead-bound by construction and
                          reports a meaningless MFU). Utilization is
                          against the v5e bf16 peak of 197 TFLOP/s, the
                          published figure for the chip this tunnel fronts.

Modes (--mode):
  real  — the axon tunnel's real chip (default).
  sim   — no chip: staging/PS against the in-repo fake N-device PJRT
          plugin (cpp/device/fake_pjrt_plugin.cc) and the train step on
          host CPU. Clearly labeled — these numbers exercise the path
          (handle lifecycle, DMA pool, compiled gather) every round so it
          cannot silently rot, but say nothing about TPU speed.
"""

import argparse
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.abspath(__file__))


def _fake_plugin_path():
    for d in ("cpp/build", "build"):
        p = os.path.join(ROOT, d, "libbrt_fake_pjrt.so")
        if os.path.exists(p):
            return p
    return None


def bench_staging(dev, out):
    mb = 64
    blob = b"x" * (mb << 20)
    # Warm-up (first transfer sets up the pool).
    h = dev.stage(blob)
    dev.fetch(h)
    dev.release(h)
    reps = 5
    t0 = time.monotonic()
    handles = []
    for _ in range(reps):
        handles.append(dev.stage(blob))
    t1 = time.monotonic()
    for h in handles:
        got = dev.fetch(h)
        assert len(got) == len(blob)
        dev.release(h)
    t2 = time.monotonic()
    out["h2d_gbps"] = round(reps * mb / 1024 / (t1 - t0), 2)
    out["d2h_gbps"] = round(reps * mb / 1024 / (t2 - t1), 2)


def bench_ps(dev, out):
    import numpy as np

    from brpc_tpu.ps_remote import DevicePsShardServer, RemoteEmbedding

    vocab, dim = 65536, 128
    s = DevicePsShardServer(vocab, dim, 0, 1, lr=0.1, device_client=dev)
    emb = RemoteEmbedding([s.address], vocab, dim, timeout_ms=120000)
    ids = np.arange(256, dtype=np.int64) * 13 % vocab
    emb.lookup(ids)  # warm (compiles the gather)
    n = 0
    t0 = time.monotonic()
    while time.monotonic() - t0 < 3.0:
        emb.lookup(ids)
        n += 1
    dt = time.monotonic() - t0
    out["ps_lookup_qps"] = round(n / dt, 1)
    out["ps_rows_per_s"] = round(n * len(ids) / dt, 0)
    emb.close()
    s.close()


def bench_step(out, sim: bool):
    import jax
    import jax.numpy as jnp
    import optax

    from brpc_tpu.models import llama
    from brpc_tpu.parallel import make_mesh, shard_batch, shard_params

    if sim:
        # Host CPU: keep the measured path identical but the shapes small
        # enough that 10 steps finish inside the parent deadline.
        cfg = llama.LlamaConfig(
            vocab_size=2048, hidden=256, n_layers=2, n_heads=4,
            n_kv_heads=2, head_dim=64, intermediate=1024)
        batch, seq, reps = 4, 256, 10
    else:
        # Matmul-bound by construction: ~570M params, 8K tokens/step →
        # ~28 TFLOP/step, far past the regime where dispatch overhead or
        # HBM-bound embedding lookups can dominate the timing.
        cfg = llama.LlamaConfig(
            vocab_size=16384, hidden=2048, n_layers=8, n_heads=16,
            n_kv_heads=8, head_dim=128, intermediate=8192)
        batch, seq, reps = 8, 1024, 10
    mesh = make_mesh({}, devices=jax.devices()[:1])
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    params = shard_params(params, llama.param_specs(cfg), mesh)
    optimizer = optax.adamw(1e-3)
    opt_state = optimizer.init(params)
    tokens = shard_batch(
        jnp.zeros((batch, seq), jnp.int32), llama.batch_specs(), mesh)
    step = jax.jit(llama.make_train_step(cfg, optimizer, None))
    with mesh:
        params, opt_state, loss = step(params, opt_state, tokens)  # compile
        jax.block_until_ready(loss)
        t0 = time.monotonic()
        for _ in range(reps):
            params, opt_state, loss = step(params, opt_state, tokens)
        jax.block_until_ready(loss)
        dt = (time.monotonic() - t0) / reps
    nparams = sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
    # Training step ≈ 6 * params * tokens FLOPs (fwd 2x + bwd 4x).
    flops = 6.0 * nparams * batch * seq
    out["step_platform"] = jax.devices()[0].platform
    out["step_time_ms"] = round(dt * 1000, 2)
    out["model_params"] = nparams
    out["achieved_tflops"] = round(flops / dt / 1e12, 3)
    # MFU is only meaningful against a known accelerator peak.
    out["mxu_utilization"] = (
        None if sim else round(flops / dt / 197e12, 4))
    out["loss"] = round(float(loss), 4)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=("real", "sim"), default="real")
    args = ap.parse_args()
    sim = args.mode == "sim"
    if sim:
        # The axon sitecustomize forces platform axon; the CPU override
        # must land before any backend initialises (tests/conftest.py
        # does the same dance).
        os.environ.setdefault("XLA_FLAGS", "")
        import jax

        jax.config.update("jax_platforms", "cpu")

    out = {"mode": args.mode}
    try:
        from brpc_tpu import rpc

        plugin = _fake_plugin_path() if sim else None
        if sim and plugin is None:
            raise RuntimeError("libbrt_fake_pjrt.so not built")
        dev = rpc.DeviceClient(plugin_path=plugin)
        out["device_count"] = dev.device_count
        bench_staging(dev, out)
        bench_ps(dev, out)
        dev.close()
    except Exception as e:  # noqa: BLE001
        out["staging_error"] = f"{type(e).__name__}: {e}"[:200]
    try:
        bench_step(out, sim)
    except Exception as e:  # noqa: BLE001
        out["step_error"] = f"{type(e).__name__}: {e}"[:200]
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
