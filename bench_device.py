#!/usr/bin/env python3
"""Device-tier bench: the TPU north-star numbers (BASELINE.md:19-22).

Run BY bench.py in a deadline-guarded subprocess (a wedged tunnel blocks
device init forever — the parent enforces the deadline, this child just
measures). Prints ONE JSON object:
  h2d_gbps / d2h_gbps   — zero-copy staging through the registered block
                          pool (cpp/device/pjrt_device.cc), the RDMA-verbs
                          analog path;
  ps_lookup_qps         — device-resident PS shard: embedding rows served
                          from HBM via compiled gather;
  step_time_ms / achieved_tflops / mxu_utilization
                        — single-chip compiled train step on the tiny
                          Llama config (utilization against the v5e bf16
                          peak of 197 TFLOP/s, the published figure for
                          the chip this tunnel fronts).
"""

import json
import sys
import time


def bench_staging(dev, out):
    from brpc_tpu import rpc  # noqa: F401

    mb = 64
    blob = b"x" * (mb << 20)
    # Warm-up (first transfer sets up the pool).
    h = dev.stage(blob)
    dev.fetch(h)
    dev.release(h)
    reps = 5
    t0 = time.monotonic()
    handles = []
    for _ in range(reps):
        handles.append(dev.stage(blob))
    t1 = time.monotonic()
    for h in handles:
        got = dev.fetch(h)
        assert len(got) == len(blob)
        dev.release(h)
    t2 = time.monotonic()
    out["h2d_gbps"] = round(reps * mb / 1024 / (t1 - t0), 2)
    out["d2h_gbps"] = round(reps * mb / 1024 / (t2 - t1), 2)


def bench_ps(dev, out):
    import numpy as np

    from brpc_tpu.ps_remote import DevicePsShardServer, RemoteEmbedding

    vocab, dim = 65536, 128
    s = DevicePsShardServer(vocab, dim, 0, 1, lr=0.1, device_client=dev)
    emb = RemoteEmbedding([s.address], vocab, dim, timeout_ms=120000)
    ids = np.arange(256, dtype=np.int64) * 13 % vocab
    emb.lookup(ids)  # warm (compiles the gather)
    n = 0
    t0 = time.monotonic()
    while time.monotonic() - t0 < 3.0:
        emb.lookup(ids)
        n += 1
    dt = time.monotonic() - t0
    out["ps_lookup_qps"] = round(n / dt, 1)
    out["ps_rows_per_s"] = round(n * len(ids) / dt, 0)
    emb.close()
    s.close()


def bench_step(out):
    import jax
    import jax.numpy as jnp
    import optax

    from brpc_tpu.models import llama
    from brpc_tpu.parallel import make_mesh, shard_batch, shard_params

    cfg = llama.LlamaConfig.tiny(vocab_size=2048)
    mesh = make_mesh({}, devices=jax.devices()[:1])
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    params = shard_params(params, llama.param_specs(cfg), mesh)
    optimizer = optax.adamw(1e-3)
    opt_state = optimizer.init(params)
    batch, seq = 8, 256
    tokens = shard_batch(
        jnp.zeros((batch, seq), jnp.int32), llama.batch_specs(), mesh)
    step = jax.jit(llama.make_train_step(cfg, optimizer, None))
    with mesh:
        params, opt_state, loss = step(params, opt_state, tokens)  # compile
        jax.block_until_ready(loss)
        reps = 20
        t0 = time.monotonic()
        for _ in range(reps):
            params, opt_state, loss = step(params, opt_state, tokens)
        jax.block_until_ready(loss)
        dt = (time.monotonic() - t0) / reps
    nparams = sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
    # Training step ≈ 6 * params * tokens FLOPs (fwd 2x + bwd 4x).
    flops = 6.0 * nparams * batch * seq
    out["step_time_ms"] = round(dt * 1000, 2)
    out["model_params"] = nparams
    out["achieved_tflops"] = round(flops / dt / 1e12, 3)
    out["mxu_utilization"] = round(flops / dt / 197e12, 4)
    out["loss"] = round(float(loss), 4)


def main() -> int:
    out = {}
    try:
        from brpc_tpu import rpc

        dev = rpc.DeviceClient()
        out["device_count"] = dev.device_count
        bench_staging(dev, out)
        bench_ps(dev, out)
        dev.close()
    except Exception as e:  # noqa: BLE001
        out["staging_error"] = f"{type(e).__name__}: {e}"[:200]
    try:
        bench_step(out)
    except Exception as e:  # noqa: BLE001
        out["step_error"] = f"{type(e).__name__}: {e}"[:200]
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
