#!/usr/bin/env python3
"""Device-tier bench: the TPU north-star numbers (BASELINE.md:19-22).

Run BY bench.py in a deadline-guarded subprocess (a wedged tunnel blocks
device init forever — the parent enforces the deadline, this child just
measures). Prints ONE JSON object:
  h2d_gbps / d2h_gbps   — zero-copy staging through the registered block
                          pool (cpp/device/pjrt_device.cc), the RDMA-verbs
                          analog path;
  ps_lookup_qps         — device-resident PS shard: embedding rows served
                          from HBM via compiled gather;
  step_time_ms / achieved_tflops / mxu_utilization
                        — single-chip compiled train step, sized to be
                          matmul-bound (hidden 2048, seq 1024 — a tiny
                          config is overhead-bound by construction and
                          reports a meaningless MFU). Utilization is
                          against the v5e bf16 peak of 197 TFLOP/s, the
                          published figure for the chip this tunnel fronts.

Modes (--mode):
  real  — the axon tunnel's real chip (default).
  sim   — no chip: staging/PS against the in-repo fake N-device PJRT
          plugin (cpp/device/fake_pjrt_plugin.cc) and the train step on
          host CPU. Clearly labeled — these numbers exercise the path
          (handle lifecycle, DMA pool, compiled gather) every round so it
          cannot silently rot, but say nothing about TPU speed.

Blocks (--block):
  baseline — the north-star numbers above (default).
  parity   — the ISSUE 20 device-tier parity scenario: an HBM-serving
             replicated pair under sustained load through kill-primary →
             failover → revival → failback, then a LIVE 1→2 device
             split — availability over EVERY op and the exact
             zero-lost-acked-update ledger at the end.  Refreshes
             BENCH_device.json; degrades to {"skipped": ...} without the
             native core / fake plugin.  The scenario proves fabric
             control flow, not chip speed — bench.py runs it in sim mode
             so a wedged tunnel cannot eat its deadline.
"""

import argparse
import json
import os
import struct
import sys
import time

ROOT = os.path.dirname(os.path.abspath(__file__))


def _fake_plugin_path():
    for d in ("cpp/build", "build"):
        p = os.path.join(ROOT, d, "libbrt_fake_pjrt.so")
        if os.path.exists(p):
            return p
    return None


def bench_staging(dev, out):
    mb = 64
    blob = b"x" * (mb << 20)
    # Warm-up (first transfer sets up the pool).
    h = dev.stage(blob)
    dev.fetch(h)
    dev.release(h)
    reps = 5
    t0 = time.monotonic()
    handles = []
    for _ in range(reps):
        handles.append(dev.stage(blob))
    t1 = time.monotonic()
    for h in handles:
        got = dev.fetch(h)
        assert len(got) == len(blob)
        dev.release(h)
    t2 = time.monotonic()
    out["h2d_gbps"] = round(reps * mb / 1024 / (t1 - t0), 2)
    out["d2h_gbps"] = round(reps * mb / 1024 / (t2 - t1), 2)


def bench_ps(dev, out):
    import numpy as np

    from brpc_tpu.ps_remote import DevicePsShardServer, RemoteEmbedding

    vocab, dim = 65536, 128
    s = DevicePsShardServer(vocab, dim, 0, 1, lr=0.1, device_client=dev)
    emb = RemoteEmbedding([s.address], vocab, dim, timeout_ms=120000)
    ids = np.arange(256, dtype=np.int64) * 13 % vocab
    emb.lookup(ids)  # warm (compiles the gather)
    n = 0
    t0 = time.monotonic()
    while time.monotonic() - t0 < 3.0:
        emb.lookup(ids)
        n += 1
    dt = time.monotonic() - t0
    out["ps_lookup_qps"] = round(n / dt, 1)
    out["ps_rows_per_s"] = round(n * len(ids) / dt, 0)
    emb.close()
    s.close()


def bench_step(out, sim: bool):
    import jax
    import jax.numpy as jnp
    import optax

    from brpc_tpu.models import llama
    from brpc_tpu.parallel import make_mesh, shard_batch, shard_params

    if sim:
        # Host CPU: keep the measured path identical but the shapes small
        # enough that 10 steps finish inside the parent deadline.
        cfg = llama.LlamaConfig(
            vocab_size=2048, hidden=256, n_layers=2, n_heads=4,
            n_kv_heads=2, head_dim=64, intermediate=1024)
        batch, seq, reps = 4, 256, 10
    else:
        # Matmul-bound by construction: ~570M params, 8K tokens/step →
        # ~28 TFLOP/step, far past the regime where dispatch overhead or
        # HBM-bound embedding lookups can dominate the timing.
        cfg = llama.LlamaConfig(
            vocab_size=16384, hidden=2048, n_layers=8, n_heads=16,
            n_kv_heads=8, head_dim=128, intermediate=8192)
        batch, seq, reps = 8, 1024, 10
    mesh = make_mesh({}, devices=jax.devices()[:1])
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    params = shard_params(params, llama.param_specs(cfg), mesh)
    optimizer = optax.adamw(1e-3)
    opt_state = optimizer.init(params)
    tokens = shard_batch(
        jnp.zeros((batch, seq), jnp.int32), llama.batch_specs(), mesh)
    step = jax.jit(llama.make_train_step(cfg, optimizer, None))
    with mesh:
        params, opt_state, loss = step(params, opt_state, tokens)  # compile
        jax.block_until_ready(loss)
        t0 = time.monotonic()
        for _ in range(reps):
            params, opt_state, loss = step(params, opt_state, tokens)
        jax.block_until_ready(loss)
        dt = (time.monotonic() - t0) / reps
    nparams = sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
    # Training step ≈ 6 * params * tokens FLOPs (fwd 2x + bwd 4x).
    flops = 6.0 * nparams * batch * seq
    out["step_platform"] = jax.devices()[0].platform
    out["step_time_ms"] = round(dt * 1000, 2)
    out["model_params"] = nparams
    out["achieved_tflops"] = round(flops / dt / 1e12, 3)
    # MFU is only meaningful against a known accelerator peak.
    out["mxu_utilization"] = (
        None if sim else round(flops / dt / 197e12, 4))
    out["loss"] = round(float(loss), 4)


def parity_main(sim: bool) -> int:  # noqa: C901 — one scenario, inline
    """Device-tier parity scenario (ISSUE 20).  One replicated device
    pair (primary serving from HBM, backup on its host mirror) under
    sustained read+write load:

      kill primary → client-driven failover (backup stages its mirror
      into HBM) → revival (the corpse is fenced back to a host-mirror
      backup) → FAILBACK (out-of-band re-promotion stages the original
      again) → a LIVE 1→2 device split (generation-pinned device
      snapshots through unchanged MigrateSync framing) → cutover.

    Measures availability over every op and closes with the exact
    zero-lost-acked-update ledger: the destination DEVICE tables must
    equal the seed minus exactly one GRAD per acked batch, replayed in
    the servers' own float order."""
    # 7 in-process servers with quorum-ack handlers share the process-
    # global fiber pool; the 1-core default of 4 workers starves into a
    # timeout spiral (same sizing note as bench_churn.py).
    os.environ.setdefault("BRT_WORKERS", "16")
    try:
        from brpc_tpu import rpc
        if not rpc.native_core_available():
            print(json.dumps({"skipped": "native core unavailable"}))
            return 0
    except Exception as e:  # noqa: BLE001 — bench must degrade, not die
        print(json.dumps({"skipped": f"{type(e).__name__}: {e}"[:200]}))
        return 0
    import threading

    import numpy as np

    from brpc_tpu import fault, obs, resilience
    from brpc_tpu.naming import (NamingClient, PartitionScheme,
                                 ReplicaSet, publish_scheme)
    from brpc_tpu.ps_remote import DevicePsShardServer, RemoteEmbedding
    from brpc_tpu.reshard import MigrationDriver

    obs.set_enabled(True)
    t0_bench = time.monotonic()
    plugin = _fake_plugin_path() if sim else None
    if sim and plugin is None:
        print(json.dumps({"skipped": "libbrt_fake_pjrt.so not built"}))
        return 0
    try:
        dev = rpc.DeviceClient(plugin_path=plugin)
    except Exception as e:  # noqa: BLE001
        print(json.dumps({"skipped": f"{type(e).__name__}: {e}"[:200]}))
        return 0

    VOCAB, DIM, GRAD, BATCH = 256, 8, 2.0 ** -6, 32
    out = {"mode": "sim" if sim else "real", "vocab": VOCAB, "dim": DIM}
    a = DevicePsShardServer(VOCAB, DIM, 0, 1, lr=1.0, seed=7,
                            device_client=dev)
    b = DevicePsShardServer(VOCAB, DIM, 0, 1, lr=1.0, seed=7,
                            device_client=dev)
    seed_table = a.table.copy()          # identical on both (same seed)
    rs = ReplicaSet((a.address, b.address), primary=0)
    a.configure_replication(rs, 0)
    b.configure_replication(rs, 1)
    sc0 = PartitionScheme(0, (rs,))
    # Registry-published schemes + a watching client: the cutover is
    # self-announcing (a writer racing it refreshes on ESCHEMEMOVED and
    # re-splits exactly-once instead of failing an op).
    reg_server = rpc.Server()
    reg_server.add_naming_registry()
    reg_addr = f"127.0.0.1:{reg_server.start('127.0.0.1:0')}"
    nc = NamingClient(reg_addr)
    publish_scheme(nc, "ps", sc0)
    emb = RemoteEmbedding.from_registry(
        reg_addr, "ps", VOCAB, DIM, timeout_ms=10000, watch=True,
        retry=resilience.RetryPolicy(
            max_attempts=6,
            backoff=resilience.Backoff(base_ms=1, max_ms=20),
            attempt_timeout_ms=1000),
        breakers=resilience.BreakerRegistry(
            resilience.BreakerOptions(short_window=4, min_samples=2,
                                      min_isolation_ms=50),
            redirect=True),
        health_check=True, health_interval_ms=20)

    perm = np.random.default_rng(7).permutation(VOCAB).astype(np.int32)
    batches = [np.sort(perm[i:i + BATCH]) for i in
               range(0, VOCAB, BATCH)]
    grads = np.full((BATCH, DIM), GRAD, np.float32)
    read_ids = np.arange(VOCAB, dtype=np.int32)
    stop = threading.Event()
    mu = threading.Lock()
    ok_ops = [0]
    failed_ops = []
    acked = []                          # batch index per acked write

    def _reader():
        while not stop.is_set():
            try:
                emb.lookup(read_ids)
                with mu:
                    ok_ops[0] += 1
            except Exception as e:  # noqa: BLE001 — the verdict
                with mu:
                    failed_ops.append("read: " + repr(e)[:120])
            time.sleep(0.002)

    def _writer():
        i = 0
        while not stop.is_set():
            bi = i % len(batches)
            try:
                emb.apply_gradients(batches[bi], grads)
                with mu:
                    ok_ops[0] += 1
                    acked.append(bi)
            except Exception as e:  # noqa: BLE001 — taints the ledger
                with mu:
                    failed_ops.append("write: " + repr(e)[:120])
            i += 1
            time.sleep(0.002)

    def _wait(pred, deadline_s):
        t_end = time.monotonic() + deadline_s
        while time.monotonic() < t_end:
            if pred():
                return True
            time.sleep(0.02)
        return pred()

    new = []
    drv = None
    try:
        emb.apply_gradients(batches[0], grads)   # warm streams+replicas
        acked.append(0)
        ok_ops[0] += 1
        threads = [threading.Thread(target=_reader),
                   threading.Thread(target=_writer)]
        for t in threads:
            t.start()
        time.sleep(0.5)                          # steady state

        # -- kill-primary -> failover ---------------------------------
        t_kill = time.monotonic()
        fault.install(fault.FaultPlan(fault.kill_rules(a.address),
                                      seed=3))
        rpc.debug_fail_connections(a.address)    # sever live streams too
        out["failover"] = _wait(
            lambda: b.is_primary and b._dev_serving, 15.0)
        out["failover_ms"] = round((time.monotonic() - t_kill) * 1e3, 1)
        time.sleep(0.5)                          # load on the new primary

        # -- revival: the corpse is fenced back to a backup ------------
        fault.clear()
        out["revived"] = _wait(lambda: not emb._isolated(a.address), 5.0)
        out["fenced_down"] = _wait(
            lambda: not a.is_primary and not a._dev_serving, 10.0)

        # -- failback: re-promote the original (the rebalancer's move) -
        # Freshness gate first (rebalance.py:_observe): sample the
        # USURPER's gen before the declared primary's — promoting a
        # backup that hasn't acked everything the usurper holds would
        # strand an acked update (the client's 2008 guard screams).
        def _caught_up():
            gen_b = b._install_gen          # usurper first
            return not a.is_primary and a._install_gen >= gen_b

        out["failback_gate"] = _wait(_caught_up, 10.0)
        ch = rpc.Channel(a.address, timeout_ms=5000)
        try:
            ch.call("Ps", "Promote",
                    struct.pack("<q", max(a.epoch, b.epoch) + 1))
        finally:
            ch.close()
        out["failback"] = _wait(
            lambda: a.is_primary and a._dev_serving, 15.0)
        time.sleep(0.5)                          # load after failback

        # -- live 1->2 device split under the same load ---------------
        new = [DevicePsShardServer(VOCAB, DIM, s, 2, lr=1.0, seed=7,
                                   importing=True, scheme_version=1,
                                   device_client=dev)
               for s in range(2)]
        sc1 = PartitionScheme(1, tuple(ReplicaSet.of(sv.address)
                                       for sv in new))
        t_split = time.monotonic()
        drv = MigrationDriver(sc0, sc1, VOCAB, registry_addr=reg_addr,
                              cluster="ps")
        drv.start()
        drv.wait_caught_up(deadline_s=60)
        drv.cutover()                            # publishes sc1 + drain
        out["split_ms"] = round((time.monotonic() - t_split) * 1e3, 1)
        out["split_serving"] = all(sv._dev_serving for sv in new)
        time.sleep(0.5)                          # load on the new tier

        stop.set()
        for t in threads:
            t.join(30)
        for sv in new:                           # drain in-flight applies
            ch = rpc.Channel(sv.address, timeout_ms=5000)
            try:
                ch.call("Ps", "Flush", b"")
            finally:
                ch.close()

        # -- exact ledger ---------------------------------------------
        # Replay the servers' own float order: every acked batch was ONE
        # float32 in-place subtract of lr*GRAD (lr=1.0, GRAD=2^-6 — the
        # device scatter's f32 multiply is exact for these values).
        expect = seed_table.copy()
        for bi in acked:
            expect[batches[bi]] -= np.float32(GRAD)
        final = np.concatenate([sv.table for sv in new])
        tainted = [f for f in failed_ops if f.startswith("write")]
        out["ledger_exact"] = bool(np.array_equal(final, expect))
        out["ledger_tainted"] = bool(tainted)
        total = ok_ops[0] + len(failed_ops)
        out["ops"] = total
        out["acked_writes"] = len(acked)
        out["failed_ops"] = failed_ops[:20]
        out["availability"] = round(ok_ops[0] / max(1, total), 6)
        for c in ("ps_client_failovers", "ps_device_promote_stages",
                  "ps_device_mirror_downs", "ps_device_wasted_launches",
                  "ps_migrate_hydrates"):
            out[c] = int(obs.counter(c).get_value())
        out["criteria"] = {
            "availability_ge_0p999": out["availability"] >= 0.999,
            "failover": bool(out["failover"]),
            "revival_and_fence": bool(out["revived"]
                                      and out["fenced_down"]),
            "failback": bool(out["failback"]),
            "live_device_split": bool(out["split_serving"]),
            "zero_lost_acked_updates": out["ledger_exact"],
        }
        out["ok"] = all(out["criteria"].values())
        out["wall_s"] = round(time.monotonic() - t0_bench, 2)
    except Exception as e:  # noqa: BLE001 — report, don't die
        out["error"] = f"{type(e).__name__}: {e}"[:300]
        out["ok"] = False
    finally:
        stop.set()
        fault.clear()
        if drv is not None:
            drv.close()
        emb.close()
        nc.close()
        for sv in [a, b] + new:
            try:
                sv.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        reg_server.close()
        dev.close()

    with open(os.path.join(ROOT, "BENCH_device.json"), "w",
              encoding="utf-8") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(json.dumps(out))
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=("real", "sim"), default="real")
    ap.add_argument("--block", choices=("baseline", "parity"),
                    default="baseline")
    args = ap.parse_args()
    sim = args.mode == "sim"
    if args.block == "parity":
        return parity_main(sim)
    if sim:
        # The axon sitecustomize forces platform axon; the CPU override
        # must land before any backend initialises (tests/conftest.py
        # does the same dance).
        os.environ.setdefault("XLA_FLAGS", "")
        import jax

        jax.config.update("jax_platforms", "cpu")

    out = {"mode": args.mode}
    try:
        from brpc_tpu import rpc

        plugin = _fake_plugin_path() if sim else None
        if sim and plugin is None:
            raise RuntimeError("libbrt_fake_pjrt.so not built")
        dev = rpc.DeviceClient(plugin_path=plugin)
        out["device_count"] = dev.device_count
        bench_staging(dev, out)
        bench_ps(dev, out)
        dev.close()
    except Exception as e:  # noqa: BLE001
        out["staging_error"] = f"{type(e).__name__}: {e}"[:200]
    try:
        bench_step(out, sim)
    except Exception as e:  # noqa: BLE001
        out["step_error"] = f"{type(e).__name__}: {e}"[:200]
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
