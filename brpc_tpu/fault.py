"""Deterministic fault injection for the RPC/PS fabric.

Every fault-tolerance behavior in :mod:`brpc_tpu.resilience` is proven
against INJECTED failures, not real network flakiness: a seeded
:class:`FaultPlan` decides — per (side, service, method, endpoint) and
per call sequence number — whether a call errors, is delayed, or is
dropped.  Decisions are a pure function of ``(seed, rule index, hit
counter)``, so the same plan replays the same failure schedule every
run (the fault-injection analog of :class:`resilience.Backoff`'s
deterministic jitter).

Hook points (both no-ops when no plan is installed — one module-global
``is None`` check):

- **server trampoline** (``rpc.Server.add_service`` /
  ``add_async_service``): :func:`server_intercept` runs before the user
  handler — an ``error`` rule raises (the trampoline's normal error path
  responds with the injected code), a ``delay`` rule sleeps on the fiber
  worker (exactly what a slow shard does to the fabric).
- **client call path** (``rpc.Channel.call`` / ``call_async``):
  :func:`client_intercept` — ``error`` raises before the wire,
  ``delay`` stalls the caller, ``drop`` burns the call's timeout budget
  and raises ERPCTIMEDOUT (a lost request seen from the client).
- **native pre-dispatch hook** (``brt_set_drop_hook``, installed by
  :func:`install` when a plan carries SERVER-side ``drop`` rules):
  :func:`server_drop_intercept` runs inside the native request path
  after the meta is parsed but before dispatch — a firing rule discards
  the request silently, NO response is ever written, and the client
  exercises its REAL timeout machinery (native deadline timer, retry
  budget, hedging), unlike the client-side ``drop`` which simulates the
  cost without touching the wire.  Needs the native core.

Rules (programmatic or ``BRPC_TPU_FAULTS`` env, JSON list)::

    [{"side": "server", "service": "Ps", "method": "Lookup",
      "action": "delay", "delay_ms": 40, "probability": 0.3},
     {"side": "client", "endpoint": "127.0.0.1:7001",
      "action": "error", "error_code": 1009, "max_hits": 2}]

Match keys (``service``/``method``/``endpoint``) are exact strings;
omitted keys match anything.  ``probability`` is evaluated by the seeded
hash per hit; ``after`` skips the first N matching calls and
``max_hits`` stops injecting after N injections (both make "fails the
first attempt, then recovers" schedules trivial).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Callable, Dict, List, Optional, Tuple

from brpc_tpu import obs
from brpc_tpu.analysis.race import checked_lock
from brpc_tpu.resilience import _hash01, sleep_ms

__all__ = [
    "FaultRule", "FaultPlan", "install", "install_from_env", "clear",
    "current", "active", "server_intercept", "server_drop_intercept",
    "client_intercept", "kill_rules", "partition_rules", "FAULTS_ENV",
]

FAULTS_ENV = "BRPC_TPU_FAULTS"

_ACTIONS = ("error", "delay", "drop")
_SIDES = ("server", "client")


@dataclasses.dataclass
class FaultRule:
    """One injection rule.  ``action``: ``error`` (respond/raise
    ``error_code``/``error_text``), ``delay`` (sleep ``delay_ms`` then
    proceed), ``drop`` — client-side: consume the call's timeout and
    raise ERPCTIMEDOUT; server-side: the native pre-dispatch hook
    discards the parsed request silently (no response — the client's
    real timeout machinery runs)."""

    action: str
    side: str = "server"
    service: Optional[str] = None
    method: Optional[str] = None
    endpoint: Optional[str] = None
    error_code: int = 2001
    error_text: str = "injected fault"
    delay_ms: float = 0.0
    probability: float = 1.0
    #: skip the first N matching calls before injecting at all
    after: int = 0
    #: stop injecting after N injections (None = forever)
    max_hits: Optional[int] = None

    def __post_init__(self) -> None:
        if self.action not in _ACTIONS:
            raise ValueError(f"unknown fault action {self.action!r}; "
                             f"valid: {', '.join(_ACTIONS)}")
        if self.side not in _SIDES:
            raise ValueError(f"unknown fault side {self.side!r}; "
                             f"valid: {', '.join(_SIDES)}")
        # Server-side drop rules fire in the NATIVE pre-dispatch hook
        # (the session never exists, so "respond exactly once" is moot);
        # client-side drop burns the caller's timeout budget locally.

    def matches(self, side: str, service: str, method: str,
                endpoint: Optional[str]) -> bool:
        if self.side != side:
            return False
        if self.service is not None and self.service != service:
            return False
        if self.method is not None and self.method != method:
            return False
        if self.endpoint is not None and self.endpoint != endpoint:
            return False
        return True


def kill_rules(*endpoints: str, code: int = 1009,
               text: str = "injected kill",
               probability: float = 1.0,
               max_hits: Optional[int] = None) -> "List[FaultRule]":
    """Rules that make ``endpoints`` DEAD: every client call to the
    address fails before the wire and every request still reaching the
    server (a peer's replication Sync, a prober's health check) errors
    — the deterministic kill-primary / kill-replica lever for the
    replication tests and benches.  The default code (EFAILEDSOCKET
    1009) is retriable and breaker-feeding, so the fabric's failover
    machinery — redirect, promotion, revival once the rules clear —
    is what gets exercised, not a special-cased error path."""
    rules: List[FaultRule] = []
    for ep in endpoints:
        for side in _SIDES:
            rules.append(FaultRule(
                action="error", side=side, endpoint=ep,
                error_code=code, error_text=f"{text} ({ep})",
                probability=probability, max_hits=max_hits))
    return rules


#: the state-propagation control/data plane between servers: replication
#: sync + delta streams and migration sync + delta streams.  Severing
#: exactly these (and nothing else) is how tests create a server that
#: SERVES clients but cannot receive peer state — the control-plane
#: partition behind stale-primary and mid-migration failure scenarios.
PROPAGATION_METHODS = ("Sync", "ReplicaApply", "MigrateSync",
                       "MigrateApply")


def partition_rules(*endpoints: str, code: int = 1009,
                    methods: Tuple[str, ...] = PROPAGATION_METHODS,
                    max_hits: Optional[int] = None) -> "List[FaultRule]":
    """Rules that sever ``endpoints``' replication/migration
    PROPAGATION plane only: Sync/ReplicaApply (replication) and
    MigrateSync/MigrateApply (resharding handoff) fail — on BOTH sides,
    like :func:`kill_rules`, because a server-only rule is silently
    absorbed by the native channel's transparent retry (max_retry
    attempts per call each consume one hit) — while client data
    traffic still flows: the deterministic "partitioned but serving"
    lever (a stale primary that cannot be informed; a migration
    destination the source cannot reach mid-stream).  With the
    client-side rule, ``max_hits`` counts logical peer calls."""
    rules: List[FaultRule] = []
    for ep in endpoints:
        for method in methods:
            for side in _SIDES:
                rules.append(FaultRule(
                    action="error", side=side, service="Ps",
                    method=method, endpoint=ep, error_code=code,
                    error_text=f"injected partition ({ep} {method})",
                    max_hits=max_hits))
    return rules


class FaultPlan:
    """A seeded list of rules plus per-rule hit counters.  ``decide``
    is the only stateful operation (counters advance under a lock);
    everything else is pure, so a plan's schedule is reproducible from
    ``(seed, rules, call order)``."""

    def __init__(self, rules: List[FaultRule], seed: int = 0):
        self.rules = list(rules)
        self.seed = seed
        self._mu = checked_lock("fault.plan")
        self._seen = [0] * len(self.rules)   # matching calls per rule
        self._hits = [0] * len(self.rules)   # injections per rule

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        data = json.loads(text)
        if isinstance(data, dict):
            seed = int(data.get("seed", 0))
            rules = data.get("rules", [])
        else:
            seed, rules = 0, data
        return cls([FaultRule(**r) for r in rules], seed=seed)

    def to_json(self) -> str:
        return json.dumps({
            "seed": self.seed,
            "rules": [dataclasses.asdict(r) for r in self.rules],
        })

    def has_server_drop_rules(self) -> bool:
        """True when any rule needs the native pre-dispatch drop hook."""
        return any(r.side == "server" and r.action == "drop"
                   for r in self.rules)

    def decide(self, side: str, service: str, method: str,
               endpoint: Optional[str] = None,
               actions: Optional[Tuple[str, ...]] = None
               ) -> Optional[FaultRule]:
        """The first rule that matches AND fires for this call (counters
        advance for every matching rule either way).  ``actions`` filters
        which rules this decision point CONSIDERS — rules outside it are
        skipped entirely, counters untouched: server-side ``drop`` rules
        are decided by the native pre-dispatch hook (which sees every
        request), ``error``/``delay`` by the trampoline (which never sees
        a dropped request), and the two decision points must not consume
        each other's hit sequence."""
        fired: Optional[FaultRule] = None
        with self._mu:
            for i, rule in enumerate(self.rules):
                if actions is not None and rule.action not in actions:
                    continue
                if not rule.matches(side, service, method, endpoint):
                    continue
                seq = self._seen[i]
                self._seen[i] += 1
                if fired is not None:
                    continue  # counters still advance on later rules
                if seq < rule.after:
                    continue
                if rule.max_hits is not None and \
                        self._hits[i] >= rule.max_hits:
                    continue
                if rule.probability < 1.0 and _hash01(
                        self.seed * 1000003 + i, seq) >= rule.probability:
                    continue
                self._hits[i] += 1
                fired = rule
        return fired

    def hits(self) -> List[int]:
        with self._mu:
            return list(self._hits)


# ---------------------------------------------------------------------------
# process-global plan + the two hook points
# ---------------------------------------------------------------------------

_plan: Optional[FaultPlan] = None


def active() -> bool:
    """Fast gate for the hot hook sites (one global read)."""
    return _plan is not None


def install(plan: Optional[FaultPlan]) -> None:
    global _plan
    if plan is not None and plan.has_server_drop_rules():
        # Server-side drop needs the native pre-dispatch hook (raises
        # NativeCoreUnavailable without the toolchain/.so).  The hook
        # stays installed after clear() — it gates on active() and costs
        # one atomic load when no plan is live.
        from brpc_tpu import rpc
        rpc.install_drop_hook()
    _plan = plan


def clear() -> None:
    install(None)


def current() -> Optional[FaultPlan]:
    return _plan


def install_from_env(env: Optional[Dict[str, str]] = None) -> bool:
    """Install a plan from ``BRPC_TPU_FAULTS`` (inline JSON, or
    ``@/path/to/plan.json``).  Returns True when a plan was installed."""
    raw = (env or os.environ).get(FAULTS_ENV, "")
    if not raw:
        return False
    if raw.startswith("@"):
        with open(raw[1:], "r", encoding="utf-8") as f:
            raw = f.read()
    install(FaultPlan.from_json(raw))
    return True


def _injected_error(rule: FaultRule):
    from brpc_tpu.rpc import RpcError  # lazy: rpc imports this module
    return RpcError(rule.error_code, rule.error_text)


def server_intercept(service: str, method: str,
                     endpoint: Optional[str] = None) -> None:
    """Called by the server trampolines before the user handler.  Raises
    to fail the call with the injected code; sleeps for ``delay`` rules
    (on the fiber worker — a faithful slow handler).  ``endpoint`` is the
    server's own listen address, so a plan can make ONE shard of a
    fleet slow or failing."""
    plan = _plan
    if plan is None:
        return
    # drop rules belong to the native pre-dispatch hook: a dropped
    # request never reaches this trampoline, so considering them here
    # would double-consume their hit sequence.
    rule = plan.decide("server", service, method, endpoint,
                       actions=("error", "delay"))
    if rule is None:
        return
    if rule.action == "delay":
        if obs.enabled():
            obs.counter("fault_injected_delays").add(1)
        sleep_ms(rule.delay_ms)
        return
    if obs.enabled():
        obs.counter("fault_injected_errors").add(1)
    raise _injected_error(rule)


def server_drop_intercept(service: str, method: str,
                          endpoint: Optional[str] = None) -> bool:
    """Called by the NATIVE pre-dispatch hook (``brt_set_drop_hook`` →
    ``rpc.install_drop_hook``) for every parsed request.  True = discard
    the request silently (no response; the client's real timeout path
    runs).  Only server-side ``drop`` rules are considered — their hit
    counters advance here, pre-dispatch, where every request is seen."""
    plan = _plan
    if plan is None:
        return False
    rule = plan.decide("server", service, method, endpoint,
                       actions=("drop",))
    if rule is None:
        return False
    if obs.enabled():
        obs.counter("fault_injected_drops").add(1)
    return True


def client_intercept(service: str, method: str, endpoint: str,
                     timeout_ms: Optional[float] = None) -> None:
    """Called by ``Channel.call``/``call_async`` before the native call.
    ``drop`` consumes the effective timeout then raises ERPCTIMEDOUT —
    exactly what a lost request costs the caller."""
    plan = _plan
    if plan is None:
        return
    rule = plan.decide("client", service, method, endpoint)
    if rule is None:
        return
    if rule.action == "delay":
        if obs.enabled():
            obs.counter("fault_injected_delays").add(1)
        sleep_ms(rule.delay_ms)
        return
    if rule.action == "drop":
        if obs.enabled():
            obs.counter("fault_injected_drops").add(1)
        sleep_ms(timeout_ms if timeout_ms is not None else rule.delay_ms)
        from brpc_tpu.rpc import RpcError  # lazy
        raise RpcError(1008, f"injected drop of {service}.{method} "
                             f"to {endpoint}")
    if obs.enabled():
        obs.counter("fault_injected_errors").add(1)
    raise _injected_error(rule)
