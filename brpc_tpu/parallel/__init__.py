from brpc_tpu.parallel.mesh import make_mesh, shard_params, shard_batch  # noqa: F401
from brpc_tpu.parallel.collective_channel import (  # noqa: F401
    CollectiveChannel,
    allreduce_benchmark,
)
from brpc_tpu.parallel.ring import ring_attention, ulysses_attention  # noqa: F401
from brpc_tpu.parallel.pipeline import pipeline_apply  # noqa: F401
