from brpc_tpu.parallel.mesh import make_mesh, shard_params, shard_batch  # noqa: F401
