"""Ring attention — sequence/context parallelism over the ICI ring.

The reference has no sequence parallelism (SURVEY.md §5.8); its scaffolding
for it is the combo-channel fan-out + the streaming pipe.  The TPU-native
realization: shard the sequence over a mesh axis ('sp'), keep Q resident,
and rotate K/V blocks around the ring with ``lax.ppermute`` while
accumulating attention with an online (flash-style) softmax — compute on
block i overlaps the transfer of block i+1, so the ring latency hides
behind the MXU work (jax-ml.github.io/scaling-book recipe; RingAttention,
Liu et al. 2023).

Causal masking across ring steps uses global block positions: ring step s
on device d holds KV block (d - s) mod n; a Q block attends iff
kv_block <= q_block, with the diagonal block applying the triangular mask.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from brpc_tpu._compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def _block_attend(q, k, v, mask):
    """One block pair: returns (unnormalized out, row max, row sumexp).

    q: [B,Tq,Hkv,G,D]  k/v: [B,Tk,Hkv,D]  mask: [Tq,Tk] additive (0/-inf).
    """
    d = q.shape[-1]
    scores = jnp.einsum("bthgd,bshd->bhgts", q, k).astype(jnp.float32)
    scores = scores * (d ** -0.5) + mask[None, None, None]
    m = jnp.max(scores, axis=-1)                        # [B,H,G,Tq]
    # guard fully-masked rows (exp(-inf - -inf))
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(scores - m_safe[..., None])             # [B,H,G,Tq,Ts]
    l = jnp.sum(p, axis=-1)                             # [B,H,G,Tq]
    o = jnp.einsum("bhgts,bshd->bthgd", p.astype(v.dtype), v)
    return o.astype(jnp.float32), m_safe, l


def _merge(o1, m1, l1, o2, m2, l2):
    """Online-softmax merge of two partial attention states."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    # o: [B,T,H,G,D]; m/l: [B,H,G,T] -> broadcast to o layout
    def scale(o, a):
        return o * jnp.transpose(a, (0, 3, 1, 2))[..., None]
    return scale(o1, a1) + scale(o2, a2), m, l1 * a1 + l2 * a2


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = True,
    head_axis: str | None = None,
) -> jax.Array:
    """Sequence-sharded GQA attention.

    q: [B, T, Hq, D], k/v: [B, T, Hkv, D] — T is the GLOBAL sequence,
    sharded over ``axis`` (dim 1). ``head_axis`` optionally keeps the head
    dim sharded (tensor parallelism composes: sp rotates KV while tp splits
    heads). Returns [B, T, Hq*D] with the same sharding as q.
    """
    n = mesh.shape[axis]

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            P(None, axis, head_axis),
            P(None, axis, head_axis),
            P(None, axis, head_axis),
        ),
        out_specs=P(None, axis, head_axis),
        check_vma=False,
    )
    def _ring(q_blk, k_blk, v_blk):
        b, t, hq_l, d = q_blk.shape
        hkv_l = k_blk.shape[2]
        group = hq_l // hkv_l
        my = lax.axis_index(axis)
        qg = q_blk.reshape(b, t, hkv_l, group, d)

        neg = jnp.float32(-1e30)
        tri = jnp.where(
            jnp.tril(jnp.ones((t, t), bool)), 0.0, neg
        ).astype(jnp.float32)
        zeros = jnp.zeros((t, t), jnp.float32)
        full_neg = jnp.full((t, t), neg, jnp.float32)

        perm = [(i, (i + 1) % n) for i in range(n)]

        def step(carry, s):
            o, m, l, kc, vc = carry
            kv_idx = (my - s) % n
            if causal:
                mask = jnp.where(
                    kv_idx == my, tri,
                    jnp.where(kv_idx < my, zeros, full_neg),
                )
            else:
                mask = zeros
            o2, m2, l2 = _block_attend(qg, kc, vc, mask)
            o, m, l = _merge(o, m, l, o2, m2, l2)
            # rotate KV to the next device; the compiler overlaps this
            # ppermute with the next iteration's compute
            kc = lax.ppermute(kc, axis, perm)
            vc = lax.ppermute(vc, axis, perm)
            return (o, m, l, kc, vc), None

        o0 = jnp.zeros((b, t, hkv_l, group, d), jnp.float32)
        m0 = jnp.full((b, hkv_l, group, t), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((b, hkv_l, group, t), jnp.float32)
        (o, m, l, _, _), _ = lax.scan(
            step, (o0, m0, l0, k_blk, v_blk), jnp.arange(n)
        )
        denom = jnp.transpose(l, (0, 3, 1, 2))[..., None]
        out = o / jnp.maximum(denom, 1e-20)
        return out.reshape(b, t, hq_l * d).astype(q_blk.dtype)

    return _ring(q, k, v)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = True,
) -> jax.Array:
    """DeepSpeed-Ulysses-style sequence parallelism: all_to_all swaps the
    sharded dim from sequence to heads, runs FULL-sequence attention on a
    head subset per device, and swaps back.  Complements ring attention:
    better when heads >> devices and the sequence fits per-device HBM.
    """
    n = mesh.shape[axis]
    hq, hkv = q.shape[2], k.shape[2]
    if hkv % n != 0:
        raise ValueError(f"kv heads {hkv} not divisible by axis size {n}")
    group = hq // hkv

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(None, axis), P(None, axis), P(None, axis)),
        out_specs=P(None, axis),
        check_vma=False,
    )
    def _ulysses(q_blk, k_blk, v_blk):
        # [B, T/n, H, D] -> all_to_all -> [B, T, H/n, D]
        def seq2head(x):
            return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                  tiled=True)

        def head2seq(x):
            return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

        qh, kh, vh = seq2head(q_blk), seq2head(k_blk), seq2head(v_blk)
        b, t, hq_l, d = qh.shape
        hkv_l = kh.shape[2]
        qg = qh.reshape(b, t, hkv_l, hq_l // hkv_l, d)
        mask = (
            jnp.where(jnp.tril(jnp.ones((t, t), bool)), 0.0, -1e30)
            if causal else jnp.zeros((t, t))
        ).astype(jnp.float32)
        o, m, l = _block_attend(qg, kh, vh, mask)
        denom = jnp.transpose(l, (0, 3, 1, 2))[..., None]
        out = (o / jnp.maximum(denom, 1e-20)).astype(q_blk.dtype)
        out = out.reshape(b, t, hq_l, d)
        return head2seq(out).reshape(b, q_blk.shape[1], hq * d)

    return _ulysses(q, k, v)
