"""Device-mesh construction and sharding helpers.

The reference scales fan-out through combo channels over sockets
(src/brpc/parallel_channel.h:185, partition_channel.h:75); the TPU-native
equivalent is a jax.sharding.Mesh whose axes name the parallelism dimensions:

- dp: data parallel (ParallelChannel fan-out + merge == grad allreduce)
- tp: tensor parallel (PartitionChannel's N/M sharding)
- sp: sequence parallel (ring attention over ICI neighbours)
- pp: pipeline parallel (streaming-RPC activation pipe)
"""

from __future__ import annotations

from typing import Dict, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    axis_sizes: Dict[str, int] | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a Mesh. Unspecified leading 'dp' absorbs leftover devices.

    make_mesh({'tp': 4}) on 8 devices -> Mesh(dp=2, tp=4).
    make_mesh() -> all devices on 'dp'.
    """
    devices = list(devices if devices is not None else jax.devices())
    axis_sizes = dict(axis_sizes or {})
    n = len(devices)
    named = int(np.prod(list(axis_sizes.values()))) if axis_sizes else 1
    if n % named != 0:
        raise ValueError(f"{n} devices not divisible by axes {axis_sizes}")
    if "dp" not in axis_sizes:
        axis_sizes = {"dp": n // named, **axis_sizes}
    shape = tuple(axis_sizes.values())
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, tuple(axis_sizes.keys()))


def _norm_spec(spec: P, mesh: Mesh) -> P:
    """Drop mesh axes the mesh doesn't have (lets one spec table serve
    dp-only and dp+tp meshes)."""
    names = set(mesh.axis_names)

    def keep(entry):
        if entry is None:
            return None
        if isinstance(entry, (tuple, list)):
            kept = tuple(e for e in entry if e in names)
            return kept if kept else None
        return entry if entry in names else None

    return P(*(keep(e) for e in spec))


def shard_params(params, specs, mesh: Mesh):
    """Device-put a param pytree with per-leaf PartitionSpecs."""
    return jax.tree_util.tree_map(
        lambda s, p: jax.device_put(p, NamedSharding(mesh, _norm_spec(s, mesh))),
        specs,
        params,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_batch(batch, spec: P, mesh: Mesh):
    return jax.device_put(batch, NamedSharding(mesh, _norm_spec(spec, mesh)))
