"""CollectiveChannel — the ParallelChannel contract compiled onto ICI.

The reference fans one call out to N sub-channels with a per-sub
``CallMapper`` (request slicing) and folds replies through a
``ResponseMerger`` (src/brpc/parallel_channel.h:94,127,185).  On TPU the
same contract has a *compiled* fast path: the "sub-channels" are mesh
devices, the mapper is a sharding constraint, and the merger is an XLA
collective riding ICI (psum / all_gather / reduce_scatter / ppermute) —
SURVEY.md §2.7/§5.9.  The RPC tier (cpp/cluster/parallel_channel.*) remains
the partial-failure-tolerant DCN path; this module is the bulk-synchronous
ICI tier, and the BASELINE "ParallelChannel → 8-chip ICI AllReduce" metric
is ``CollectiveChannel.all_reduce``.

Everything here is shard_map-based: callers hand in global arrays with any
sharding; each op pins the input layout, runs the collective per shard, and
returns the merged result.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from brpc_tpu import obs
from brpc_tpu._compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _record_collective(op: str, x) -> None:  # lint: allow-trace-impure
    """Per-collective call + byte counters (``collective_<op>_calls`` /
    ``collective_<op>_bytes``).  These fire when the python method runs:
    eagerly that is once per collective; under ``jax.jit`` it is once per
    trace — i.e. they count collective *programs* built, the compile-side
    view of ICI traffic (sizes still come from the abstract value, which
    tracers carry).  The pragma declares exactly that intent to the
    ``trace-purity`` check: running once at trace time IS the design."""
    if not obs.enabled():
        return
    obs.counter(f"collective_{op}_calls").add(1)
    obs.counter(f"collective_{op}_bytes").add(
        int(np.prod(np.shape(x))) * np.dtype(x.dtype).itemsize)


class CollectiveChannel:
    """Fan-out/merge primitives over one mesh axis.

    ``axis`` names the "sub-channel" dimension (the ParallelChannel's
    AddChannel list); ``mesh`` supplies the devices. All methods are
    jittable and differentiable.
    """

    def __init__(self, mesh: Mesh, axis: str = "dp"):
        if axis not in mesh.axis_names:
            raise ValueError(f"mesh has no axis {axis!r}: {mesh.axis_names}")
        self.mesh = mesh
        self.axis = axis

    @property
    def num_channels(self) -> int:
        return self.mesh.shape[self.axis]

    # ---- ParallelChannel analogs (fan-out + ResponseMerger) ----

    def all_reduce(self, x: jax.Array, op: str = "sum") -> jax.Array:
        """Every shard contributes, every shard receives the merge.

        The reference shape: ParallelChannel broadcast + additive merger.
        x is sharded over ``axis`` on its leading dim; the result is the
        elementwise reduction, replicated.
        """
        _record_collective("all_reduce", x)
        reducer = {"sum": lax.psum, "max": lax.pmax, "min": lax.pmin}[op]

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=P(self.axis),
            out_specs=P(),
            check_vma=False,
        )
        def _ar(shard):
            return reducer(jnp.sum(shard, axis=0), self.axis)

        return _ar(x)

    def all_reduce_inplace(self, x: jax.Array, op: str = "sum") -> jax.Array:
        """AllReduce of replicated-shape tensors (grad sync): x has the SAME
        shape on every shard; result is the cross-shard reduction."""
        _record_collective("all_reduce_inplace", x)
        reducer = {"sum": lax.psum, "max": lax.pmax, "min": lax.pmin}[op]

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=P(*[None] * x.ndim),
            out_specs=P(*[None] * x.ndim),
            check_vma=False,
        )
        def _ar(shard):
            return reducer(shard, self.axis)

        return _ar(x)

    def all_gather(self, x: jax.Array, tiled: bool = True) -> jax.Array:
        """Each shard's slice, concatenated everywhere (fan-out + concat
        merger — the reference's default "append responses in channel
        order")."""
        _record_collective("all_gather", x)

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=P(self.axis),
            out_specs=P(),
            check_vma=False,
        )
        def _ag(shard):
            return lax.all_gather(shard, self.axis, tiled=True)

        return _ag(x)

    def reduce_scatter(self, x: jax.Array) -> jax.Array:
        """Sum across shards, then each shard keeps its slice (the sharded
        merger — PartitionChannel's write path)."""
        _record_collective("reduce_scatter", x)

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=P(*[None] * x.ndim),
            out_specs=P(self.axis),
            check_vma=False,
        )
        def _rs(full):
            return lax.psum_scatter(full, self.axis, scatter_dimension=0,
                                    tiled=True)

        return _rs(x)

    def broadcast(self, x: jax.Array, root: int = 0) -> jax.Array:
        """Root shard's value everywhere (SelectiveChannel pick-one +
        replicate)."""
        _record_collective("broadcast", x)

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=P(self.axis),
            out_specs=P(),
            check_vma=False,
        )
        def _bc(shard):
            full = lax.all_gather(shard, self.axis, tiled=True)
            n = self.num_channels
            return lax.dynamic_slice_in_dim(full, root * (full.shape[0] // n),
                                            full.shape[0] // n, axis=0)

        return _bc(x)

    def shift(self, x: jax.Array, offset: int = 1) -> jax.Array:
        """Neighbour exchange over the ring (ppermute) — the streaming-RPC/
        cascade analog; building block of ring attention and PP."""
        _record_collective("shift", x)
        n = self.num_channels
        perm = [(i, (i + offset) % n) for i in range(n)]

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=P(self.axis),
            out_specs=P(self.axis),
            check_vma=False,
        )
        def _sh(shard):
            return lax.ppermute(shard, self.axis, perm)

        return _sh(x)

    def map_reduce(
        self,
        fn: Callable[[jax.Array], jax.Array],
        x: jax.Array,
        op: str = "sum",
    ) -> jax.Array:
        """CallMapper + ResponseMerger in one: apply ``fn`` per shard
        (mapper), reduce results across shards (merger)."""
        _record_collective("map_reduce", x)
        reducer = {"sum": lax.psum, "max": lax.pmax, "min": lax.pmin}[op]

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=P(self.axis),
            out_specs=P(),
            check_vma=False,
        )
        def _mr(shard):
            return reducer(fn(shard), self.axis)

        return _mr(x)


def allreduce_benchmark(
    mesh: Mesh,
    axis: str = "dp",
    size_mb: float = 64.0,
    iters: int = 20,
    dtype=jnp.float32,
):
    """The BASELINE #3 workload: fp32 AllReduce over ICI; returns GB/s/chip.

    Algorithm bandwidth = 2*(n-1)/n * bytes / time per chip (ring allreduce
    moves each byte twice around all-but-one hops).
    """
    import time

    n = mesh.shape[axis]
    elems = int(size_mb * 1e6 / np.dtype(dtype).itemsize)
    elems = max(elems - elems % (n * 128), n * 128)
    chan = CollectiveChannel(mesh, axis)
    x = jax.device_put(
        jnp.ones((elems,), dtype),
        NamedSharding(mesh, P(axis)),
    )
    ar = jax.jit(lambda a: chan.all_reduce(a, "sum"))
    jax.block_until_ready(ar(x))  # compile
    t0 = time.perf_counter()
    for _ in range(iters):
        out = ar(x)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    nbytes = elems * np.dtype(dtype).itemsize
    algo_bytes = 2 * (n - 1) / n * nbytes
    return {
        "bytes": nbytes,
        "seconds": dt,
        "gbps_per_chip": algo_bytes / dt / 1e9,
        "devices": n,
    }
