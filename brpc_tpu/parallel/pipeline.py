"""Pipeline parallelism — the streaming-RPC activation pipe, compiled.

The reference's streaming RPC is an ordered, flow-controlled byte pipe
between stages (src/brpc/stream.cpp; BASELINE #4 uses it as the activation
pipe for 2-stage PP).  The TPU-native sibling keeps the same shape — stage
i pushes activations to stage i+1 — but compiles the pipe into a
``lax.ppermute`` ring over the 'pp' mesh axis with GPipe-style microbatch
scheduling: at tick t, stage s computes microbatch (t - s) while the
transfer of its previous output overlaps (scaling-book pipelining recipe).
The RPC-tier pipe (cpp/rpc/stream.*) stays the cross-host DCN fallback.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from brpc_tpu._compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_apply(
    stage_fn: Callable[[jax.Array, jax.Array], jax.Array],
    stage_params: jax.Array,
    x: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "pp",
    microbatches: int | None = None,
) -> jax.Array:
    """Runs ``microbatches`` slices of ``x`` through all pipeline stages.

    stage_params: pytree whose leaves have a leading [n_stages] dim, sharded
    over ``axis`` (each device holds its stage's params).
    stage_fn(params_for_stage, microbatch) -> microbatch (same shape).
    x: [M, ...] microbatched input, M divisible by ``microbatches``;
    returns the fully-processed x.

    Schedule: the classic loop — (M + S - 1) ticks; at each tick every
    stage computes one microbatch then passes it right (the activation
    "StreamWrite"); stage 0 feeds fresh microbatches, stage S-1 banks
    results. Bubble fraction (S-1)/(M+S-1), amortized by M.
    """
    n = mesh.shape[axis]
    mb = microbatches or n

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis), P(axis)),
        out_specs=P(axis),
        check_vma=False,
    )
    def _pipe(params_blk, x_blk):
        # params_blk: stage params with leading dim 1; x_blk: [M/n, ...]
        params = jax.tree_util.tree_map(lambda p: p[0], params_blk)
        stage = lax.axis_index(axis)
        # Gather the full microbatch set on every stage; stage 0 is the
        # feeder (cheap at microbatch granularity; the steady-state traffic
        # is the neighbour ppermute below).
        x_all = lax.all_gather(x_blk, axis, tiled=True)
        m_total = x_all.shape[0]
        per = m_total // mb  # rows per microbatch
        shaped = x_all.reshape(mb, per, *x_all.shape[1:])

        right = [(i, (i + 1) % n) for i in range(n)]
        ticks = mb + n - 1

        def tick(carry, t):
            inflight, done = carry
            # stage 0 injects microbatch t (or zeros past the end)
            fresh = lax.dynamic_index_in_dim(
                shaped, jnp.minimum(t, mb - 1), keepdims=False
            )
            cur = jnp.where(stage == 0, fresh, inflight)
            active = (t - stage >= 0) & (t - stage < mb)
            out = stage_fn(params, cur)
            out = jnp.where(active, out, cur)
            # last stage banks microbatch (t - (n-1)) when it was active
            bank_idx = t - (n - 1)
            done = lax.cond(
                (stage == n - 1) & (bank_idx >= 0) & (bank_idx < mb),
                lambda d: lax.dynamic_update_index_in_dim(
                    d, out, jnp.maximum(bank_idx, 0), 0
                ),
                lambda d: d,
                done,
            )
            # the activation pipe: pass right (stage S-1 → 0 link is idle
            # data, ignored by stage 0 which injects fresh input)
            inflight = lax.ppermute(out, axis, right)
            return (inflight, done), None

        zero_mb = jnp.zeros_like(shaped[0])
        done0 = jnp.zeros_like(shaped)
        (_, done), _ = lax.scan(
            tick, (zero_mb, done0), jnp.arange(ticks)
        )
        full = done.reshape(m_total, *x_all.shape[1:])
        # only stage n-1 banked results; psum of masked copies broadcasts
        # them (ppermute can't fan out one source to many destinations)
        full = lax.psum(
            jnp.where(stage == n - 1, full, jnp.zeros_like(full)), axis
        )
        per_dev = m_total // n
        return lax.dynamic_slice_in_dim(
            full, stage * per_dev, per_dev, axis=0
        )

    return _pipe(stage_params, x)
