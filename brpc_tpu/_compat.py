"""JAX version compatibility shims.

``shard_map`` moved twice across JAX releases: ``jax.experimental.
shard_map.shard_map`` (≤0.4.x) → ``jax.shard_map`` (≥0.5), and its
replication-check kwarg was renamed ``check_rep`` → ``check_vma``.  The
parallel/ps modules are written against the new surface; this shim maps
them onto whichever JAX is installed so the pure-JAX tiers import (and
their tests run) on the container's pinned JAX with no native toolchain
involved.
"""

from __future__ import annotations

import inspect

try:
    from jax import shard_map as _shard_map_impl  # jax >= 0.5
except ImportError:  # pragma: no cover - exercised on jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map_impl

_PARAMS = set(inspect.signature(_shard_map_impl).parameters)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
    if check_vma is not None:
        if "check_vma" in _PARAMS:
            kw["check_vma"] = check_vma
        elif "check_rep" in _PARAMS:
            kw["check_rep"] = check_vma
    return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, **kw)
