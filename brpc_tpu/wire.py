"""Wire contracts: the declarative frame-schema registry for the PS
fabric's hand-rolled framings.

Every binary framing that crosses the wire — Lookup/ApplyGrad requests,
the stream frame header, writer-seq windows, ``ApplyGradId`` with its
guards, replication ``Sync``, the migration handoff payloads — is
declared here ONCE as named fields with explicit type, width and
endianness, plus the length-prefix relationships between them.  The
hand-rolled ``_pack_*``/``_unpack_*`` sites in ``ps_remote.py`` /
``reshard.py`` stay (they are the measured hot path), but they are no
longer the only statement of the format:

- the ``wire-contract`` lint check (:mod:`brpc_tpu.analysis.lint`)
  cross-checks every registered site's struct format strings against
  the schema it claims to implement, flags pack/unpack drift, and flags
  count/length reads on parse paths that never reach a bounds check;
- the structure-aware fuzzer (:mod:`brpc_tpu.analysis.fuzz`) derives
  its mutation points (field boundaries, length fields, string fields)
  from the same schemas, so every declared framing is fuzzed;
- :func:`FrameSchema.pack`/:func:`FrameSchema.unpack` are the reference
  implementations the hand-rolled sites are tested against
  (``tests/test_wire.py`` parity tests).

The guard helpers (:func:`need`, :func:`check_count`, :func:`read`) are
the sanctioned bounds-validation vocabulary: hostile input must raise
:class:`WireError` — a clean, non-retriable ``EBADFRAME`` on the wire —
before any unbounded allocation, loop, or table mutation.  The
reference framework treats every protocol parser as hostile-input
surface and fuzzes each one (SURVEY §2.5, §4); this module is the
contract those fuzzers and lints enforce.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "EBADFRAME", "WireError", "need", "check_count", "read",
    "Int", "Bytes", "Array", "Group", "Tail", "FrameSchema",
    "REGISTRY", "TEXT_PARSERS", "schema",
]

#: error code for a malformed frame rejected by a wire-contract guard
#: (outside the native errors.h space, beside EBREAKEROPEN..ESCHEMEMOVED
#: in :mod:`brpc_tpu.resilience`).  Never retriable: the same bytes
#: parse the same way twice.
EBADFRAME = 2013

#: absolute sanity cap on any wire count field — no legitimate frame in
#: this fabric carries more elements than this, and every parse-path
#: bound is additionally clamped by the bytes actually present.
MAX_WIRE_COUNT = 1 << 24

#: first-int32 sentinel of the optional deadline header
#: (schema ``deadline_hdr``): > MAX_WIRE_COUNT, so no legitimate count
#: or length field of any data-plane framing can collide with it — a
#: request starting with this value carries a 12-byte deadline prefix,
#: anything else is the bare legacy framing.  The native Lookup parser
#: (cpp/capi/ps_shard.cc) tests the same constant.
DEADLINE_MAGIC = 0x7EAD11E5

#: first-int32 sentinel of the v2 deadline header (schema
#: ``deadline_hdr_v2``): RELATIVE budget + server-side arrival stamp —
#: drops the same-host/NTP wall-clock assumption of the absolute form.
#: Also above MAX_WIRE_COUNT, and tested by the native Lookup parser.
DEADLINE_MAGIC2 = 0x7EAD11E6

#: first-int32 sentinel of a press trace file ("PRS1" little-endian,
#: schema ``press_header``)
PRESS_MAGIC = 0x31535250

#: first-int32 sentinel of a checkpoint base snapshot file ("SNAP"
#: little-endian, schema ``ckpt_snap``).  Like the deadline magics it
#: sits above MAX_WIRE_COUNT, so no legitimate count field collides.
CKPT_SNAP_MAGIC = 0x50414E53

#: first-int32 sentinel of one delta-log record ("DLT1" little-endian,
#: schema ``ckpt_delta``)
CKPT_DELTA_MAGIC = 0x31544C44

#: first-int32 sentinel of the compaction marker file ("CMK1"
#: little-endian, schema ``ckpt_marker``)
CKPT_MARKER_MAGIC = 0x314B4D43


class WireError(ValueError):
    """Malformed frame, rejected by a bounds/validity check BEFORE any
    allocation or state mutation.  Carries :data:`EBADFRAME` so the
    server trampoline answers a clean, non-retriable code (the
    ``_error_code_of`` contract in :mod:`brpc_tpu.rpc`)."""

    code = EBADFRAME


def need(payload, offset: int, nbytes: int, what: str = "frame") -> None:
    """The span guard: ``payload`` must hold ``nbytes`` at ``offset``."""
    if offset < 0 or nbytes < 0 or len(payload) - offset < nbytes:
        raise WireError(
            f"{what}: need {nbytes} byte(s) at offset {offset}, have "
            f"{len(payload)} total")


def check_count(count: int, limit: int, what: str = "count") -> int:
    """The count guard: a wire count must be non-negative and bounded by
    what the payload can actually carry (``limit`` is the caller's
    bytes-derived cap).  Returns ``count`` so guards chain inline.
    A negative count is ALWAYS hostile — numpy's ``frombuffer`` treats
    ``count=-1`` as "read everything", silently re-interpreting the
    whole payload."""
    if not 0 <= count <= min(limit, MAX_WIRE_COUNT):
        raise WireError(
            f"{what} {count} outside [0, {min(limit, MAX_WIRE_COUNT)}]")
    return count


def _sizeof(fmt: str) -> int:
    # struct caches compiled formats internally (and is C-thread-safe),
    # so no hand cache is needed on this handler-reachable path
    return struct.calcsize(fmt)


def read(fmt: str, payload, offset: int = 0,
         what: str = "frame") -> tuple:
    """Guarded ``struct.unpack_from``: raises :class:`WireError` (not
    ``struct.error``) when the payload is shorter than the format — the
    drop-in for control-plane header reads."""
    need(payload, offset, _sizeof(fmt), what)
    return struct.unpack_from(fmt, payload, offset)


# ---------------------------------------------------------------------------
# field model
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Int:
    """One fixed-width little-endian integer (``fmt`` is ``"<i"`` or
    ``"<q"``)."""

    name: str
    fmt: str = "<q"


@dataclasses.dataclass(frozen=True)
class Bytes:
    """A length-prefixed byte string; ``length`` names the earlier
    :class:`Int` field carrying its byte length."""

    name: str
    length: str


@dataclasses.dataclass(frozen=True)
class Array:
    """A packed scalar array tail; element count is ``count_field *
    mult`` where ``mult`` is a literal or the symbolic ``"dim"``
    (resolved at pack/unpack time — the embedding width is serving
    geometry, not wire data)."""

    name: str
    dtype: str          # numpy dtype string, e.g. "<i4" / "<f4"
    count: str          # name of the Int field holding the element count
    mult: object = 1    # int, or "dim"


@dataclasses.dataclass(frozen=True)
class Group:
    """``count`` repetitions of a record of scalar/bytes fields."""

    name: str
    count: str          # name of the Int field holding the repeat count
    fields: Tuple = ()


@dataclasses.dataclass(frozen=True)
class Tail:
    """The rest of the payload, opaque at this level; ``schema`` names
    the nested :class:`FrameSchema` when the tail is itself framed."""

    name: str
    schema: str = ""


def _group_min_entry(g: Group) -> int:
    """Smallest possible wire size of one group entry (empty strings)."""
    total = 0
    for f in g.fields:
        if isinstance(f, Int):
            total += _sizeof(f.fmt)
    return max(total, 1)


# ---------------------------------------------------------------------------
# the schema object
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class FrameSchema:
    """One framing, declared once.  ``pack_sites``/``unpack_sites`` are
    the in-tree functions implementing it by hand (qualnames relative to
    ``brpc_tpu``: ``"ps_remote._pack_windows"``); ``exact_sites`` are
    the dedicated single-purpose functions whose struct-format stream
    must EXACTLY equal this schema's scalar sequence (shared multi-frame
    functions are checked by in-order subsequence instead).
    ``native_sites`` documents native-side consumers (cpp paths) — they
    satisfy the pairing requirement without a Python unpack site, and
    the cross-language tier (``analysis.native``) checks each one's C++
    read sequence against this schema.  ``segments`` upgrades a shared
    multi-frame site from subsequence to EXACT matching: each entry maps
    a site qualname to the dispatch-discriminant keys
    (``("ps_remote.PsShardServer._serve_control", ("Sync",))`` means
    "inside the ``method == \"Sync\"`` branch the stream must equal this
    schema exactly").  ``prebranch`` declares, per segmented site, the
    format stream of the SHARED header the handler reads before (i.e.
    outside) its dispatch branches — ``("ps_remote.PsShardServer._serve",
    "i")`` says "one int32 is read pre-branch"; the lint prepends it to
    the keyed branch's stream for the exact comparison and flags a
    declaration that drifts from the actual shared reads.
    ``response=True`` marks server→client response
    frames whose client consumer is trusted/optional — unpaired is
    explained, not flagged."""

    name: str
    fields: Tuple
    doc: str = ""
    pack_sites: Tuple[str, ...] = ()
    unpack_sites: Tuple[str, ...] = ()
    exact_sites: Tuple[str, ...] = ()
    native_sites: Tuple[str, ...] = ()
    segments: Tuple[Tuple[str, Tuple[str, ...]], ...] = ()
    prebranch: Tuple[Tuple[str, str], ...] = ()
    response: bool = False

    # -- derived ----------------------------------------------------------

    def scalar_formats(self) -> List[str]:
        """The ordered scalar struct-format characters this schema puts
        on the wire (group records contribute one iteration) — what the
        lint matches against a site's extracted format stream."""
        out: List[str] = []

        def walk(fields: Sequence) -> None:
            for f in fields:
                if isinstance(f, Int):
                    out.append(f.fmt.lstrip("<>=!@"))
                elif isinstance(f, Group):
                    walk(f.fields)

        walk(self.fields)
        return out

    def _mult(self, f: Array, dim: int) -> int:
        return dim if f.mult == "dim" else int(f.mult)

    # -- reference implementations ---------------------------------------

    def pack(self, values: Dict[str, object], *, dim: int = 1) -> bytes:
        """Reference packer: builds the frame from a field-value dict
        (ints by name; ``Bytes`` as bytes — their length fields are
        derived; ``Group`` as a list of per-entry dicts — the count
        field is derived; ``Array`` as a numpy array or bytes; ``Tail``
        as bytes)."""
        parts: List[bytes] = []
        self._pack_into(self.fields, values, parts, dim)
        return b"".join(parts)

    def _pack_into(self, fields: Sequence, values: Dict[str, object],
                   parts: List[bytes], dim: int) -> None:
        derived: Dict[str, int] = {}
        for f in fields:
            if isinstance(f, Bytes):
                derived.setdefault(f.length, len(values[f.name]))
            elif isinstance(f, Group):
                derived.setdefault(f.count, len(values[f.name]))
            elif isinstance(f, Array):
                arr = np.asarray(values[f.name])
                mult = self._mult(f, dim)
                derived.setdefault(f.count, arr.size // max(mult, 1))
        for f in fields:
            if isinstance(f, Int):
                val = values.get(f.name, derived.get(f.name, 0))
                parts.append(struct.pack(f.fmt, int(val)))
            elif isinstance(f, Bytes):
                parts.append(bytes(values[f.name]))
            elif isinstance(f, Array):
                arr = np.asarray(values[f.name]).astype(
                    np.dtype(f.dtype), copy=False)
                parts.append(arr.tobytes())
            elif isinstance(f, Group):
                for entry in values[f.name]:
                    self._pack_into(f.fields, entry, parts, dim)
            elif isinstance(f, Tail):
                parts.append(bytes(values.get(f.name, b"")))

    def unpack(self, payload, *, offset: int = 0,
               dim: int = 1) -> Tuple[Dict[str, object], int]:
        """Reference parser: fully guarded — every length/count is
        bounds-checked against the bytes present before it drives an
        allocation or loop.  Returns ``(values, end_offset)``."""
        values, off = self._unpack_from(self.fields, payload, offset,
                                        dim, self.name)
        return values, off

    def _unpack_from(self, fields: Sequence, payload, off: int,
                     dim: int, what: str
                     ) -> Tuple[Dict[str, object], int]:
        values: Dict[str, object] = {}
        for f in fields:
            if isinstance(f, Int):
                (values[f.name],) = read(f.fmt, payload, off,
                                         f"{what}.{f.name}")
                off += _sizeof(f.fmt)
            elif isinstance(f, Bytes):
                ln = check_count(int(values[f.length]),
                                 len(payload) - off,
                                 f"{what}.{f.length}")
                values[f.name] = bytes(payload[off:off + ln])
                off += ln
            elif isinstance(f, Array):
                mult = self._mult(f, dim)
                dt = np.dtype(f.dtype)
                n = check_count(int(values[f.count]),
                                (len(payload) - off) //
                                max(dt.itemsize * max(mult, 1), 1),
                                f"{what}.{f.count}") * mult
                values[f.name] = np.frombuffer(payload, dt, n, off)
                off += n * dt.itemsize
            elif isinstance(f, Group):
                cnt = check_count(int(values[f.count]),
                                  (len(payload) - off) //
                                  _group_min_entry(f),
                                  f"{what}.{f.count}")
                entries = []
                for _ in range(cnt):
                    entry, off = self._unpack_from(f.fields, payload,
                                                   off, dim,
                                                   f"{what}.{f.name}")
                    entries.append(entry)
                values[f.name] = entries
            elif isinstance(f, Tail):
                values[f.name] = bytes(payload[off:])
                off = len(payload)
        return values, off

    # -- fuzzing support --------------------------------------------------

    def example(self, rng, *, dim: int = 4) -> Dict[str, object]:
        """A small valid value dict, deterministic under ``rng`` (a
        ``random.Random``) — the fuzzer's mutation baseline."""
        values: Dict[str, object] = {}
        self._example_into(self.fields, values, rng, dim)
        return values

    def _example_into(self, fields: Sequence, values: Dict[str, object],
                      rng, dim: int) -> None:
        derived = set()
        for f in fields:
            if isinstance(f, Bytes):
                derived.add(f.length)
            elif isinstance(f, (Array, Group)):
                derived.add(f.count)
        for f in fields:
            if isinstance(f, Int):
                if f.name not in derived:
                    values[f.name] = rng.randrange(0, 1 << 16)
            elif isinstance(f, Bytes):
                s = bytes(rng.randrange(97, 123)
                          for _ in range(rng.randrange(0, 9)))
                values[f.name] = s
                values[f.length] = len(s)
            elif isinstance(f, Array):
                mult = self._mult(f, dim)
                # shared count fields (apply_req's ids/grads) must agree
                n = int(values.get(f.count, rng.randrange(0, 5)))
                values[f.count] = n
                dt = np.dtype(f.dtype)
                raw = bytes(rng.randrange(0, 256)
                            for _ in range(n * mult * dt.itemsize))
                values[f.name] = np.frombuffer(raw, dt)
            elif isinstance(f, Group):
                n = rng.randrange(0, 4)
                values[f.count] = n
                entries = []
                for _ in range(n):
                    entry: Dict[str, object] = {}
                    self._example_into(f.fields, entry, rng, dim)
                    entries.append(entry)
                values[f.name] = entries
            elif isinstance(f, Tail):
                if f.schema and f.schema in REGISTRY:
                    nested = REGISTRY[f.schema]
                    values[f.name] = nested.pack(
                        nested.example(rng, dim=dim), dim=dim)
                else:
                    values[f.name] = bytes(
                        rng.randrange(0, 256)
                        for _ in range(rng.randrange(0, 17)))


# ---------------------------------------------------------------------------
# the registry: every framing in the tree, declared once
# ---------------------------------------------------------------------------

REGISTRY: Dict[str, FrameSchema] = {}


def schema(name: str, *fields, **kw) -> FrameSchema:
    sc = FrameSchema(name=name, fields=tuple(fields), **kw)
    REGISTRY[name] = sc
    return sc


schema(
    "lookup_req",
    Int("count", "<i"), Array("ids", "<i4", "count"),
    doc="Lookup request: int32 count ++ int32 ids (absolute)",
    pack_sites=("ps_remote._pack_lookup_req",
                "ps_remote._pack_lookup_req_iobuf"),
    unpack_sites=("ps_remote.PsShardServer._serve",
                  "ps_remote.DevicePsShardServer._serve"),
    exact_sites=("ps_remote._pack_lookup_req",),
    native_sites=("cpp/capi/ps_shard.cc:CPsService::ServeLookup",),
    segments=(("ps_remote.PsShardServer._serve", ("Lookup",)),
              ("ps_remote.DevicePsShardServer._serve", ("Lookup",))),
    prebranch=(("ps_remote.PsShardServer._serve", "i"),
               ("ps_remote.DevicePsShardServer._serve", "i")))

schema(
    "apply_req",
    Int("count", "<i"), Array("ids", "<i4", "count"),
    Array("grads", "<f4", "count", mult="dim"),
    doc="ApplyGrad framing: count ++ ids ++ float32 grads [count, dim]",
    pack_sites=("ps_remote._pack_apply_req",
                "ps_remote._pack_apply_req_iobuf"),
    unpack_sites=("ps_remote._unpack_apply",),
    exact_sites=("ps_remote._pack_apply_req", "ps_remote._unpack_apply"))

schema(
    "stream_frame",
    Int("seq"), Int("epoch"), Int("gen"), Tail("body"),
    doc="stream frame header (seq, epoch, gen int64) + framed body",
    pack_sites=("ps_remote._pack_stream_frame",
                "ps_remote._pack_stream_frame_iobuf"),
    unpack_sites=("ps_remote._ApplyStreamReceiver.on_data",
                  "ps_remote._ReplicaStreamReceiver.on_data",
                  "ps_remote._MigrateStreamReceiver.on_data"),
    exact_sites=("ps_remote._pack_stream_frame",))

schema(
    "windows",
    Int("count", "<i"),
    Group("entries", "count",
          (Int("wlen", "<i"), Bytes("writer", "wlen"), Int("seq"))),
    doc="writer seq high-water map: count ++ (len ++ utf8 ++ seq)*",
    pack_sites=("ps_remote._pack_windows",),
    unpack_sites=("ps_remote._unpack_windows",),
    exact_sites=("ps_remote._pack_windows", "ps_remote._unpack_windows"))

schema(
    "apply_id_req",
    Int("wlen", "<i"), Bytes("writer", "wlen"), Int("seq"),
    Int("nguards", "<i"),
    Group("guards", "nguards",
          (Int("klen", "<i"), Bytes("key", "klen"), Int("q"))),
    Tail("body", schema="apply_req"),
    doc="ApplyGradId: writer key ++ seq ++ guards ++ apply_req body",
    pack_sites=("ps_remote._pack_apply_id_req",),
    unpack_sites=("ps_remote._unpack_apply_id",),
    exact_sites=("ps_remote._pack_apply_id_req",
                 "ps_remote._unpack_apply_id"))

schema(
    "replica_apply_body",
    Tail("windows", schema="windows"),
    doc="ReplicaApply/MigrateApply frame body: windows ++ apply_req "
        "(two nested frames back to back; the windows parser returns "
        "its end offset)",
    pack_sites=("ps_remote.PsShardServer._apply_batch",
                "ps_remote.DevicePsShardServer._apply_batch",
                "reshard.MigrationShipper.ship"),
    unpack_sites=("ps_remote.PsShardServer._apply_replica_frame",
                  "ps_remote.PsShardServer._apply_migrate_frame"))

schema(
    "replica_apply_setup",
    Int("epoch"),
    doc="ReplicaApply stream setup: the sender's fencing epoch",
    pack_sites=("ps_remote._Replicator._connect",
                "ps_remote._Replicator._try_hydrate"),
    unpack_sites=("ps_remote.PsShardServer._serve_stream_setup",))

schema(
    "sync_req",
    Int("epoch"), Int("gen"), Int("count"),
    Array("table", "<f4", "count"), Tail("windows", schema="windows"),
    doc="replication Sync: epoch ++ gen ++ f32 count ++ table ++ windows",
    pack_sites=("ps_remote._Replicator._connect",
                "durable.hydrate_replica"),
    unpack_sites=("ps_remote.PsShardServer._serve_control",),
    segments=(("ps_remote.PsShardServer._serve_control", ("Sync",)),))

schema(
    "promote_req",
    Int("epoch"),
    doc="Promote: the new fencing epoch",
    pack_sites=("ps_remote.RemoteEmbedding._failover",),
    unpack_sites=("ps_remote.PsShardServer._serve_control",),
    segments=(("ps_remote.PsShardServer._serve_control",
               ("Promote",)),))

schema(
    "scheme_fence_req",
    Int("ver"),
    doc="SchemeFence: the successor scheme version",
    pack_sites=("reshard.MigrationDriver.cutover",),
    unpack_sites=("ps_remote.PsShardServer._serve_control",),
    segments=(("ps_remote.PsShardServer._serve_control",
               ("SchemeFence",)),))

schema(
    "migrate_sync_req",
    Int("scheme"), Int("src_gen"), Int("row0"), Int("count"),
    Int("alen", "<i"), Bytes("src", "alen"),
    Array("rows", "<f4", "count", mult="dim"),
    Tail("windows", schema="windows"),
    doc="MigrateSync: range handoff header ++ source addr ++ rows ++ "
        "windows",
    pack_sites=("reshard.MigrationShipper._connect",
                "durable.hydrate_destination"),
    unpack_sites=("ps_remote.PsShardServer._serve_control",),
    segments=(("ps_remote.PsShardServer._serve_control",
               ("MigrateSync",)),))

schema(
    "migrate_apply_setup",
    Int("scheme"), Int("alen", "<i"), Bytes("src", "alen"),
    doc="MigrateApply stream setup: successor scheme ++ source addr",
    pack_sites=("reshard.MigrationShipper._connect",
                "reshard.MigrationShipper._try_hydrate"),
    unpack_sites=("ps_remote.PsShardServer._serve_stream_setup",))

schema(
    "ack_frame",
    Int("gen"),
    doc="one int64 riding a reply stream: a generation ack, or a "
        "negative fence notification",
    pack_sites=("ps_remote._ApplyStreamReceiver._fence",
                "ps_remote._ReplicaStreamReceiver.on_data",
                "ps_remote._MigrateStreamReceiver.on_data"),
    unpack_sites=("ps_remote._ReplicaAckReceiver.on_data",
                  "ps_remote._PushStreamReceiver.on_data",
                  "reshard._ShipperAckReceiver.on_data"))

schema(
    "gen_rsp",
    Int("gen"),
    doc="int64 generation response (ApplyGrad/Flush/MigrateStart/...)",
    pack_sites=("ps_remote.PsShardServer._serve_control",
                "ps_remote.PsShardServer._serve_apply_id",),
    unpack_sites=("ps_remote.RemoteEmbedding._note_acked_gen",),
    segments=(("ps_remote.PsShardServer._serve_control",
               ("Flush", "MigrateStart", "SchemeFence",
                "CompleteImport")),),
    response=True)

schema(
    "epoch_gen_rsp",
    Int("epoch"), Int("gen"),
    doc="(epoch, gen) int64 pair: the Promote response",
    pack_sites=("ps_remote.PsShardServer._serve_control",),
    segments=(("ps_remote.PsShardServer._serve_control",
               ("Promote",)),),
    response=True)

schema(
    "replica_setup_rsp",
    Int("epoch"), Int("gen"), Int("seeded"),
    doc="ReplicaApply stream setup response: the backup's fencing "
        "epoch ++ installed generation ++ chain-seeded flag — seeded "
        "distinguishes a gen-0 backup whose table WAS established by a "
        "wholesale Sync (or a seeded checkpoint base) from a fresh "
        "random-init table, so first-boot hydration can ship only the "
        "delta tail",
    pack_sites=("ps_remote.PsShardServer._serve_stream_setup",),
    unpack_sites=("ps_remote._Replicator._try_hydrate",),
    response=True)

schema(
    "deadline_hdr",
    Int("magic", "<i"), Int("deadline_us"), Tail("body"),
    doc="optional request prefix (overload control): DEADLINE_MAGIC ++ "
        "absolute wall-clock deadline in microseconds ++ the original "
        "request body — servers shed work whose budget is already "
        "exhausted (EDEADLINE 2014) before touching the table; the "
        "native Lookup handler peels the same header",
    pack_sites=("ps_remote._pack_deadline",
                "ps_remote._pack_deadline_iobuf"),
    unpack_sites=("ps_remote._unpack_deadline",),
    exact_sites=("ps_remote._pack_deadline",
                 "ps_remote._unpack_deadline"),
    native_sites=("cpp/capi/ps_shard.cc:CPsService::ServeLookup",))

schema(
    "deadline_hdr_v2",
    Int("magic", "<i"), Int("budget_us"), Tail("body"),
    doc="v2 deadline prefix: DEADLINE_MAGIC2 ++ RELATIVE budget in "
        "microseconds ++ the original request body — the server stamps "
        "arrival with its OWN clock and computes expiry as arrival + "
        "budget, so no same-host/NTP wall-clock agreement is assumed; "
        "the shared _unpack_deadline dispatches on the magic and the "
        "native Lookup handler peels both forms",
    pack_sites=("ps_remote._pack_deadline_rel",
                "ps_remote._pack_deadline_rel_iobuf"),
    unpack_sites=("ps_remote._unpack_deadline",),
    exact_sites=("ps_remote._pack_deadline_rel",),
    native_sites=("cpp/capi/ps_shard.cc:CPsService::ServeLookup",))

schema(
    "press_header",
    Int("magic", "<i"), Int("version", "<i"), Int("seed"),
    Int("vocab"), Int("dim", "<i"), Int("count", "<i"),
    doc="press trace file header: PRESS_MAGIC ++ format version ++ "
        "workload seed ++ vocab ++ dim ++ record count",
    pack_sites=("press._pack_press_header",),
    unpack_sites=("press._unpack_press_header",),
    exact_sites=("press._pack_press_header",
                 "press._unpack_press_header"))

schema(
    "press_record",
    Int("t_us"), Int("op", "<i"), Int("nids", "<i"),
    Array("ids", "<i4", "nids"),
    doc="one recorded traffic op: scheduled arrival offset (us from "
        "trace start) ++ op kind (0=lookup, 1=apply) ++ key ids; "
        "gradients are re-derived from the header seed on replay",
    pack_sites=("press._pack_press_record",),
    unpack_sites=("press._unpack_press_record",),
    exact_sites=("press._pack_press_record",
                 "press._unpack_press_record"))

schema(
    "ckpt_snap",
    Int("magic", "<i"), Int("version", "<i"), Int("epoch"), Int("gen"),
    Int("rows", "<i"), Int("dim", "<i"), Int("seeded", "<i"),
    Int("crc"), Int("count"),
    Array("table", "<f4", "count"), Tail("windows", schema="windows"),
    doc="checkpoint base snapshot file (brpc_tpu.durable), format v2: "
        "CKPT_SNAP_MAGIC ++ format version ++ fencing epoch ++ "
        "generation ++ table geometry ++ chain-seeded flag (a gen-0 "
        "base from a Sync'd server is not a fresh random table) ++ "
        "crc32 of everything after the header ++ f32 element count ++ "
        "the table image ++ writer dedup windows — restore parses disk "
        "bytes as hostile input, so torn/bit-flipped files must answer "
        "a clean reject",
    pack_sites=("durable._pack_snapshot",),
    unpack_sites=("durable._unpack_snapshot",),
    exact_sites=("durable._pack_snapshot", "durable._unpack_snapshot"))

schema(
    "ckpt_delta",
    Int("magic", "<i"), Int("gen"), Int("crc"), Int("blen", "<i"),
    Bytes("body", "blen"),
    doc="one delta-log record (brpc_tpu.durable): CKPT_DELTA_MAGIC ++ "
        "the generation this batch produced ++ crc32 of the body ++ "
        "body length ++ a replica_apply_body frame (windows ++ "
        "apply_req) — the ReplicaApply framing teed to disk, so "
        "apply order is log order",
    pack_sites=("durable._pack_delta",),
    unpack_sites=("durable._unpack_delta",),
    exact_sites=("durable._pack_delta", "durable._unpack_delta"))

schema(
    "ckpt_marker",
    Int("magic", "<i"), Int("version", "<i"), Int("base_gen"),
    doc="compaction marker file (brpc_tpu.durable): the generation of "
        "the newest durable base snapshot — advisory cross-check only "
        "(restore trusts the validated snapshots themselves), so a "
        "stale marker after a crash mid-compaction is tolerated",
    pack_sites=("durable._pack_marker",),
    unpack_sites=("durable._unpack_marker",),
    exact_sites=("durable._pack_marker", "durable._unpack_marker"))

schema(
    "writer_seq_rsp",
    Int("applied"), Int("gen"),
    doc="WriterSeq response: applied high-water ++ covering gen",
    pack_sites=("ps_remote.PsShardServer._serve_control",),
    unpack_sites=("ps_remote.RemoteEmbedding._transfer_pushes",
                  "ps_remote.RemoteEmbedding._confirm_push"),
    segments=(("ps_remote.PsShardServer._serve_control",
               ("WriterSeq",)),),
    response=True)


#: text/record parsers on the registry plane — not byte frames, but
#: hostile-input surface all the same.  The lint verifies each exists
#: and each is covered by a fuzz target (the "fuzzers for every parser"
#: gate); the fuzzer mutates tags / JSON records directly.
TEXT_PARSERS: Tuple[str, ...] = (
    "naming.parse_shard_tag",
    "naming.parse_claim_tag",
    "naming.parse_schemes",
    "naming.parse_claims",
)
