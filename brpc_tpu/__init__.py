"""brpc_tpu — a TPU-native RPC and parameter-server fabric.

A from-scratch rebuild of the capabilities of Apache bRPC (reference:
/root/reference, see SURVEY.md) designed TPU-first:

- ``cpp/``            native C++ core: IOBuf, M:N fiber scheduler, wait-free
                      socket transport, RPC runtime (Server/Channel/Controller),
                      cluster layer (naming services, load balancers, circuit
                      breaker), bvar-style metrics.  Mirrors bRPC's
                      butil/bthread/bvar/brpc layering (SURVEY.md §1).
- ``brpc_tpu.rpc``    ctypes bindings over the native core's C ABI.
- ``brpc_tpu.parallel`` the combo-channel contract (ParallelChannel /
                      SelectiveChannel / PartitionChannel, reference
                      src/brpc/parallel_channel.h:185) mapped onto XLA
                      collectives over a jax.sharding.Mesh: CollectiveChannel
                      (AllReduce/AllGather/ReduceScatter on ICI), ring
                      attention for sequence parallelism, pipeline stages as
                      the streaming-RPC analog.
- ``brpc_tpu.models`` flagship models for the parameter-server workloads
                      (Llama-family embedding shards + transformer).
- ``brpc_tpu.ops``    TPU kernels (pallas) and numerics helpers.
- ``brpc_tpu.obs``    observability: metrics registry, rpcz-style tracing.
- ``brpc_tpu.resilience`` fault tolerance: retry policy with deadline
                      budgets, backup requests (hedging + native cancel),
                      per-endpoint circuit breakers, health-check revival.
- ``brpc_tpu.fault``  deterministic fault injection (seeded FaultPlan)
                      hooked at the server trampolines and client calls.
"""

__version__ = "0.1.0"
