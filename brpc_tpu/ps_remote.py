"""Remote parameter-server tier: embedding shards served over the native
RPC fabric, driven from JAX training loops.

This is the DCN tier of the BASELINE #5 workload ("param-server serving
embedding shards, allreduce grads"): each shard is a native Server
(cpp/rpc) holding rows [i*rows_per, (i+1)*rows_per); the client routes ids
to owners (the PartitionChannel "i/N" contract, cpp/cluster/
partition_channel.*) and runs Lookup / ApplyGrad calls. The intra-pod tier
— where the table fits in pod HBM — is brpc_tpu.ps (compiled collectives).

Wire format (little-endian): Lookup req = int32 count ++ int32 ids;
rsp = float32 rows [count, dim]. ApplyGrad req = int32 count ++ int32 ids
++ float32 grads [count, dim]; rsp = empty.  The streaming push
(``StreamApply``) reuses the ApplyGrad framing: the setup RPC carries the
writer's id (empty = the legacy unframed mode) and every stream FRAME is
one ``(seq, epoch, gen)`` int64 header + framed delta — no per-frame
response; application order/completion ride the stream close, and the
server's per-writer seq window makes reconnect replay IDEMPOTENT (a
frame whose write failed may still have reached the server; replaying it
dedups instead of double-applying).

Replication (this tier's availability story): a :class:`naming.ReplicaSet`
per shard range declares primary+backups.  Reads route to any live
replica by latency+inflight score; writes go to the primary, which
propagates every APPLIED batch to its backups over the same stream
framing (``ReplicaApply``), generation-tagged so a backup installing
gen N+1 is byte-identical to the primary.  Promotion is fenced by an
epoch: a stale primary's propagation is rejected (EFENCED) and demotes
itself.  See the "Replication & failover" README section.
"""

from __future__ import annotations

import collections
import itertools
import json
import struct
import threading
import time
import uuid
from typing import Dict, List, Optional, Sequence

import numpy as np

from brpc_tpu import obs, resilience, rpc, wire
from brpc_tpu.analysis.race import checked_lock, checked_rwlock
from brpc_tpu.limiter import ServerLimiter
from brpc_tpu.naming import (PartitionScheme, ReplicaSet, parse_claims,
                             parse_schemes, parse_shard_tag)


def _reject_frame(method: str) -> None:
    """Count one malformed-frame rejection (``ps_parse_rejects`` total +
    per method) — fuzz runs and hostile real traffic both show up in the
    ``_status`` vars instead of vanishing into generic errors."""
    if obs.enabled():
        obs.counter("ps_parse_rejects").add(1)
        obs.counter(f"ps_parse_rejects_{method}").add(1)


def _record_ps_server(shard_index: int, method: str, count: int,
                      req_len: int, rsp_len: int, t0: int) -> None:
    """PS-side counters: keys/s, bytes in/out, per-shard handler latency
    (the ``add_service`` trampoline separately records the full RPC
    latency; this recorder isolates the table work)."""
    obs.recorder(f"ps_server_shard{shard_index}_{method}").record(
        (time.monotonic_ns() - t0) / 1e9)
    obs.counter("ps_server_keys").add(count)
    obs.counter("ps_server_bytes_in").add(req_len)
    obs.counter("ps_server_bytes_out").add(rsp_len)


class _ExclusiveAsRw:
    """Presents a plain mutex through the ``read()``/``write()`` surface
    (the pre-parallel single-lock serving model — kept as the bench
    baseline for ``bench_ps.py``'s mutex-vs-rwlock comparison)."""

    __slots__ = ("_lock",)

    def __init__(self, lock):
        self._lock = lock

    def read(self):
        return self._lock

    def write(self):
        return self._lock


def _pack_lookup_req(owned: np.ndarray) -> bytearray:
    """Frame a Lookup request into ONE pre-sized buffer, written in place
    (the old ``struct.pack + tobytes + concat`` built three intermediate
    buffers per shard — measurable at 8-client fan-out even after the
    native read path).  The native call paths accept writable buffers
    zero-copy (:func:`rpc._req_ptr`)."""
    req = bytearray(4 + 4 * owned.size)
    struct.pack_into("<i", req, 0, owned.size)
    np.frombuffer(req, np.int32, owned.size, 4)[:] = owned
    return req


def _pack_apply_req(owned: np.ndarray, grads: np.ndarray) -> bytearray:
    """Frame an ApplyGrad request (count ++ ids ++ grads) into one
    pre-sized buffer — same discipline as :func:`_pack_lookup_req`."""
    n = owned.size
    req = bytearray(4 + 4 * n + 4 * grads.size)
    struct.pack_into("<i", req, 0, n)
    np.frombuffer(req, np.int32, n, 4)[:] = owned
    np.frombuffer(req, np.float32, grads.size, 4 + 4 * n)[:] = \
        grads.reshape(-1)
    return req


def _pack_deadline(deadline_us: int, body) -> bytearray:
    """Prefix a data-plane request with its deadline header (wire
    schema ``deadline_hdr``): magic ++ absolute wall-clock deadline in
    microseconds ++ the original body.  The magic sits above any
    legitimate count/length field, so stamped and bare framings never
    collide; servers (Python AND the native Lookup handler) peel it and
    shed expired work before touching the table."""
    out = bytearray(12 + len(body))
    struct.pack_into("<iq", out, 0, wire.DEADLINE_MAGIC, deadline_us)
    out[12:] = body
    return out


def _pack_deadline_rel(budget_us: int, body) -> bytearray:
    """The v2 deadline header (wire schema ``deadline_hdr_v2``):
    magic ++ RELATIVE budget in microseconds ++ the original body.
    Unlike the absolute-us form this makes no same-host/NTP wall-clock
    assumption — the server stamps ARRIVAL with its own clock and
    computes expiry as ``local_arrival + budget``, so only transit
    time (not clock skew) eats into the budget."""
    out = bytearray(12 + len(body))
    struct.pack_into("<iq", out, 0, wire.DEADLINE_MAGIC2, budget_us)
    out[12:] = body
    return out


def _peel_deadline_rel(payload):
    """Server half of the v2 header: read the relative budget and
    convert it to an ABSOLUTE local deadline at arrival time (the
    arrival stamp).  Downstream admission/drain checks then compare
    against the same local clock the stamp came from."""
    (budget_us,) = wire.read("<q", payload, 4, "deadline.budget")
    deadline_us = int(time.time() * 1e6) + budget_us
    return bytes(memoryview(payload)[12:]), deadline_us


def _unpack_deadline(payload):
    """Inverse of :func:`_pack_deadline`: returns ``(body,
    deadline_us)`` — ``(payload, 0)`` when no header is present.  A
    frame that DOES open with the magic must carry the full 12-byte
    header (guarded: truncation is a hostile frame, not a legacy
    one — no legitimate count field equals the magic).  The v2 magic
    (relative budget) dispatches to :func:`_peel_deadline_rel`, which
    arrival-stamps with the LOCAL clock."""
    if len(payload) < 4:
        return payload, 0
    (magic,) = struct.unpack_from("<i", payload, 0)
    if magic == wire.DEADLINE_MAGIC2:
        return _peel_deadline_rel(payload)
    if magic != wire.DEADLINE_MAGIC:
        return payload, 0
    (deadline_us,) = wire.read("<q", payload, 4, "deadline.us")
    return bytes(memoryview(payload)[12:]), deadline_us


def _admit_deadline(method: str, payload: bytes):
    """Deadline admission for one request: peel the optional header
    (absolute v1 or relative arrival-stamped v2) and SHED work whose
    propagated budget is already exhausted — before any parse, any
    lock, any table touch (``EDEADLINE``; the acceptance contract of
    the overload tier).  Counted per method in
    ``ps_deadline_drops[_<Method>]``; the server span carries a
    ``shed=deadline`` rpcz tag via the trampoline.  Returns ``(body,
    deadline_us)`` — the surviving LOCAL absolute deadline rides into
    the combiner so work whose budget dies in the combine queue sheds
    again at drain time."""
    body, deadline_us = _unpack_deadline(payload)
    if deadline_us > 0 and time.time() * 1e6 > deadline_us:
        if obs.enabled():
            obs.counter("ps_deadline_drops").add(1)
            obs.counter(f"ps_deadline_drops_{method}").add(1)
        raise rpc.RpcError(
            resilience.EDEADLINE,
            f"{method}: propagated deadline budget exhausted before "
            f"the handler started")
    return body, deadline_us


#: stream frame header: (seq, epoch, gen) int64 — StreamApply uses seq
#: (per-writer dedup window), ReplicaApply uses epoch (fencing) + gen
#: (in-order install / dedup); unused fields are 0.
_FRAME_HDR = struct.Struct("<qqq")


def _pack_stream_frame(seq: int, epoch: int, gen: int,
                       body) -> bytearray:
    """One framed stream message: header + ApplyGrad-framed body, built
    into a single pre-sized buffer (same discipline as the request
    packers)."""
    out = bytearray(_FRAME_HDR.size + len(body))
    _FRAME_HDR.pack_into(out, 0, seq, epoch, gen)
    out[_FRAME_HDR.size:] = body
    return out


# --- zero-copy framings (brt_iobuf) ------------------------------------
# Byte-identical on the wire to the bytearray packers above (the
# wire-contract registry claims them under the same schemas), but the
# payload rides as BORROWED blocks: the few-byte header is the only copy.
# Runtime-switchable so the zerocopy bench can measure the copy path as
# its baseline in the same process.

_zerocopy = [True]


#: borrow-path engagement floor: below this payload size the per-call
#: handle lifecycle (new/pin/destroy + finalizers) costs more than the
#: memcpys it saves (bench_zerocopy's 16-byte cell measures the
#: crossover), so small unary legs stay on the bytes path.  The RPC
#: tier enforces the same floor for explicit IOBuf callers
#: (rpc.IOBUF_MIN_BYTES routes sub-floor payloads to the bytes twin),
#: so the two crossovers are one constant.
_ZC_MIN_BYTES = rpc.IOBUF_MIN_BYTES


def zerocopy_enabled() -> bool:
    """True when the PS hot paths frame through borrowed IOBuf blocks
    instead of copying into request buffers."""
    return _zerocopy[0] and rpc.native_core_available()


def set_zerocopy(on: bool) -> bool:
    """Flips the zero-copy hot paths (returns the previous setting) —
    the A/B switch for ``bench_zerocopy``."""
    prev = _zerocopy[0]
    _zerocopy[0] = bool(on)
    return prev


def _pack_lookup_req_iobuf(owned: np.ndarray) -> "rpc.IOBuf":
    """Zero-copy ``lookup_req`` framing: the 4-byte count header is the
    only copied byte span — the ids array itself is appended as a
    borrowed block (pinned until the wire write drains)."""
    ids = np.ascontiguousarray(owned, np.int32)
    io = rpc.IOBuf()
    io.append(struct.pack("<i", ids.size))
    io.append_pinned(ids)
    return io


def _pack_apply_req_iobuf(owned: np.ndarray,
                          grads: np.ndarray) -> "rpc.IOBuf":
    """Zero-copy ``apply_req`` framing: count header owned, ids and
    grads borrowed."""
    ids = np.ascontiguousarray(owned, np.int32)
    g = np.ascontiguousarray(grads, np.float32).reshape(-1)
    io = rpc.IOBuf()
    io.append(struct.pack("<i", ids.size))
    io.append_pinned(ids)
    io.append_pinned(g)
    return io


def _pack_stream_frame_iobuf(seq: int, epoch: int, gen: int,
                             body) -> "rpc.IOBuf":
    """Zero-copy ``stream_frame`` framing: 24-byte header owned, body
    borrowed (bytes) or block-shared (:class:`rpc.IOBuf`)."""
    io = rpc.IOBuf()
    io.append(struct.pack("<qqq", seq, epoch, gen))
    if isinstance(body, rpc.IOBuf):
        io.append_iobuf(body)
    elif len(body):
        io.append_pinned(body)
    return io


def _pack_deadline_iobuf(deadline_us: int, body) -> "rpc.IOBuf":
    """Zero-copy ``deadline_hdr`` framing: the 12-byte header becomes a
    PREPENDED owned block and the body's blocks are shared — stamping a
    deadline no longer re-copies the whole request."""
    io = rpc.IOBuf()
    io.append(struct.pack("<iq", wire.DEADLINE_MAGIC, deadline_us))
    if isinstance(body, rpc.IOBuf):
        io.append_iobuf(body)
    elif len(body):
        io.append_pinned(body)
    return io


def _pack_deadline_rel_iobuf(budget_us: int, body) -> "rpc.IOBuf":
    """Zero-copy ``deadline_hdr_v2`` framing (relative budget): header
    owned, body shared/borrowed."""
    io = rpc.IOBuf()
    io.append(struct.pack("<iq", wire.DEADLINE_MAGIC2, budget_us))
    if isinstance(body, rpc.IOBuf):
        io.append_iobuf(body)
    elif len(body):
        io.append_pinned(body)
    return io


def _pack_windows(windows: Dict[str, int]) -> bytes:
    """Writer seq high-water map on the wire: ``int32 count`` ++ per
    entry ``int32 len ++ writer utf8 ++ int64 seq``.  Rides every
    ``ReplicaApply`` frame and the ``Sync`` payload so a promoted backup
    inherits the dedup window — replay idempotence must survive
    failover, not just reconnect-to-the-same-primary."""
    parts = [struct.pack("<i", len(windows))]
    for w, seq in windows.items():
        wb = w.encode()
        parts.append(struct.pack("<i", len(wb)) + wb
                     + struct.pack("<q", seq))
    return b"".join(parts)


def _unpack_windows(payload, offset: int = 0):
    """Inverse of :func:`_pack_windows`: returns ``(windows, end)``.
    Guarded (wire schema ``windows``): the entry count is bounded by the
    bytes actually present (min 12/entry) and every writer length is
    span-checked, so a hostile count can neither drive an unbounded loop
    nor walk the read off the payload."""
    (count,) = wire.read("<i", payload, offset, "windows.count")
    offset += 4
    wire.check_count(count, (len(payload) - offset) // 12,
                     "windows.count")
    windows: Dict[str, int] = {}
    for _ in range(count):
        (wlen,) = wire.read("<i", payload, offset, "windows.wlen")
        offset += 4
        # check_count, not need: a NEGATIVE length passes a `wlen + 8`
        # span check and walks the offset backwards
        wire.check_count(wlen, len(payload) - offset - 8,
                         "windows.wlen")
        w = bytes(payload[offset:offset + wlen]).decode(errors="replace")
        offset += wlen
        (seq,) = struct.unpack_from("<q", payload, offset)
        offset += 8
        windows[w] = seq
    return windows, offset


def _pack_apply_id_req(writer: str, seq: int, guards, owned: np.ndarray,
                       grads: np.ndarray) -> bytearray:
    """Frame an ``ApplyGradId`` request: the idempotent unary write.
    Header = writer key + per-(writer, shard) monotonic seq (the same
    high-water machinery as the framed push — a timed-out-but-applied
    attempt that retries is dropped server-side) + optional GUARDS:
    each names a superseded frame ``(key, seq)`` from a retired
    partition scheme that fully contained this delta — if the server's
    inherited applied window already covers a guard, the delta migrated
    here with the old shard's data and must not apply twice."""
    wb = writer.encode()
    guards = list(guards or ())
    gsz = sum(4 + len(k.encode()) + 8 for k, _ in guards)
    body = _pack_apply_req(owned, grads)
    req = bytearray(4 + len(wb) + 8 + 4 + gsz + len(body))
    struct.pack_into("<i", req, 0, len(wb))
    off = 4
    req[off:off + len(wb)] = wb
    off += len(wb)
    struct.pack_into("<qi", req, off, seq, len(guards))
    off += 12
    for k, q in guards:
        kb = k.encode()
        struct.pack_into("<i", req, off, len(kb))
        off += 4
        req[off:off + len(kb)] = kb
        off += len(kb)
        struct.pack_into("<q", req, off, q)
        off += 8
    req[off:] = body
    return req


def _unpack_apply_id(payload):
    """Inverse of :func:`_pack_apply_id_req`: returns
    ``(writer, seq, guards, apply_body)``.  Guarded (wire schema
    ``apply_id_req``): writer/guard-key lengths are span-checked and the
    guard count is bounded by the bytes present (min 12/guard) before
    any loop runs."""
    (wlen,) = wire.read("<i", payload, 0, "apply_id.wlen")
    off = 4
    wire.check_count(wlen, len(payload) - off - 12, "apply_id.wlen")
    writer = bytes(payload[off:off + wlen]).decode(errors="replace")
    off += wlen
    seq, nguards = struct.unpack_from("<qi", payload, off)
    off += 12
    wire.check_count(nguards, (len(payload) - off) // 12,
                     "apply_id.nguards")
    guards = []
    for _ in range(nguards):
        (klen,) = wire.read("<i", payload, off, "apply_id.klen")
        off += 4
        wire.check_count(klen, len(payload) - off - 8, "apply_id.klen")
        key = bytes(payload[off:off + klen]).decode(errors="replace")
        off += klen
        (q,) = struct.unpack_from("<q", payload, off)
        off += 8
        guards.append((key, q))
    return writer, seq, guards, memoryview(payload)[off:]


def _unpack_apply(payload: bytes, base: int, rows_per: int, dim: int):
    """Parse + validate one ApplyGrad-framed delta (unary request body or
    stream frame): returns ``(local_ids, grads[count, dim])``.  Raises
    ``ValueError`` on out-of-range ids BEFORE anything is enqueued, so a
    bad contribution can never poison a combined batch.  The count is
    guarded first (wire schema ``apply_req``): a negative count would
    make ``np.frombuffer`` silently re-interpret the whole payload
    (``count=-1`` means "read everything" to numpy — garbage ids AND
    garbage grads that can pass the range check), and an oversized one
    must reject cleanly instead of surfacing numpy internals."""
    (count,) = wire.read("<i", payload, 0, "apply.count")
    wire.check_count(count, (len(payload) - 4) // (4 + 4 * dim),
                     "apply.count")
    ids = np.frombuffer(payload, np.int32, count, 4) - base
    if ids.size and (ids.min() < 0 or ids.max() >= rows_per):
        raise ValueError(
            f"ids outside shard [{base}, {base + rows_per}) "
            f"for shard base {base}")
    grads = np.frombuffer(payload, np.float32, count * dim, 4 + 4 * count)
    return ids, grads.reshape(count, dim)


class GradCombiner:
    """Per-shard server-side write combiner (the execution-queue
    write-combining shape, cpp/fiber/execution_queue.h, applied to
    gradient application).

    ApplyGrad contributions ENQUEUE here instead of applying
    individually; whoever finds the combiner idle becomes the LEADER and
    drains every pending contribution into ONE concatenated application
    per drained batch — ``apply_fn`` runs once per batch, so write-lock
    hold time, snapshot installs (CPU shard) and scatter launches (device
    shard) are paid per BATCH, not per request.  Duplicate-id
    contributions sum exactly: both ``np.subtract.at`` and the device
    scatter (``unique_indices = false``) accumulate repeated indices, so
    concatenation IS the combine — commutative, order-independent up to
    float addition order.

    ``add(wait=True)`` (unary handlers) blocks until the caller's batch
    is applied and re-raises the batch's failure; ``add(wait=False)``
    (stream frames — no per-frame response exists) returns immediately,
    and :meth:`flush` provides the "everything before this point is
    applied" barrier by riding the queue as an empty contribution.
    Followers never lead and the leader never waits on followers, so
    there is no circular wait even on a single worker."""

    __slots__ = ("_apply", "_dim", "_mu", "_q", "_draining", "_shut",
                 "_pass_meta", "last_error")

    def __init__(self, apply_fn, dim: int, pass_meta: bool = False):
        self._apply = apply_fn          # apply_fn(local_ids, grads): ONE
        self._dim = dim                 # combined application
        self._mu = checked_lock("ps.combine")
        self._q: list = []
        self._draining = False
        self._shut = False
        # pass_meta: apply_fn(ids, grads, metas) — the drained batch's
        # per-contribution (writer, seq) tags ride along, so a
        # replicated shard can propagate its applied dedup window with
        # the batch it belongs to (never ahead of the data).
        self._pass_meta = bool(pass_meta)
        self.last_error: Optional[BaseException] = None

    def add(self, ids: np.ndarray, grads: np.ndarray,
            wait: bool = True, meta=None, deadline_us: int = 0) -> None:
        # [ids, grads, done-event, error, meta, deadline_us] — error is
        # filled by whichever leader applies the batch this entry lands
        # in.  deadline_us > 0 re-checks at DRAIN time: a contribution
        # whose propagated budget died while queued behind a slow batch
        # is dropped, not applied (the admission check alone cannot see
        # queueing inside the combiner — the PR-12 deferral).
        entry = [ids, grads, threading.Event() if wait else None, None,
                 meta, deadline_us]
        with self._mu:
            if self._shut:
                # Server teardown: late contributions (a dead client's
                # stream receiver being torn down by the socket-failure
                # hook, frames still in its delivery queue) are dropped —
                # the shard/device behind apply_fn may already be gone.
                return
            self._q.append(entry)
            leader = not self._draining
            if leader:
                self._draining = True
        if not leader:
            ev = entry[2]
            if ev is not None:
                ev.wait()
                if entry[3] is not None:
                    raise entry[3]
            return
        self._drain()
        if entry[3] is not None:
            raise entry[3]

    def _drain(self) -> None:
        """Leader loop: drain batches until the queue is empty (entries
        enqueued while a batch applies land in the next one)."""
        while True:
            with self._mu:
                batch = self._q
                if not batch:
                    self._draining = False
                    return
                self._q = []
            # Drain-time deadline shedding: a deadline that expired
            # while the entry sat in the combine queue must not apply —
            # its caller's budget is gone and a late mutation is worse
            # than a clean EDEADLINE (the answer is already too late,
            # the write would still burn the lock/snapshot).
            now_us = time.time() * 1e6
            expired = []
            live = []
            for e in batch:
                (expired if 0 < e[5] < now_us else live).append(e)
            if expired:
                batch = live
                if obs.enabled():
                    obs.counter("ps_deadline_drops").add(len(expired))
                    obs.counter("ps_deadline_drops_Drain").add(
                        len(expired))
                shed_err = rpc.RpcError(
                    resilience.EDEADLINE,
                    "propagated deadline budget exhausted in the "
                    "combine queue; contribution shed at drain")
                for e_ in expired:
                    e_[3] = shed_err
                    if e_[2] is not None:
                        e_[2].set()
                if not batch:
                    continue
            err: Optional[BaseException] = None
            try:
                if len(batch) == 1:
                    ids, grads = batch[0][0], batch[0][1]
                else:
                    ids = np.concatenate([e[0] for e in batch])
                    grads = np.concatenate([e[1] for e in batch])
                if ids.size:
                    if self._pass_meta:
                        self._apply(ids, grads,
                                    [e[4] for e in batch
                                     if e[4] is not None])
                    else:
                        self._apply(ids, grads)
                    if obs.enabled():
                        obs.counter("ps_combined_applies").add(1)
                        obs.counter("ps_combined_keys").add(int(ids.size))
                        obs.maxer("ps_combine_depth").update(len(batch))
            except Exception as e:  # noqa: BLE001 — delivered per entry
                err = e
                with self._mu:
                    self.last_error = e
                if obs.enabled():
                    obs.counter("ps_combine_errors").add(1)
            for e_ in batch:
                e_[3] = err
                if e_[2] is not None:
                    e_[2].set()

    def flush(self) -> None:
        """Returns once every contribution enqueued BEFORE this call has
        been applied (the stream-close barrier).  Raises the failure of
        the flush batch, if any.  A no-op after :meth:`shutdown`."""
        self.add(np.empty(0, np.int32),
                 np.empty((0, self._dim), np.float32), wait=True)

    def shutdown(self) -> None:
        """Stops accepting contributions and waits for any in-flight
        drain to finish.  Server close paths call this BEFORE destroying
        the table/shard/device behind ``apply_fn``, so a drain can never
        race resource teardown — late frames from dying streams are
        dropped instead of applied to freed state."""
        with self._mu:
            self._shut = True
            draining = self._draining
        while draining:
            time.sleep(0.001)
            with self._mu:
                draining = self._draining


class _ApplyStreamReceiver:
    """Server half of the streaming gradient push: each frame is one
    ApplyGrad-framed delta fed straight into the shard's combiner (no
    per-frame response).  Runs serialized on the stream's native
    delivery fiber — a combiner drain happening here delays the
    consumed-bytes feedback, which is exactly how server-side apply cost
    back-pressures the pushing trainer.  ``on_closed`` flushes the
    combiner (and, on a replicated primary, waits for backup acks)
    BEFORE the server's half closes, so a client's ``close(); join()``
    is an "every pushed delta is applied everywhere" barrier.

    ``writer`` non-empty = the framed mode: every frame carries a
    ``(seq, 0, 0)`` header and the server's per-writer monotonic seq
    window drops replays (reconnect-after-partial-write ships the same
    frame twice at most; the window makes the second a no-op instead of
    a double apply).  Empty writer = the legacy unframed mode.

    FENCING is re-checked per frame, not just at stream setup: a
    primary demoted while a push stream is up must not keep applying
    frames locally (the new primary's Sync would overwrite them — an
    acked-then-lost write).  A frame landing on a demoted server is
    DROPPED without reserving its seq, a fence notification (a negative
    int64) is written on the reply half, and the reply closes to break
    the stream — the pushing client fails over and replays; the dropped
    frame's seq stays below every replica's window so the replay
    applies."""

    __slots__ = ("_server", "_writer", "reply", "_fenced")

    def __init__(self, server, writer: str = ""):
        self._server = server
        self._writer = writer
        self.reply: "Optional[rpc.Stream]" = None
        self._fenced = False

    def _demoted(self) -> bool:
        fenced = getattr(self._server, "_stream_write_fenced", None)
        return fenced is not None and fenced()

    def _fence(self) -> None:
        """Mark this stream fenced and tell the client: a negative ack
        frame (-1 = replica demotion, -2 = the partition scheme was
        retired by a cutover), then break the stream so the next write
        fails over / refreshes its scheme."""
        if self._fenced:
            return
        self._fenced = True
        if obs.enabled():
            obs.counter("ps_stream_fenced").add(1)
        if self.reply is not None:
            code = -2 if getattr(self._server, "_scheme_fenced", False) \
                else -1
            try:
                self.reply.write(struct.pack("<q", code))
            except rpc.RpcError:
                pass   # client gone; its reconnect pays ENOTPRIMARY
            self.reply.close()

    def on_data(self, data: bytes) -> None:
        if self._fenced:
            return
        if self._demoted():
            self._fence()
            return
        try:
            if not self._writer:
                self._server._apply_frame(data)
                return
            if len(data) < _FRAME_HDR.size:
                raise wire.WireError(
                    f"stream frame shorter than its header "
                    f"({len(data)} < {_FRAME_HDR.size})")
            seq, _epoch, _gen = _FRAME_HDR.unpack_from(data, 0)
            if not self._server._reserve_seq(self._writer, seq):
                if obs.enabled():
                    obs.counter("ps_stream_dedup_drops").add(1)
                return
            self._server._apply_frame(memoryview(data)[_FRAME_HDR.size:],
                                      (self._writer, seq))
        except wire.WireError:
            # Frames have no response channel: a malformed frame is
            # counted and DROPPED — it must not kill the receiver or
            # poison the combiner.
            _reject_frame("StreamApply")

    def on_closed(self) -> None:
        try:
            self._server._combiner.flush()
            self._server.flush_replication()
        except rpc.RpcError:
            # ENOTPRIMARY from a demotion racing the drain, or EFENCED
            # from the replication barrier: the close must not read as
            # an "applied everywhere" ack.
            self._fence()
            return
        if self._demoted():
            self._fence()


class _ReplicaStreamReceiver:
    """Backup half of primary→backup delta propagation: each frame is
    one applied batch, epoch-fenced and generation-tagged.  Frames apply
    IN ORDER (the stream is ordered and this receiver is serialized), so
    after any prefix the backup's table is byte-identical to the
    primary's table at that generation — same concatenated batches, same
    ``subtract.at`` order, same float ops.  ``reply`` is the server half
    of the stream: every processed frame acks the backup's current
    generation back to the primary (the server→client direction), which
    is what the primary's flush barrier waits on."""

    __slots__ = ("_server", "reply")

    def __init__(self, server):
        self._server = server
        self.reply: "Optional[rpc.Stream]" = None

    def on_data(self, data: bytes) -> None:
        try:
            if len(data) < _FRAME_HDR.size:
                raise wire.WireError(
                    f"ReplicaApply frame shorter than its header "
                    f"({len(data)} < {_FRAME_HDR.size})")
            _seq, epoch, gen = _FRAME_HDR.unpack_from(data, 0)
            acked = self._server._apply_replica_frame(
                epoch, gen, memoryview(data)[_FRAME_HDR.size:])
        except wire.WireError:
            # A malformed propagation frame means the stream itself is
            # corrupt: count it and break the stream so the primary
            # reconnects through a full Sync (same treatment as a gap).
            _reject_frame("ReplicaApply")
            acked = None
        if acked is None:
            # Gap: break the stream so the primary reconnects through a
            # full sync instead of streaming into divergence.
            if self.reply is not None:
                self.reply.close()
            return
        if self.reply is not None:
            try:
                # negative = FENCE notification (acked is -epoch): the
                # sender is stale — tell it synchronously so an
                # in-flight flush fails with EFENCED instead of a
                # write being acked by a zombie, then break the stream.
                self.reply.write(struct.pack("<q", acked))
            except rpc.RpcError:
                pass  # primary gone; its reconnect re-learns the gen
            if acked < 0:
                self.reply.close()

    def on_closed(self) -> None:
        pass


class _ReplicaAckReceiver:
    """Primary-side read half of a propagation stream: collects the
    backup's per-frame generation acks."""

    __slots__ = ("_replicator", "_addr")

    def __init__(self, replicator, addr: str):
        self._replicator = replicator
        self._addr = addr

    def on_data(self, data: bytes) -> None:
        if len(data) < 8:
            _reject_frame("ReplicaAck")
            return
        (gen,) = struct.unpack_from("<q", data, 0)
        if gen < 0:   # fence notification: a newer primary exists
            self._replicator._note_fenced(self._addr)
            return
        self._replicator._note_ack(self._addr, gen)

    def on_closed(self) -> None:
        self._replicator._note_closed(self._addr)


class _MigrateStreamReceiver:
    """Import half of a live reshard on the DESTINATION shard: each
    frame is one source-shard applied batch FILTERED to this shard's
    row range (global ids; the ``ReplicaApply`` framing with the
    source's generation in the header), applied in arrival order —
    the stream is ordered and this receiver serialized, so per source
    the destination replays the source's exact float ops on the
    migrated rows.  Every processed frame acks the source-generation
    watermark back on the reply half (what the source's cutover flush
    waits on); a frame arriving after the import completed is refused
    (``None``) and the stream breaks — the source's resync attempt
    then fails loudly with ESCHEMEMOVED instead of silently diverging."""

    __slots__ = ("_server", "_src", "reply")

    def __init__(self, server, src: str):
        self._server = server
        self._src = src
        self.reply: "Optional[rpc.Stream]" = None

    def on_data(self, data: bytes) -> None:
        try:
            if len(data) < _FRAME_HDR.size:
                raise wire.WireError(
                    f"MigrateApply frame shorter than its header "
                    f"({len(data)} < {_FRAME_HDR.size})")
            gen, _scheme, _gen2 = _FRAME_HDR.unpack_from(data, 0)
            acked = self._server._apply_migrate_frame(
                self._src, gen, memoryview(data)[_FRAME_HDR.size:])
        except wire.WireError:
            # Same contract as the replica receiver: a malformed handoff
            # frame breaks the stream so the source resyncs wholesale.
            _reject_frame("MigrateApply")
            acked = None
        if acked is None:
            if self.reply is not None:
                self.reply.close()
            return
        if self.reply is not None:
            try:
                self.reply.write(struct.pack("<q", acked))
            except rpc.RpcError:
                pass  # source gone; its reconnect re-syncs the range

    def on_closed(self) -> None:
        pass


class _PeerState:
    """One backup's propagation state (owned by its worker thread; the
    queue/ack fields are shared under the replicator lock)."""

    __slots__ = ("addr", "queue", "wake", "stream", "synced_gen",
                 "acked_gen", "need_sync", "fenced", "down")

    def __init__(self, addr: str):
        self.addr = addr
        self.queue: collections.deque = collections.deque()
        self.wake = threading.Event()
        self.stream: "Optional[rpc.Stream]" = None
        self.synced_gen = -1     # -1 = never connected
        self.acked_gen = 0
        self.need_sync = True
        self.fenced = False
        # True after a failed connect attempt (network, not fencing):
        # the ack barrier skips an unreachable peer — its eventual
        # reconnect resyncs the FULL table, so nothing shipped while it
        # was down can be lost, only delayed.
        self.down = False


class _Replicator:
    """Primary-side delta propagation: one worker thread per backup
    ships every applied batch, in generation order, over a persistent
    ``ReplicaApply`` stream (reconnect → full ``Sync`` first, so a gap
    can never stream into divergence).  ``ship`` is an append under the
    lock — the applying writer never blocks on a slow backup; a backup
    that falls more than ``max_queue`` batches behind is resynced
    wholesale instead of queueing unboundedly.  ``flush(target_gen)``
    waits until every un-fenced backup has ACKED ``target_gen`` (acks
    ride the server→client half of the stream) — the zero-lost-updates
    barrier.  An EFENCED from any backup means a newer primary exists:
    the owner demotes itself and every worker stops.

    QUORUM mode (``quorum`` = the total number of replicas, primary
    included, that must hold a write before it acks): ``flush`` waits
    until ``quorum - 1`` backups acked ``target_gen`` — and unlike the
    legacy connected-only barrier it does NOT skip a disconnected peer:
    a bootstrap write blocks until real acks exist, which is what
    closes the PR-9 single-fault loss window (an acked write on
    ``quorum`` replicas intersects every majority promotion sweep, so
    the client's acked-gen floor becomes a guarantee instead of a
    refusal heuristic)."""

    def __init__(self, server, peers: Sequence[str], epoch: int,
                 max_queue: int = 512, timeout_ms: int = 5000,
                 quorum: Optional[int] = None):
        self._server = server
        self.epoch = epoch
        self.max_queue = max_queue
        self.timeout_ms = timeout_ms
        if quorum is not None and not 1 <= quorum <= len(peers) + 1:
            raise ValueError(
                f"quorum {quorum} outside [1, {len(peers) + 1}] for "
                f"{len(peers)} backup(s)")
        self.quorum = quorum
        #: hydrate-first (re)connect: when the owning server has a
        #: checkpoint store attached, a peer already inside the store's
        #: delta window gets the TAIL instead of a wholesale Sync
        self.hydrate = True
        self._mu = checked_lock("ps.replicate")
        self._stop = threading.Event()
        # True when stopped BECAUSE of a fence/demotion: an in-flight
        # flush must raise EFENCED (the new primary's Sync will wipe the
        # batch), never break out as success.
        self._demoted = False
        self._ack_ev = threading.Event()
        self._chans: Dict[str, rpc.Channel] = {}
        self._peers = [_PeerState(a) for a in peers]
        self._threads: List[threading.Thread] = []
        for p in self._peers:
            t = threading.Thread(target=self._worker, args=(p,),
                                 daemon=True,
                                 name=f"brt-replicate-{p.addr}")
            t.start()
            self._threads.append(t)

    # -- the apply path's side (non-blocking) -----------------------------

    def ship(self, gen: int, body) -> None:
        """Enqueue one applied batch (already ApplyGrad-framed with
        GLOBAL ids) for every backup.  Called under the shard write lock
        — append-only, never blocks on the network."""
        frame = bytes(_pack_stream_frame(gen, self.epoch, gen, body))
        with self._mu:
            for p in self._peers:
                p.queue.append((gen, frame))
                if len(p.queue) > self.max_queue:
                    # Hopelessly behind: resync wholesale on reconnect
                    # rather than holding every batch in memory.
                    p.queue.clear()
                    p.need_sync = True
        for p in self._peers:
            p.wake.set()
        if obs.enabled():
            obs.counter("ps_replica_frames").add(len(self._peers))
            obs.counter("ps_replica_bytes").add(
                len(frame) * len(self._peers))

    # -- ack plumbing ------------------------------------------------------

    def _note_ack(self, addr: str, gen: int) -> None:
        with self._mu:
            for p in self._peers:
                if p.addr == addr and gen > p.acked_gen:
                    p.acked_gen = gen
        self._ack_ev.set()

    def _note_closed(self, addr: str) -> None:
        with self._mu:
            for p in self._peers:
                if p.addr == addr:
                    p.need_sync = True
        self._ack_ev.set()

    def _note_fenced(self, addr: str) -> None:
        """A backup refused a frame with a FENCE notification: a newer
        primary exists.  Fail any in-flight flush with EFENCED and
        demote the owner."""
        with self._mu:
            for p in self._peers:
                if p.addr == addr:
                    p.fenced = True
        self._ack_ev.set()
        self._server._demote_on_fence()

    def acked_gens(self) -> Dict[str, int]:
        with self._mu:
            return {p.addr: p.acked_gen for p in self._peers}

    def resync_peers(self, hydrate: Optional[bool] = None) -> None:
        """Force every backup through a resync.  With a checkpoint
        store attached (and ``hydrate`` mode on) the reconnect tries
        hydrate-first: a backup whose generation still sits inside the
        store's delta window receives only the tail; anyone else — and
        every backup after a ``MigrateSync`` range install, which
        re-bases the store — falls through to the full-table ``Sync``
        of the current state.  ``hydrate`` (when not None) stickily
        switches the mode."""
        if hydrate is not None:
            self.hydrate = bool(hydrate)
        with self._mu:
            for p in self._peers:
                p.queue.clear()
                p.need_sync = True
        for p in self._peers:
            p.wake.set()

    def flush(self, target_gen: int, timeout_s: float = 5.0) -> None:
        """The ack barrier.  QUORUM mode (``quorum`` set): returns once
        this primary plus ``quorum - 1`` backups hold ``target_gen`` —
        a disconnected peer is NOT skipped, the write waits for real
        acks (or fails loudly).  Legacy mode: returns once every
        CONNECTED backup acked ``target_gen``; a peer without an
        established delta stream (never synced, mid resync, or
        unreachable) is skipped — its (re)connect starts with a full
        ``Sync`` of the current table, so skipping delays its copy
        without losing updates.  Raises ERPCTIMEDOUT naming the laggard
        on timeout, EFENCED if a newer primary fenced this one
        mid-flush."""
        if self.quorum is not None:
            self._flush_quorum(target_gen, timeout_s)
            return
        deadline = time.monotonic() + timeout_s
        for p in self._peers:
            while True:
                with self._mu:
                    acked, fenced = p.acked_gen, p.fenced
                    live = (p.stream is not None and not p.need_sync
                            and not p.down)
                if fenced or self._demoted:
                    raise rpc.RpcError(
                        resilience.EFENCED,
                        f"fenced by a newer primary while flushing "
                        f"to {p.addr}")
                if acked >= target_gen or not live or \
                        self._stop.is_set():
                    break
                if time.monotonic() > deadline:
                    raise rpc.RpcError(
                        1008, f"replica {p.addr} acked gen {acked} < "
                              f"{target_gen} within {timeout_s:.1f}s")
                self._ack_ev.clear()
                with self._mu:
                    if p.acked_gen >= target_gen:
                        break
                self._ack_ev.wait(0.005)

    def _flush_quorum(self, target_gen: int, timeout_s: float) -> None:
        """Majority-ack barrier: blocks until ``quorum - 1`` backups
        acked ``target_gen`` (this primary is the remaining voter).
        Never skips a disconnected peer — with the quorum unreachable
        the write FAILS after ``timeout_s`` instead of acking on the
        primary alone (loud unavailability over silent loss)."""
        need = self.quorum - 1
        deadline = time.monotonic() + timeout_s
        while True:
            with self._mu:
                acked = sum(1 for p in self._peers
                            if p.acked_gen >= target_gen)
                fenced = any(p.fenced for p in self._peers)
            if fenced or self._demoted:
                raise rpc.RpcError(
                    resilience.EFENCED,
                    f"fenced by a newer primary while awaiting quorum "
                    f"for gen {target_gen}")
            if acked >= need:
                return
            if self._stop.is_set():
                raise rpc.RpcError(
                    1008,
                    f"replicator stopped before gen {target_gen} "
                    f"reached quorum ({acked + 1}/{self.quorum})")
            if time.monotonic() > deadline:
                raise rpc.RpcError(
                    1008,
                    f"quorum {self.quorum} not reached for gen "
                    f"{target_gen} within {timeout_s:.1f}s "
                    f"({acked + 1}/{self.quorum} hold it; acked "
                    f"{self.acked_gens()})")
            self._ack_ev.clear()
            with self._mu:
                if sum(1 for p in self._peers
                       if p.acked_gen >= target_gen) >= need:
                    return
            self._ack_ev.wait(0.005)

    # -- per-backup worker -------------------------------------------------

    def _channel(self, addr: str) -> rpc.Channel:
        ch = self._chans.get(addr)
        if ch is None:
            ch = rpc.Channel(addr, timeout_ms=self.timeout_ms)
            self._chans[addr] = ch
        return ch

    def _connect(self, p: _PeerState) -> bool:
        """Full-state handoff then a fresh delta stream: ``Sync`` ships
        a consistent (epoch, gen, table) snapshot — the backup installs
        it wholesale — and the stream resumes from that generation, so
        queued frames at or below it are ship-skipped (the backup would
        dedup them anyway)."""
        epoch, gen, table, windows = \
            self._server._replication_snapshot()
        ch = self._channel(p.addr)
        try:
            ch.call("Ps", "Sync",
                    struct.pack("<qqq", epoch, gen,
                                len(table) // 4) + table
                    + _pack_windows(windows),
                    timeout_ms=self.timeout_ms)
            st = ch.stream("Ps", "ReplicaApply",
                           struct.pack("<q", epoch),
                           receiver=_ReplicaAckReceiver(self, p.addr))
        except rpc.RpcError as e:
            if e.code == resilience.EFENCED:
                with self._mu:
                    p.fenced = True
                self._ack_ev.set()
                self._server._demote_on_fence()
                return False
            with self._mu:
                p.down = True   # unreachable: the ack barrier skips it
            self._ack_ev.set()
            if obs.enabled():
                obs.counter("ps_replica_connect_errors").add(1)
            return False
        with self._mu:
            p.stream = st
            p.synced_gen = gen
            p.need_sync = False
            p.down = False
            if gen > p.acked_gen:
                p.acked_gen = gen   # the Sync response IS the ack
        self._ack_ev.set()
        if obs.enabled():
            obs.counter("ps_replica_syncs").add(1)
            obs.counter("ps_replica_sync_bytes").add(len(table))
        return True

    def _try_hydrate(self, p: _PeerState) -> Optional[bool]:
        """Hydrate-first (re)connect: when the backup's current
        generation sits inside the checkpoint store's delta window,
        open the delta stream and ship only the missing TAIL from disk
        — the live table is never snapshotted or shipped.  Safe because
        within one epoch the generation sequence is a function of the
        primary's apply chain (the stream setup adopts our epoch or
        fences us), and a ``Promote``/wholesale install always re-bases
        the store, pushing any possibly-divergent peer out of the
        window.  Returns True on success, False on a hard failure
        (fenced/unreachable — the caller backs off), None to fall
        through to the wholesale ``_connect``."""
        store = getattr(self._server, "_durable", None)
        if store is None or not self.hydrate:
            return None
        ch = self._channel(p.addr)
        try:
            st = ch.stream("Ps", "ReplicaApply",
                           struct.pack("<q", self.epoch),
                           receiver=_ReplicaAckReceiver(self, p.addr))
        except rpc.RpcError as e:
            if e.code == resilience.EFENCED:
                with self._mu:
                    p.fenced = True
                self._ack_ev.set()
                self._server._demote_on_fence()
                return False
            with self._mu:
                p.down = True
            self._ack_ev.set()
            if obs.enabled():
                obs.counter("ps_replica_connect_errors").add(1)
            return False
        try:
            _peer_epoch, peer_gen, peer_seeded = wire.read(
                "<qqq", st.response, 0, "ReplicaApply.rsp")
        except wire.WireError:
            st.close()
            return None
        if peer_gen < 0 or (peer_gen == 0 and not peer_seeded):
            # A fresh backup's seed table is not provably this chain's
            # gen-0 image — only a wholesale Sync (or a restored
            # seeded checkpoint base, which the setup response's
            # seeded flag attests) may establish it.
            st.close()
            return None
        deltas = store.tail_since(peer_gen)
        if deltas is None or peer_gen > store.last_gen:
            # The peer predates the base — or claims a generation the
            # log never recorded (a divergent history): wholesale.
            st.close()
            return None
        last = peer_gen
        tail_bytes = 0
        if zerocopy_enabled():
            # Whole tail in one batched native crossing, delta bodies
            # borrowed rather than copied into frame bytes.
            batch = []
            try:
                for gen, body in deltas:
                    batch.append(_pack_stream_frame_iobuf(
                        gen, self.epoch, gen, body))
                    tail_bytes += len(batch[-1])
                    last = gen
                try:
                    st.writev(batch)
                except rpc.RpcError:
                    st.close()
                    return None   # died mid-tail: wholesale converges
            finally:
                for io in batch:
                    io.close()
        else:
            try:
                for gen, body in deltas:
                    frame = bytes(_pack_stream_frame(gen, self.epoch,
                                                     gen, body))
                    st.write(frame)
                    tail_bytes += len(frame)
                    last = gen
            except rpc.RpcError:
                st.close()
                return None   # stream died mid-tail: wholesale converges
        with self._mu:
            p.stream = st
            p.synced_gen = last
            p.need_sync = False
            p.down = False
            if peer_gen > p.acked_gen:
                p.acked_gen = peer_gen
        self._ack_ev.set()
        if obs.enabled():
            obs.counter("ps_replica_hydrates").add(1)
            obs.counter("ps_replica_hydrate_tail_bytes").add(tail_bytes)
        return True

    def _worker(self, p: _PeerState) -> None:
        backoff = resilience.Backoff(base_ms=5.0, max_ms=200.0)
        fails = 0
        while not self._stop.is_set():
            with self._mu:
                fenced = p.fenced
                item = p.queue[0] if (p.queue and not p.need_sync
                                      and p.stream is not None) else None
                # Eager: (re)connect whether or not anything is queued —
                # backups sync at boot/recovery time, not first-write
                # time, which shrinks the window where the ack barrier
                # has no established stream to wait on.
                need_connect = (not fenced
                                and (p.need_sync or p.stream is None))
            if fenced:
                return
            if need_connect:
                old, p.stream = p.stream, None
                if old is not None:
                    old.close()   # rx stream: close (abort strands relay)
                ok = self._try_hydrate(p)
                if ok is None:
                    ok = self._connect(p)
                if ok:
                    fails = 0
                else:
                    if self._stop.is_set() or p.fenced:
                        return
                    fails += 1
                    resilience.sleep_ms(backoff.delay_ms(min(fails, 6)))
                continue
            if item is None:
                p.wake.wait(0.05)
                p.wake.clear()
                continue
            gen, frame = item
            if gen <= p.synced_gen:
                with self._mu:
                    if p.queue and p.queue[0] is item:
                        p.queue.popleft()
                continue
            if zerocopy_enabled():
                # Drain the eligible head run in ONE native crossing —
                # queue gens are append-ordered, so once the head
                # clears ``synced_gen`` the whole run does.  Frame
                # bytes are pinned (not copied) by ``writev``.
                with self._mu:
                    batch = []
                    for it in p.queue:
                        if it[0] <= p.synced_gen:
                            break
                        batch.append(it)
                        if len(batch) >= 64:
                            break
                try:
                    p.stream.writev([it[1] for it in batch])
                except rpc.RpcError as e:
                    # frames before the break ARE on the wire: pop
                    # them so the resync does not re-ship
                    nw = getattr(e, "frames_written", 0)
                    st, p.stream = p.stream, None
                    if st is not None:
                        st.close()
                    with self._mu:
                        for it in batch[:nw]:
                            if p.queue and p.queue[0] is it:
                                p.queue.popleft()
                        p.need_sync = True
                    continue
                with self._mu:
                    for it in batch:
                        if p.queue and p.queue[0] is it:
                            p.queue.popleft()
                continue
            try:
                p.stream.write(frame)
            except rpc.RpcError:
                st, p.stream = p.stream, None
                if st is not None:
                    st.close()
                with self._mu:
                    p.need_sync = True
                continue  # frame stays queued; resync covers ordering
            with self._mu:
                if p.queue and p.queue[0] is item:
                    p.queue.popleft()

    def stop(self, join: bool = True, fenced: bool = False) -> None:
        """Stop propagation.  Channels/streams are closed only AFTER
        every worker exited: a worker can be mid-``ch.call`` on one of
        them, and closing the native channel under it is a
        use-after-free (the bring-up crash the churn bench found — a
        fence-driven ``stop(join=False)`` used to close the channel
        set while a sibling worker's Sync was still on the wire).
        ``join=False`` (and any call from a worker/receiver thread —
        ``_demote_on_fence`` runs on both) defers the teardown to a
        reaper thread instead of blocking the caller."""
        if fenced:
            self._demoted = True
        self._stop.set()
        self._ack_ev.set()
        for p in self._peers:
            p.wake.set()
        if join and threading.current_thread() not in self._threads:
            for t in self._threads:
                t.join(timeout=5)
            self.close()
        else:
            threading.Thread(target=self._reap, daemon=True,
                             name="brt-replicator-reaper").start()

    def _reap(self) -> None:
        for t in self._threads:
            if t is not threading.current_thread():
                t.join(timeout=5)
        self.close()

    def close(self) -> None:
        """Release the peer streams and channels.  Only safe once the
        workers exited — ``stop``/``_reap`` are the callers."""
        for p in self._peers:
            st, p.stream = p.stream, None
            if st is not None:
                st.close()
        for ch in self._chans.values():
            ch.close()
        self._chans.clear()


#: process-unique suffix for per-SERVER obs variables (two servers with
#: the same shard_index — a primary and its backup — must not pool their
#: tail-pressure signals)
_server_seq = itertools.count()


class PsShardServer:
    """One embedding shard behind a native RPC server.

    ``native_read=True`` serves ``Lookup`` with ZERO Python in the loop:
    a native generation-versioned shard (:class:`rpc.PsShard`) is
    attached to the same service, and the Python tier keeps the whole
    write path — ``ApplyGrad`` mutates the numpy table under the write
    lock, then publishes an immutable snapshot via ``install``.  Both
    paths serve ONE table; reads never see a torn row because snapshots
    are immutable and generation-pinned (the device shard's
    handle-generation scheme, moved into the native core).  Note that
    server-side fault injection and obs hooks live in the Python
    trampoline, so with ``native_read`` they apply to the write path
    only — the reference's position (SURVEY §3.1) is that the read hot
    path IS the native handler.

    Write-path scale (the read path's mirror image):

    - ``combine=True`` routes unary ApplyGrad through a
      :class:`GradCombiner` — concurrent writers' grads coalesce and the
      write lock / snapshot install is paid once per DRAINED BATCH
      instead of once per request (the dominant unary cost under
      ``native_read``, where every apply memcpy's the whole table).
    - ``stream=True`` additionally serves ``StreamApply``: a client
      opens an ordered flow-controlled stream (``Channel.stream`` /
      ``RemoteEmbedding.push_gradients``) and ships framed deltas at
      wire rate, no per-call dispatch; frames feed the combiner
      directly and the client's ``close(); join()`` barrier guarantees
      application.  Because the combiner sums duplicate ids exactly and
      float addition is commutative here, unary / combined / streamed
      orderings land byte-identical tables for exactly-representable
      gradients (proven in tests/test_ps_stream.py)."""

    #: data-plane methods gated by a spec-string limiter; control
    #: traffic (failover, migration, flush barriers) stays admissible
    #: under overload — shedding a Promote would turn an overload into
    #: an availability incident
    LIMITED_METHODS = ("Lookup", "ApplyGrad", "ApplyGradId")

    def __init__(self, vocab: int, dim: int, shard_index: int,
                 num_shards: int, lr: float = 0.1, seed: int = 0,
                 lock_mode: str = "rw", native_read: bool = False,
                 combine: bool = False, stream: bool = False,
                 importing: bool = False, scheme_version: int = 0,
                 limiter=None):
        if vocab % num_shards:
            raise ValueError("num_shards must divide vocab")
        self.shard_index = shard_index
        self.num_shards = num_shards
        self.rows_per = vocab // num_shards
        self.base = shard_index * self.rows_per
        self.dim = dim
        self.lr = lr
        rng = np.random.default_rng(seed + shard_index)
        self.table = (rng.standard_normal((self.rows_per, dim)) * 0.02
                      ).astype(np.float32)
        # Handlers run concurrently on fiber workers (the trampoline
        # releases the GIL, and numpy releases it again for big ops): a
        # Lookup gather racing an ApplyGrad scatter-sub on overlapping
        # rows reads torn updates.  Reads share, writes exclude: hot read
        # loads gather in parallel while ApplyGrad takes the write side.
        # lock_mode="mutex" restores the old fully-serialized model (the
        # bench baseline).
        if lock_mode == "rw":
            self._mu = checked_rwlock("ps.shard")
        elif lock_mode == "mutex":
            self._mu = _ExclusiveAsRw(checked_lock("ps.shard"))
        else:
            raise ValueError(f"unknown lock_mode {lock_mode!r}")
        self.native_read = bool(native_read)
        self.combine = bool(combine)
        self.stream = bool(stream)
        self._shard: "Optional[rpc.PsShard]" = None
        self._install_gen = 0
        # Replication state (configure_replication): fencing epoch,
        # whether THIS replica owns writes, the declared replica set, and
        # the primary-side propagation machinery.
        self._epoch = 0
        self._primary_flag = True
        self._replica_set: Optional[ReplicaSet] = None
        self._replica_index = 0
        self._replicator: Optional[_Replicator] = None
        #: resolved write-quorum size (replicas, primary included, that
        #: must hold a write before it acks); None = the legacy
        #: connected-backups-only barrier
        self._quorum: Optional[int] = None
        #: replicated migration spec (MigrateStart payload): a promoted
        #: source re-installs its shipper from this — the automatic
        #: re-drive that replaces the manual re-issued MigrateStart
        self._pending_migration: Optional[dict] = None
        #: attached checkpoint store (brpc_tpu.durable.CheckpointStore;
        #: None = volatile).  The apply paths tee every generation into
        #: it UNDER the table write lock — log order is apply order —
        #: and replica reconnects go hydrate-first through its tail.
        self._durable = None
        #: whether this table was established by the replication chain
        #: (a wholesale Sync landed, a seeded checkpoint base restored,
        #: or this node was promoted).  A PRIMARY is implicitly seeded
        #: — its table IS the chain origin — so consumers read
        #: ``self._seeded or self._primary_flag``.  This is what makes
        #: a gen-0 backup hydratable: without it, gen 0 could mean
        #: "fresh random-init table" just as well as "the chain's
        #: exact gen-0 image" (the PR-16 first-boot residue).
        self._seeded = False
        self._repl_mu = checked_lock("ps.repl_state")
        # Elastic-resharding state: which partition scheme this shard
        # belongs to, whether it is still IMPORTING its row range (a
        # split/merge destination before cutover — data paths answer
        # EMIGRATING until CompleteImport), whether its scheme was
        # retired by a fenced cutover (writes answer ESCHEMEMOVED — the
        # redirect that drives client scheme refresh), and the
        # primary-side migration shipper streaming this shard's rows to
        # the successor scheme (brpc_tpu.reshard.MigrationShipper).
        self.scheme_version = int(scheme_version)
        self._importing = bool(importing)
        self._scheme_fenced = False
        self._next_scheme: Optional[int] = None
        self._migrator = None
        #: per-source migration watermark: the source shard's generation
        #: covered by this import so far (guarded by the table WRITE
        #: lock — every mutation happens inside an apply/sync install)
        self._import_gens: Dict[str, int] = {}
        self._read_count = 0
        #: per-SERVER tail-pressure signals surfaced through SchemeInfo
        #: (uniquely named on purpose: the process-wide per-shard-index
        #: recorders blur same-index servers across schemes/replicas);
        #: dropped at close alongside the limiter gauges
        sid = next(_server_seq)
        self._sig_names = (f"ps_p99_shard{shard_index}_{sid}",
                           f"ps_sheds_shard{shard_index}_{sid}")
        self._lat = obs.recorder(self._sig_names[0])
        self._sheds = obs.counter(self._sig_names[1])
        #: last (sum_us, count) folded from the native Lookup path into
        #: self._lat — zero-Python reads never cross the Python recorder,
        #: so SchemeInfo drains the native counters (PsShard.lookup_stats)
        #: into it incrementally before reporting p99
        self._native_lat_seen = (0, 0)
        #: how long a replicated apply waits for backup acks before
        #: failing the write (sync replication among reachable replicas)
        self.repl_ack_timeout_s = 5.0
        #: per-call timeout for replication control traffic (Sync /
        #: stream setup to backups) — bounds how long a blackholed
        #: backup can stall the first flush before it is marked down
        self.repl_timeout_ms = 2000
        # Per-writer monotonic seq windows for idempotent stream replay:
        # _writer_seqs is the ADMISSION window (reserved at enqueue —
        # dedups replays on this server); _writer_applied trails it at
        # APPLY time and is what replication propagates (Sync +
        # per-frame), so a promoted backup inherits a window that never
        # claims a seq whose data it does not hold.
        self._seq_mu = checked_lock("ps.writer_seq")
        self._writer_seqs: Dict[str, int] = {}
        self._writer_applied: Dict[str, int] = {}
        # The combiner exists whenever anything feeds it: unary combining
        # (combine) or streamed deltas (stream — frames ALWAYS combine,
        # they have no per-frame response to serialize on).
        self._combiner: Optional[GradCombiner] = (
            GradCombiner(self._apply_batch, dim, pass_meta=True)
            if (self.combine or self.stream) else None)
        self.server = rpc.Server()
        # Overload control (brpc_tpu.limiter): a spec string ("auto" /
        # "constant:<n>") gates the DATA-PLANE methods with per-method
        # adaptive admission, and — under native_read — installs the
        # same policy as the NATIVE server-wide limiter so the
        # zero-Python Lookup path sheds too (both answer ELIMIT).  A
        # ready-built ServerLimiter passes through as-is (callers pick
        # their own method set / options / clock).
        self.limiter: Optional[ServerLimiter] = None
        self._gauge_names: tuple = ()
        if limiter is not None:
            if isinstance(limiter, str):
                self.limiter = ServerLimiter(
                    limiter, methods=self.LIMITED_METHODS,
                    counter_prefix="ps")
                if self.native_read:
                    name, _, arg = limiter.partition(":")
                    self.server.set_native_concurrency_limiter(
                        name, int(arg) if arg else 0)
            else:
                self.limiter = limiter
            self.server.set_concurrency_limiter(self.limiter)
            if obs.enabled():
                lim = self.limiter
                self._gauge_names = (
                    f"ps_inflight_shard{shard_index}",
                    f"ps_max_concurrency_shard{shard_index}")
                obs.gauge(self._gauge_names[0], lim.total_inflight)
                obs.gauge(self._gauge_names[1],
                          lambda: max(lim.max_concurrency().values(),
                                      default=0))
        # The trampoline is ALWAYS stream-capable: replica delta
        # propagation (ReplicaApply) rides a stream whether or not the
        # client-facing StreamApply mode is on.
        if self.native_read:
            self._shard = rpc.PsShard(vocab, dim, shard_index, num_shards)
            if not self._importing:
                self._shard.install(self.table, 0)
            # An IMPORTING destination defers its first install to
            # CompleteImport: until then the native handler answers
            # Lookup with "no table generation installed" (EINTERNAL) —
            # never unmigrated garbage — and scheme-aware clients fall
            # back to the source scheme.
            self.server.add_ps_service(
                "Ps", self._shard, self._handle_stream, stream=True)
        else:
            self.server.add_stream_handler("Ps", self._handle_stream)
        # `_status` rides along so the health-check prober can revive
        # this shard after a circuit-breaker isolation (resilience tier).
        self.server.add_status_service()
        self.port = self.server.start("127.0.0.1:0")

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    # -- replication surface ----------------------------------------------

    def configure_replication(self, replica_set: ReplicaSet,
                              replica_index: int, *,
                              timeout_ms: Optional[int] = None,
                              ack_timeout_s: Optional[float] = None,
                              quorum: "int | str | None" = "auto"
                              ) -> None:
        """Declares this server's place in its range's replica group
        (call after every replica has started — addresses are only known
        then).  The replica at ``replica_set.primary`` owns writes and
        starts propagating applied batches to the others; everyone else
        serves reads and applies ``ReplicaApply`` deltas.
        ``timeout_ms``/``ack_timeout_s`` tune the propagation control
        timeout and the per-apply ack wait.

        ``quorum`` is the write-ack quorum (replicas, primary included,
        that must HOLD a write before it acks): ``"auto"`` (the
        default) takes the majority for groups of three or more and the
        legacy connected-backups barrier for pairs; ``"majority"``
        forces the majority; an int passes through; ``None`` forces the
        legacy barrier.  With a quorum, the bootstrap loss window is
        closed — the first write blocks until a backup really holds it
        — and a majority promotion sweep provably intersects every
        acked write."""
        if replica_set.addresses[replica_index] != self.address:
            raise ValueError(
                f"replica_index {replica_index} is "
                f"{replica_set.addresses[replica_index]}, not this "
                f"server ({self.address})")
        if timeout_ms is not None:
            self.repl_timeout_ms = int(timeout_ms)
        if ack_timeout_s is not None:
            self.repl_ack_timeout_s = float(ack_timeout_s)
        n = len(replica_set.addresses)
        if quorum == "auto":
            quorum = n // 2 + 1 if n >= 3 else None
        elif quorum == "majority":
            quorum = n // 2 + 1
        elif quorum is not None:
            quorum = int(quorum)
            if not 1 <= quorum <= n:
                raise ValueError(
                    f"quorum {quorum} outside [1, {n}]")
        with self._repl_mu:
            self._replica_set = replica_set
            self._replica_index = replica_index
            self._quorum = quorum
            self._primary_flag = replica_index == replica_set.primary
            if self._primary_flag and len(replica_set.addresses) > 1:
                self._replicator = _Replicator(
                    self, [a for a in replica_set.addresses
                           if a != self.address], epoch=self._epoch,
                    timeout_ms=self.repl_timeout_ms,
                    quorum=self._quorum)

    @property
    def epoch(self) -> int:
        return self._epoch

    @property
    def is_primary(self) -> bool:
        """True when this replica owns writes (always true without a
        configured replica set — the legacy single-owner mode)."""
        return self._primary_flag

    def _peers(self) -> List[str]:
        rs = self._replica_set
        if rs is None:
            return []
        return [a for a in rs.addresses if a != self.address]

    def _check_primary(self) -> None:
        if not self._primary_flag:
            raise rpc.RpcError(
                resilience.ENOTPRIMARY,
                f"shard {self.shard_index} replica {self._replica_index} "
                f"({self.address}) is not the primary (epoch "
                f"{self._epoch})")

    def _check_repl_epoch(self, epoch: int) -> None:
        """Fencing: a replication message (Sync / ReplicaApply setup)
        carrying a stale epoch is rejected; a NEWER epoch means a newer
        primary exists — adopt it and demote if this node thought it was
        primary."""
        demote = None
        with self._repl_mu:
            if self._replica_set is None:
                # Bring-up race: this server has not been configured
                # into its replica group yet, so it cannot judge epochs
                # — and it must NOT answer the equal-epoch EFENCED
                # meant for stale primaries (an eager-connecting real
                # primary would demote itself off it).  Reject
                # retriably; the sender backs off and resyncs once
                # configuration lands.
                raise rpc.RpcError(
                    2001,
                    f"shard {self.shard_index} ({self.address}) has no "
                    f"replica group configured yet; retry the sync")
            if epoch < self._epoch or (epoch == self._epoch
                                       and self._primary_flag):
                if obs.enabled():
                    obs.counter("ps_replica_fenced").add(1)
                raise rpc.RpcError(
                    resilience.EFENCED,
                    f"stale replication epoch {epoch} (current "
                    f"{self._epoch}, primary={self._primary_flag})")
            if epoch > self._epoch:
                self._epoch = epoch
                if self._primary_flag:
                    self._primary_flag = False
                    demote, self._replicator = self._replicator, None
        if demote is not None:
            demote.stop(join=False, fenced=True)

    def _demote_on_fence(self) -> None:
        """A backup rejected our propagation with EFENCED: a newer
        primary exists.  Stop propagating and stop accepting writes; the
        new primary's Sync will overwrite any divergence."""
        demote = None
        with self._repl_mu:
            if self._primary_flag:
                self._primary_flag = False
                demote, self._replicator = self._replicator, None
                if obs.enabled():
                    obs.counter("ps_replica_demotions").add(1)
        if demote is not None:
            demote.stop(join=False, fenced=True)

    def _stream_write_fenced(self) -> bool:
        """True when streamed writes must be refused: this replica was
        demoted (or never was primary) while carrying a push stream, or
        its partition scheme was retired by a cutover."""
        return self._scheme_fenced or (
            self._replica_set is not None and not self._primary_flag)

    def _check_scheme(self) -> None:
        """Scheme gate for the WRITE paths (+ the importing half for
        reads): a cutover-fenced shard redirects writers to the
        successor scheme; an importing destination asks callers to wait
        out (writes) or fall back across schemes (reads)."""
        if self._scheme_fenced:
            nxt = f" (successor scheme v{self._next_scheme})" \
                if self._next_scheme is not None else ""
            raise rpc.RpcError(
                resilience.ESCHEMEMOVED,
                f"shard {self.shard_index} scheme "
                f"v{self.scheme_version} was retired by a fenced "
                f"cutover{nxt}; refresh the partition scheme")
        if self._importing:
            raise rpc.RpcError(
                resilience.EMIGRATING,
                f"shard {self.shard_index} scheme "
                f"v{self.scheme_version} is still importing rows "
                f"[{self.base}, {self.base + self.rows_per})")

    def claim_tag(self) -> str:
        """This replica's shard tag WITH its live primary/epoch claim —
        pass as ``tag_fn=`` to :meth:`naming.NamingClient.register` so
        every heartbeat publishes failover state into the registry
        (clients adopt the claimed primary instead of sweeping)."""
        from brpc_tpu import naming
        return naming.shard_tag(self.shard_index, self.num_shards,
                                self._replica_index, epoch=self._epoch,
                                primary=self._primary_flag,
                                scheme=self.scheme_version)

    def _reads(self) -> int:
        """Total reads ever served (Python + native path) — the drain
        signal: a retiring scheme's shards are idle once this stops
        moving."""
        with self._seq_mu:
            n = self._read_count
        return n + self.native_lookups

    def _fold_native_latency(self) -> None:
        """Drain the native Lookup latency counters into ``self._lat``.

        The zero-Python read path (ps_shard.cc ServeLookup) stamps a
        sum/count pair instead of calling the Python recorder; folding
        the delta since the last poll (as its mean, via record_bulk)
        makes SchemeInfo's p99 — and with it RebalancePolicy's
        tail-pressure input — see native-served traffic too."""
        shard = self._shard
        if shard is None:
            return
        sum_us, count = shard.lookup_stats()
        seen_sum, seen_count = self._native_lat_seen
        dn = count - seen_count
        if dn <= 0:
            return
        self._native_lat_seen = (sum_us, count)
        self._lat.record_bulk(max(sum_us - seen_sum, 0) / dn / 1e6, dn)

    def _install_full(self, gen: int) -> None:
        """One wholesale table establishment landed — checkpoint
        restore, replication Sync, a propagated ReplicaApply install,
        or CompleteImport opening the import: publish it to the native
        read path.  Called under the table WRITE lock.  The device
        subclass hooks here to stage the fresh host image into HBM
        when this replica is the serving primary."""
        if self._shard is not None and not self._importing:
            self._shard.install(self.table, gen)

    def _on_promoted(self) -> None:
        """Subclass hook: runs once per Promote, after the replicator
        swap and before the migration re-drive / durable re-base.  The
        device tier stages its host mirror into HBM here (backups hold
        the cheap host mirror; HBM is paid only on promotion)."""

    def _replication_snapshot(self):
        """Consistent ``(epoch, gen, table bytes, applied windows)`` for
        a full-state Sync.  Epoch is read under ``_repl_mu`` (it is
        mutated there — Promote/fence adoption), THEN the table read
        lock pins (gen, table, windows) together: a concurrent promotion
        can no longer pair a stale epoch with a fresh table.  Lock order
        is repl_mu → shard → writer_seq everywhere."""
        with self._repl_mu:
            epoch = self._epoch
            with self._mu.read():
                with self._seq_mu:
                    windows = dict(self._writer_applied)
                return (epoch, self._install_gen, self.table.tobytes(),
                        windows)

    # -- durable checkpoint (brpc_tpu.durable) ----------------------------

    def attach_checkpoint(self, store, *, recover: bool = True):
        """Attach a :class:`brpc_tpu.durable.CheckpointStore`: from here
        on every applied generation is teed into its delta log under
        the table write lock, wholesale installs and promotions fold
        into fresh base snapshots, and replica reconnects go
        hydrate-first through its tail.

        With ``recover=True`` (the default) the store's on-disk state
        is restored FIRST — base installed, delta bodies replayed
        through the exact live-apply parse and arithmetic
        (``_unpack_apply`` + ``subtract.at`` with this server's ``lr``),
        writer windows merged — so the acked ledger continues bit for
        bit across a cold start.  Either way a fresh base is snapshotted
        before the tee arms: the delta chain always extends a base this
        process wrote.  Returns the ``durable.RestorePoint`` (or None
        when nothing was recovered)."""
        point = store.restore() if recover else None
        if point is not None:
            if point.table.shape != (self.rows_per, self.dim):
                raise ValueError(
                    f"checkpoint geometry {point.table.shape} does not "
                    f"match shard ({self.rows_per}, {self.dim})")
            with self._repl_mu:
                if point.epoch > self._epoch:
                    self._epoch = point.epoch
                if point.seeded:
                    self._seeded = True
                with self._mu.write():
                    self.table[:] = point.table
                    with self._seq_mu:
                        for w, q in point.windows.items():
                            if q > self._writer_seqs.get(w, 0):
                                self._writer_seqs[w] = q
                            if q > self._writer_applied.get(w, 0):
                                self._writer_applied[w] = q
                    for _gen, body in point.deltas:
                        windows, off = _unpack_windows(body)
                        ids, grads = _unpack_apply(
                            memoryview(body)[off:], self.base,
                            self.rows_per, self.dim)
                        if ids.size:
                            np.subtract.at(self.table, ids,
                                           self.lr * grads)
                        if windows:
                            with self._seq_mu:
                                for w, q in windows.items():
                                    if q > self._writer_seqs.get(w, 0):
                                        self._writer_seqs[w] = q
                                    if q > self._writer_applied.get(
                                            w, 0):
                                        self._writer_applied[w] = q
                    self._install_gen = point.gen
                    self._install_full(self._install_gen)
        epoch, gen, table, windows = self._replication_snapshot()
        store.save_snapshot(
            epoch, gen,
            np.frombuffer(table, np.float32).reshape(self.rows_per,
                                                     self.dim),
            windows, seeded=self._seeded or self._primary_flag)
        self._durable = store
        return point

    def _tee_delta(self, dur, gen: int, body: bytes) -> None:
        """Tee one applied generation into the checkpoint store.
        Called under the table WRITE lock, so log order is apply order.
        A refused append — generation jump the delta framing cannot
        express, or an epoch bump (promotion without install) the open
        base predates — or a compaction-due tail folds the current
        state into a fresh base instead."""
        if (not dur.append_delta(gen, body, epoch=self._epoch)
                or dur.should_compact()):
            self._snapshot_to(dur, gen)

    def _snapshot_to(self, dur, gen: int) -> None:
        """Fold the CURRENT table into a new base.  Must run under the
        table write lock — (gen, table, windows) are pinned; the epoch
        is a racy read and a concurrent Promote re-snapshots on its own
        once it lands."""
        with self._seq_mu:
            windows = dict(self._writer_applied)
        dur.save_snapshot(self._epoch, gen, self.table, windows,
                          seeded=self._seeded or self._primary_flag)

    def flush_replication(self, timeout_s: float = 5.0) -> None:
        """Blocks until every backup has ACKED everything applied so far
        (no-op for an unreplicated or backup server) — the zero-lost-
        updates half of the flush barrier."""
        rep = self._replicator
        if rep is None:
            return
        with self._mu.read():
            target = self._install_gen
        rep.flush(target, timeout_s)

    def _migration_snapshot(self, row0: int, count: int):
        """Consistent ``(gen, rows bytes, applied windows)`` for one
        destination's row-range handoff: the read lock pins the triple
        together (the PR-4/PR-6 generation-pinning discipline — the
        shipped rows are exactly the table at ``gen`` and the windows
        cover exactly the frames applied by then)."""
        lo = row0 - self.base
        if lo < 0 or row0 + count > self.base + self.rows_per:
            raise ValueError(
                f"migration range [{row0}, {row0 + count}) outside "
                f"shard [{self.base}, {self.base + self.rows_per})")
        with self._mu.read():
            with self._seq_mu:
                windows = dict(self._writer_applied)
            return (self._install_gen,
                    self.table[lo:lo + count].tobytes(), windows)

    def _apply_migrate_frame(self, src: str, gen: int,
                             body) -> Optional[int]:
        """One source-shard batch (filtered to this shard's range)
        during import: applied in arrival order, deduped by the
        per-source generation watermark (a resync replays from its
        sync point; anything at or below the watermark is already
        here).  Returns the watermark to ack, or ``None`` once the
        import has completed — late frames must break the stream, not
        mutate a live table.

        On a REPLICATED destination the batch propagates to this
        shard's backups (the same ``ReplicaApply`` framing, enqueued
        under the write lock = apply order) and the watermark is acked
        only once the ack barrier holds — a destination primary dying
        right after cutover can then promote a backup that already
        holds every migrated row."""
        windows, off = _unpack_windows(body)
        ids, grads = _unpack_apply(memoryview(body)[off:], self.base,
                                   self.rows_per, self.dim)
        rep = None
        new_gen = 0
        with self._mu.write():
            if not self._importing:
                return None
            last = self._import_gens.get(src, -1)
            if gen <= last:
                return last   # duplicate after resync: ack, don't apply
            if ids.size:
                np.subtract.at(self.table, ids, self.lr * grads)
                self._install_gen += 1
                new_gen = self._install_gen
                rep = self._replicator
                dur = self._durable
                if rep is not None or dur is not None:
                    gids = (ids + self.base).astype(np.int32)
                    rbody = _pack_windows(windows) + bytes(
                        _pack_apply_req(gids, grads))
                if rep is not None:
                    rep.ship(new_gen, rbody)
                if dur is not None:
                    self._tee_delta(dur, new_gen, rbody)
            self._import_gens[src] = gen
            if windows:
                with self._seq_mu:
                    for w, q in windows.items():
                        if q > self._writer_seqs.get(w, 0):
                            self._writer_seqs[w] = q
                        if q > self._writer_applied.get(w, 0):
                            self._writer_applied[w] = q
            if obs.enabled():
                obs.counter("ps_migrate_frames_in").add(1)
        if rep is not None:
            try:
                rep.flush(new_gen, timeout_s=self.repl_ack_timeout_s)
            except rpc.RpcError:
                # Backups did not confirm: the watermark must NOT ack
                # (the source's cutover flush would count rows safe
                # that only this process holds).  Breaking the stream
                # forces a wholesale resync, which converges.
                return None
        return gen

    @staticmethod
    def _parse_migration_spec(payload, what: str) -> dict:
        """Validate one MigrateStart/MigrateSpec JSON spec — hostile
        input like every control payload."""
        try:
            spec = json.loads(payload)
            targets = spec["targets"]
            int(spec["scheme"])
            if not isinstance(targets, list) or not all(
                    isinstance(t, dict)
                    and isinstance(t.get("addr"), str)
                    and int(t["base"]) >= 0 and int(t["rows"]) > 0
                    and isinstance(t.get("replicas", []), list)
                    and all(isinstance(a, str)
                            for a in t.get("replicas", []))
                    for t in targets):
                raise ValueError("bad targets")
        except (ValueError, KeyError, TypeError,
                RecursionError) as e:
            raise wire.WireError(
                f"malformed {what} spec: {e}") from e
        return spec

    def _install_migrator(self, spec: dict) -> None:
        """Install (or replace) the migration shipper described by
        ``spec`` and remember the spec — a later promotion of a backup
        re-drives from its replicated copy."""
        from brpc_tpu import reshard  # lazy: reshard imports us
        with self._repl_mu:
            if self._scheme_fenced or self._importing:
                raise rpc.RpcError(
                    resilience.ESCHEMEMOVED,
                    f"shard {self.shard_index} cannot source a "
                    f"migration (fenced={self._scheme_fenced}, "
                    f"importing={self._importing})")
            old, self._migrator = self._migrator, None
        if old is not None:
            old.stop()
        shipper = reshard.MigrationShipper(
            self, spec["targets"], int(spec["scheme"]),
            timeout_ms=self.repl_timeout_ms)
        with self._repl_mu:
            self._migrator = shipper
            self._pending_migration = spec
        # Workers start only once the apply path sees the shipper:
        # every batch from here on either ships or predates the
        # workers' range snapshots — never neither.
        shipper.start()

    def _reserve_seq(self, writer: str, seq: int) -> bool:
        """True exactly once per (writer, seq): the server-side dedup
        window that makes reconnect replay idempotent.  Monotonic per
        writer — the stream is ordered, so a lower-or-equal seq can only
        be a replay of something already enqueued."""
        with self._seq_mu:
            if seq <= self._writer_seqs.get(writer, 0):
                return False
            self._writer_seqs[writer] = seq
            return True

    def _apply_replica_frame(self, epoch: int, gen: int,
                             body) -> Optional[int]:
        """One propagated batch from the primary: fence-checked,
        applied only when it is the NEXT generation (duplicates ack the
        current gen; a gap returns None so the receiver breaks the
        stream and forces a full resync).  Returns the gen to ack, or a
        NEGATIVE value (-epoch) when the sender is fenced — the
        receiver relays it as an explicit fence notification."""
        if epoch < self._epoch:
            if obs.enabled():
                obs.counter("ps_replica_fenced").add(1)
            return -self._epoch
        windows, off = _unpack_windows(body)
        ids, grads = _unpack_apply(memoryview(body)[off:], self.base,
                                   self.rows_per, self.dim)
        with self._mu.write():
            if gen <= self._install_gen:
                return self._install_gen   # duplicate: ack, don't apply
            if gen != self._install_gen + 1:
                if obs.enabled():
                    obs.counter("ps_replica_gaps").add(1)
                return None
            np.subtract.at(self.table, ids, self.lr * grads)
            self._install_gen = gen
            # An importing destination's backup defers its first
            # native snapshot to CompleteImport — the native read
            # path must never serve unmigrated rows.
            self._install_full(gen)
            if windows:
                # Inherit the primary's dedup window WITH the batch it
                # covers: on promotion, a replayed frame at or below
                # this mark dedups instead of double-applying.
                with self._seq_mu:
                    for w, q in windows.items():
                        if q > self._writer_seqs.get(w, 0):
                            self._writer_seqs[w] = q
                        if q > self._writer_applied.get(w, 0):
                            self._writer_applied[w] = q
            dur = self._durable
            if dur is not None:
                # A backup's checkpoint tees the propagated frames
                # verbatim: a promoted backup restarts with the same
                # durable ledger the primary had.
                self._tee_delta(dur, gen, bytes(body))
            return gen

    # -- request handling --------------------------------------------------

    @staticmethod
    def _payload_keys(method: str, payload: bytes) -> int:
        """Key count of one data-path request (0 for control traffic)."""
        if method in ("Lookup", "ApplyGrad"):
            return struct.unpack_from("<i", payload, 0)[0]
        if method == "ApplyGradId":
            body = _unpack_apply_id(payload)[3]
            return struct.unpack_from("<i", body, 0)[0]
        return 0

    def _handle(self, method: str, payload: bytes) -> bytes:
        try:
            # Deadline admission FIRST: expired queued work sheds here
            # (EDEADLINE), before any parse or table touch.
            payload, deadline_us = _admit_deadline(method, payload)
            if not obs.enabled():
                return self._serve(method, payload, deadline_us)
            t0 = time.monotonic_ns()
            rsp = self._serve(method, payload, deadline_us)
        except wire.WireError:
            _reject_frame(method)
            raise
        except rpc.RpcError as e:
            if e.code == resilience.EDEADLINE:
                # Per-SERVER shed mark: SchemeInfo reports it alongside
                # the limiter gate sheds as the rebalancer's
                # tail-pressure input.
                self._sheds.add(1)
            raise
        if method in self.LIMITED_METHODS:
            # Per-server data-plane latency — the SchemeInfo p99 the
            # rebalancer consumes (per server, unlike the process-wide
            # per-shard-index recorders above).
            self._lat.record((time.monotonic_ns() - t0) / 1e9)
        _record_ps_server(self.shard_index, method,
                          self._payload_keys(method, payload),
                          len(payload), len(rsp), t0)
        return rsp

    def _handle_stream(self, method: str, payload: bytes, accept) -> bytes:
        """Stream-capable trampoline target: ``StreamApply`` binds a
        client's push stream to this shard's combiner (primary only;
        a non-empty setup request is the writer id for the idempotent
        framed mode and answers with that writer's seq high-water mark);
        ``ReplicaApply`` binds the primary's delta stream to this
        backup's table; everything else is the plain :meth:`_handle`
        contract."""
        if method in ("StreamApply", "MigrateApply", "ReplicaApply"):
            try:
                return self._serve_stream_setup(method, payload, accept)
            except wire.WireError:
                _reject_frame(method)
                raise
        return self._handle(method, payload)

    def _serve_stream_setup(self, method: str, payload: bytes,
                            accept) -> bytes:
        if method == "StreamApply":
            if not self.stream:
                raise ValueError(f"unknown method {method}")
            self._check_primary()
            self._check_scheme()
            writer = payload.decode(errors="replace") if payload else ""
            recv = _ApplyStreamReceiver(self, writer)
            # The reply half carries the fence notification (a demotion
            # mid-stream must fail the client's flush, not silently
            # drop into a zombie's table).
            recv.reply = accept(recv)
            if writer:
                with self._seq_mu:
                    last = self._writer_seqs.get(writer, 0)
                return struct.pack("<q", last)
            return b""
        if method == "MigrateApply":
            # A migration source binds its delta stream to this
            # importing destination; the setup answers the per-source
            # watermark so a resync can skip already-covered frames.
            _scheme, alen = wire.read("<qi", payload, 0,
                                      "MigrateApply.setup")
            wire.need(payload, 12, alen, "MigrateApply.src")
            src = bytes(payload[12:12 + alen]).decode(errors="replace")
            with self._mu.read():
                if not self._importing:
                    raise rpc.RpcError(
                        resilience.ESCHEMEMOVED,
                        f"shard {self.shard_index} completed its "
                        f"import; late migration streams are refused")
                last = self._import_gens.get(src, -1)
            recv = _MigrateStreamReceiver(self, src)
            recv.reply = accept(recv)
            return struct.pack("<q", last)
        if method == "ReplicaApply":
            (epoch,) = wire.read("<q", payload, 0, "ReplicaApply.setup")
            self._check_repl_epoch(epoch)
            recv = _ReplicaStreamReceiver(self)
            recv.reply = accept(recv)
            # Schema replica_setup_rsp: the seeded flag is what lets a
            # gen-0 backup that holds the chain's exact gen-0 image
            # (Sync'd, or restored from a seeded base) hydrate the
            # delta tail instead of forcing another wholesale Sync.
            return struct.pack(
                "<qqq", self._epoch, self._install_gen,
                1 if (self._seeded or self._primary_flag) else 0)
        raise ValueError(f"unknown stream method {method}")

    def _apply_frame(self, payload, meta=None) -> None:
        """One streamed delta: parse/validate, enqueue without waiting
        (frames have no response; the close barrier flushes).  ``meta``
        is the frame's (writer, seq) tag — it rides the combiner into
        :meth:`_apply_batch` so the applied window propagates with the
        batch that covers it."""
        t0 = time.monotonic_ns() if obs.enabled() else 0
        ids, grads = _unpack_apply(payload, self.base, self.rows_per,
                                   self.dim)
        self._combiner.add(ids, grads, wait=False, meta=meta)
        if t0:
            _record_ps_server(self.shard_index, "StreamApply",
                              int(ids.size), len(payload), 0, t0)

    def _apply_batch(self, ids: np.ndarray, grads: np.ndarray,
                     metas=()) -> None:
        """ONE combined application for a drained batch: a single
        unbuffered ``subtract.at`` (duplicate ids sum exactly), a
        generation bump, under ``native_read`` a single snapshot
        install — and, on a replicated primary, ONE propagation frame
        shipped to every backup (enqueued under the write lock so
        backups see batches in exactly the apply order).  A DEMOTED
        replica refuses outright: applying here would land updates only
        the new primary's next Sync erases."""
        if not ids.size:
            return   # nothing applied: no generation, nothing to ship
        with self._repl_mu:
            if self._replica_set is not None and not self._primary_flag:
                raise rpc.RpcError(
                    resilience.ENOTPRIMARY,
                    f"shard {self.shard_index} replica "
                    f"{self._replica_index} was demoted (epoch "
                    f"{self._epoch}); refusing the apply")
        updates: Dict[str, int] = {}
        for m in metas:
            if m[1] > updates.get(m[0], 0):
                updates[m[0]] = m[1]
        with self._mu.write():
            # Re-checked INSIDE the write lock: SchemeFence reads its
            # final generation under this lock after setting the flag,
            # so an apply that raced the fence either finished (its gen
            # is covered by the cutover flush) or refuses here and the
            # caller re-routes — an acked-but-unmigrated write cannot
            # exist.
            if self._scheme_fenced:
                raise rpc.RpcError(
                    resilience.ESCHEMEMOVED,
                    f"shard {self.shard_index} scheme "
                    f"v{self.scheme_version} was fenced mid-apply; "
                    f"refusing the write")
            np.subtract.at(self.table, ids, self.lr * grads)
            self._install_gen += 1
            gen = self._install_gen
            if self._shard is not None:
                self._shard.install(self.table, gen)
            if updates:
                with self._seq_mu:
                    for w, q in updates.items():
                        if q > self._writer_applied.get(w, 0):
                            self._writer_applied[w] = q
            rep = self._replicator
            mig = self._migrator
            dur = self._durable
            if rep is not None or mig is not None or dur is not None:
                gids = (ids + self.base).astype(np.int32)
            if rep is not None or dur is not None:
                body = _pack_windows(updates) + bytes(
                    _pack_apply_req(gids, grads))
            if rep is not None:
                rep.ship(gen, body)
            if dur is not None:
                self._tee_delta(dur, gen, body)
            if mig is not None:
                # Live reshard: the successor scheme's shards subscribe
                # to this shard's applied batches (range-filtered by the
                # shipper) — enqueued under the write lock so the
                # destinations see batches in exactly the apply order.
                mig.ship(gen, gids, grads, updates)
        # Synchronous replication: the apply (and therefore the unary
        # response / combiner barrier riding it) completes only once
        # every CONNECTED backup acked this batch — a write acked to
        # the client can never be lost to a failover among synced
        # replicas.  Disconnected backups are skipped (their reconnect
        # starts with a full-table Sync, so nothing is lost, only
        # delayed); the wait happens OUTSIDE the write lock so reads
        # keep flowing.
        if rep is not None:
            rep.flush(gen, timeout_s=self.repl_ack_timeout_s)

    def _serve_apply_id(self, payload, deadline_us: int = 0) -> bytes:
        """Idempotent unary write (``ApplyGradId``): the per-(writer,
        shard) seq window drops a timed-out-but-APPLIED attempt's retry
        server-side (exactly-once against this shard), and a GUARD
        naming a superseded frame from a retired scheme drops a
        re-split delta whose content already migrated here with the
        old shard's rows.  Always answers the covering install gen."""
        self._check_primary()
        self._check_scheme()
        writer, seq, guards, body = _unpack_apply_id(payload)
        ids, grads = _unpack_apply(body, self.base, self.rows_per,
                                   self.dim)
        apply = True
        if guards:
            with self._seq_mu:
                covered = any(self._writer_applied.get(k, 0) >= q
                              for k, q in guards)
            if covered:
                apply = False
                if obs.enabled():
                    obs.counter("ps_scheme_guard_drops").add(1)
        if apply and not self._reserve_seq(writer, seq):
            # an earlier attempt of this exact request was admitted:
            # the retry is a replay, not a new write
            apply = False
            if obs.enabled():
                obs.counter("ps_unary_dedup_drops").add(1)
        if apply and ids.size:
            if self.combine:
                self._combiner.add(ids, grads, meta=(writer, seq),
                                   deadline_us=deadline_us)
            else:
                self._apply_batch(ids, grads, metas=[(writer, seq)])
        with self._mu.read():
            return struct.pack("<q", self._install_gen)

    def _serve_control(self, method: str, payload: bytes) -> bytes:
        """Replication control plane (unary, tiny, off the data path)."""
        if method == "ReplicaState":
            return json.dumps({
                "epoch": self._epoch, "gen": self._install_gen,
                "primary": self._primary_flag,
                "replica_index": self._replica_index,
                "addr": self.address,
            }).encode()
        if method == "Promote":
            (epoch,) = wire.read("<q", payload, 0, "Promote.epoch")
            with self._repl_mu:
                if epoch <= self._epoch:
                    raise rpc.RpcError(
                        resilience.EFENCED,
                        f"promote epoch {epoch} <= current "
                        f"{self._epoch}")
                self._epoch = epoch
                self._primary_flag = True
                # The promoted table is the chain from here on — it
                # stays provably chain-established across a later
                # demotion too.
                self._seeded = True
                # Reserved-but-never-applied seqs (enqueued on a
                # since-demoted run, failed with the demotion) must not
                # survive into the new reign's admission window — they
                # would dedup a replay whose data this table lacks.
                with self._seq_mu:
                    self._writer_seqs = dict(self._writer_applied)
                old, self._replicator = self._replicator, None
                peers = self._peers()
                if peers:
                    self._replicator = _Replicator(
                        self, peers, epoch=epoch,
                        timeout_ms=self.repl_timeout_ms,
                        quorum=self._quorum)
                pending = self._pending_migration
            if old is not None:
                old.stop(join=False)
            if obs.enabled():
                obs.counter("ps_replica_promotions").add(1)
            self._on_promoted()
            if pending is not None and not self._scheme_fenced \
                    and not self._importing:
                # Automatic re-drive: the dead primary carried an
                # in-flight migration whose spec was replicated here.
                # The fresh shipper resyncs every destination wholesale
                # from THIS table (byte-identical at its generation) and
                # resumes deltas — no manual MigrateStart; destinations
                # key their watermarks per source ADDRESS, so the new
                # source starts its own watermark and the old one goes
                # quiet.
                self._install_migrator(pending)
                if obs.enabled():
                    obs.counter("ps_migration_redrives").add(1)
            dur = self._durable
            if dur is not None:
                # Make the new reign durable: an epoch-only change has
                # no delta record, so fold it into a fresh base.  This
                # also re-bases the store, which pushes any peer with a
                # possibly-divergent history out of the hydrate window.
                e2, g2, tbl, w2 = self._replication_snapshot()
                dur.save_snapshot(
                    e2, g2,
                    np.frombuffer(tbl, np.float32).reshape(
                        self.rows_per, self.dim), w2, seeded=True)
            return struct.pack("<qq", self._epoch, self._install_gen)
        if method == "Sync":
            epoch, gen, count = wire.read("<qqq", payload, 0, "Sync.hdr")
            self._check_repl_epoch(epoch)
            if count != self.rows_per * self.dim:
                raise ValueError(
                    f"sync size {count} != shard table "
                    f"{self.rows_per * self.dim}")
            wire.need(payload, 24, count * 4, "Sync.table")
            table = np.frombuffer(payload, np.float32, count,
                                  24).reshape(self.rows_per, self.dim)
            tbl_end = 24 + count * 4
            windows = _unpack_windows(payload, tbl_end)[0] \
                if len(payload) > tbl_end else {}
            with self._repl_mu:
                # Re-verify under the epoch's own lock: a Promote that
                # slipped in between the fence check and this install
                # must not let a now-stale Sync overwrite the new
                # primary's table.
                if epoch < self._epoch or self._primary_flag:
                    raise rpc.RpcError(
                        resilience.EFENCED,
                        f"stale sync epoch {epoch} (current "
                        f"{self._epoch}, primary={self._primary_flag})")
                with self._mu.write():
                    self.table[:] = table
                    self._install_gen = gen
                    # A wholesale Sync IS chain establishment: even at
                    # gen 0 this table is now provably the chain's
                    # image, so later hydrates may trust it.
                    self._seeded = True
                    self._install_full(gen)
                    # Full-state handoff: the received (table, gen,
                    # windows) triple is authoritative — local window
                    # history refers to a table this install replaces.
                    with self._seq_mu:
                        self._writer_seqs = dict(windows)
                        self._writer_applied = dict(windows)
                    dur = self._durable
                    if dur is not None:
                        # A wholesale install jumps the generation — the
                        # delta framing cannot express it, so re-base.
                        self._snapshot_to(dur, gen)
            return b""
        if method == "WriterSeq":
            # Applied high-water for one writer + current gen: the
            # client's flush barrier verifies against the PRIMARY's
            # applied window (a zombie answers ENOTPRIMARY and the
            # client re-resolves).
            self._check_primary()
            writer = payload.decode(errors="replace")
            with self._seq_mu:
                applied = self._writer_applied.get(writer, 0)
            with self._mu.read():
                gen = self._install_gen
            return struct.pack("<qq", applied, gen)
        if method == "Flush":
            if self._combiner is not None:
                self._combiner.flush()
            self.flush_replication()
            return struct.pack("<q", self._install_gen)
        if method == "SchemeInfo":
            with self._mu.read():
                gen = self._install_gen
            self._fold_native_latency()
            shed = int(self._sheds.get_value())
            lim = self.limiter
            if lim is not None:
                shed += sum(int(g.get("shed", 0))
                            for g in lim.snapshot().values())
            return json.dumps({
                "scheme": self.scheme_version,
                "importing": self._importing,
                "fenced": self._scheme_fenced,
                "next_scheme": self._next_scheme,
                "gen": gen,
                "reads": self._reads(),
                "primary": self._primary_flag,
                "epoch": self._epoch,
                "addr": self.address,
                "table_bytes": self.rows_per * self.dim * 4,
                # Tail-pressure inputs (RebalancePolicy): data-plane
                # handler p99 on THIS server and its cumulative shed
                # count (deadline admission + limiter gates).
                "p99_us": self._lat.percentile(0.99),
                "shed": shed,
            }).encode()
        if method == "MigrateStart":
            # Begin streaming this shard's rows to the successor
            # scheme's shards: one shipper per overlapping destination
            # (range-filtered Sync at a pinned generation, then every
            # applied batch).  Idempotent — a re-issued start replaces
            # the shipper and the destinations resync wholesale.
            self._check_primary()
            spec = self._parse_migration_spec(payload, "MigrateStart")
            self._install_migrator(spec)
            with self._mu.read():
                return struct.pack("<q", self._install_gen)
        if method == "MigrateSpec":
            # The re-drive half of fault-tolerant migration: a source
            # BACKUP stores the in-flight migration's spec; if it is
            # later promoted (the source primary died mid-copy), the
            # Promote handler re-installs the shipper from it — no
            # manual MigrateStart.  The driver distributes this to
            # every non-primary source replica at start().
            spec = self._parse_migration_spec(payload, "MigrateSpec")
            with self._repl_mu:
                self._pending_migration = spec
            return b""
        if method == "MigrateState":
            mig = self._migrator
            with self._mu.read():
                gen = self._install_gen
            return json.dumps({
                "gen": gen, "active": mig is not None,
                "fenced": self._scheme_fenced,
                "targets": mig.state() if mig is not None else {},
            }).encode()
        if method == "MigrateStop":
            # Abort path: stop shipping, forget the successor AND the
            # replicated spec (a later promotion must not re-drive an
            # aborted migration).  The destinations stay importing
            # (their owner closes them).
            with self._repl_mu:
                mig, self._migrator = self._migrator, None
                self._pending_migration = None
            if mig is not None:
                # join the workers BEFORE the channel set closes — an
                # aborted migration must leave no native handle behind
                mig.stop()
            return b""
        if method == "SchemeFence":
            # The CUTOVER write fence: no new writes are admitted under
            # the retiring scheme (they answer ESCHEMEMOVED and the
            # client refreshes its routing), already-admitted writes
            # drain, and the final migration flush waits until every
            # destination acked the final generation — after this
            # returns, the successor shards hold every acked update.
            (ver,) = wire.read("<q", payload, 0, "SchemeFence.ver")
            with self._repl_mu:
                if self._importing:
                    raise rpc.RpcError(
                        resilience.EMIGRATING,
                        f"shard {self.shard_index} is importing; an "
                        f"importing destination cannot be fenced")
                was_fenced = self._scheme_fenced
                prev_next = self._next_scheme
                self._scheme_fenced = True
                self._next_scheme = int(ver)
            try:
                if self._combiner is not None:
                    # Drain what was admitted before the flag: entries
                    # that lost the race bounce with ESCHEMEMOVED
                    # (their callers re-route with guards) — expected,
                    # not a fence failure.
                    try:
                        self._combiner.flush()
                    except rpc.RpcError as e:
                        if e.code != resilience.ESCHEMEMOVED:
                            raise
                self.flush_replication()
                mig = self._migrator
                # The WRITE lock is the fence barrier: any apply that
                # passed the admission check before the flag has either
                # bumped the generation (covered by the flush below) or
                # will refuse inside the lock after we release it.
                with self._mu.write():
                    gen = self._install_gen
                if mig is not None:
                    mig.flush(gen, timeout_s=self.repl_ack_timeout_s)
            except BaseException:
                # A fence that cannot PROVE the handoff must not stick:
                # with no successor ever published, a stuck flag would
                # refuse every write forever while no scheme owns the
                # range.  Roll back (unless a previous fence already
                # completed — a failed re-issue must not unfence a
                # cut-over shard) and let the driver retry or abort.
                if not was_fenced:
                    with self._repl_mu:
                        self._scheme_fenced = False
                        self._next_scheme = prev_next
                raise
            if obs.enabled():
                obs.counter("ps_scheme_fences").add(1)
            with self._repl_mu:
                # cutover complete for this source: a later promotion
                # must not re-drive the finished migration
                self._pending_migration = None
            return struct.pack("<q", gen)
        if method == "SchemeUnfence":
            # Abort-path rollback (MigrationDriver.abort): a cutover
            # that fenced SOME sources and then failed leaves them
            # refusing writes with no successor ever published; this
            # control readmits writes under the retiring scheme.  Must
            # not be issued after a COMPLETED cutover (the destinations
            # are open and own the ranges).
            with self._repl_mu:
                self._scheme_fenced = False
                self._next_scheme = None
            if obs.enabled():
                obs.counter("ps_scheme_unfences").add(1)
            return b""
        if method == "MigrateSync":
            # Range handoff: install the source's rows for (a slice of)
            # this shard's range wholesale, at the source's pinned
            # generation, windows included — the import-side mirror of
            # the replication Sync.
            scheme, src_gen, row0, count, alen = wire.read(
                "<qqqqi", payload, 0, "MigrateSync.hdr")
            wire.need(payload, 36, alen, "MigrateSync.src")
            src = bytes(payload[36:36 + alen]).decode(errors="replace")
            off = 36 + alen
            wire.check_count(count, self.rows_per, "MigrateSync.count")
            lo = row0 - self.base
            if lo < 0 or row0 + count > self.base + self.rows_per:
                raise ValueError(
                    f"sync range [{row0}, {row0 + count}) outside "
                    f"shard [{self.base}, {self.base + self.rows_per})")
            wire.need(payload, off, count * self.dim * 4,
                      "MigrateSync.rows")
            rows = np.frombuffer(payload, np.float32, count * self.dim,
                                 off).reshape(count, self.dim)
            windows = _unpack_windows(
                payload, off + count * self.dim * 4)[0]
            rep = None
            with self._mu.write():
                if not self._importing:
                    raise rpc.RpcError(
                        resilience.ESCHEMEMOVED,
                        f"shard {self.shard_index} completed its "
                        f"import; a late source sync must not "
                        f"overwrite a live table")
                self.table[lo:lo + count] = rows
                self._import_gens[src] = src_gen
                self._install_gen += 1
                sync_gen = self._install_gen
                rep = self._replicator
                if windows:
                    with self._seq_mu:
                        for w, q in windows.items():
                            if q > self._writer_seqs.get(w, 0):
                                self._writer_seqs[w] = q
                            if q > self._writer_applied.get(w, 0):
                                self._writer_applied[w] = q
                dur = self._durable
                if dur is not None:
                    # The range overwrite jumped the generation: re-base
                    # the checkpoint (which also pushes this shard's
                    # backups out of the hydrate window — they really do
                    # need the wholesale resync below).
                    self._snapshot_to(dur, sync_gen)
            if rep is not None:
                # A wholesale range overwrite is inexpressible in the
                # delta framing: force this destination's backups
                # through a full-table Sync and hold the source's
                # response until the ack barrier covers it — the Sync
                # response IS the source's ack that this slice is safe.
                rep.resync_peers()
                rep.flush(sync_gen, timeout_s=self.repl_ack_timeout_s)
            if obs.enabled():
                obs.counter("ps_migrate_syncs").add(1)
            return b""
        if method == "CompleteImport":
            # The import is byte-complete (every source fenced and
            # flushed): open for business.  Publishes the first native
            # snapshot — until here the native read path answered
            # errors, never unmigrated rows.
            with self._repl_mu:
                backup = (self._replica_set is not None
                          and not self._primary_flag)
                with self._mu.write():
                    was = self._importing
                    if was and backup and self._install_gen == 0:
                        # A destination backup that never received its
                        # primary's Sync holds seed garbage — opening
                        # it would serve unmigrated rows.  Stay
                        # importing; the reconnect Sync brings the data
                        # and the driver's retry opens it then.
                        raise rpc.RpcError(
                            resilience.EMIGRATING,
                            f"shard {self.shard_index} backup has no "
                            f"replicated state yet; refusing to open "
                            f"an empty import")
                    self._importing = False
                    gen = self._install_gen
                    if was:
                        self._install_full(gen)
                rep = self._replicator
            if was and rep is not None:
                # Open the backups too: force a fresh full-table Sync
                # (one may have lagged the import propagation) and
                # clear their import flags — a destination backup that
                # missed the driver's open would otherwise answer
                # EMIGRATING until restarted.  The unary fan-out runs
                # on its OWN thread: a native call from inside this
                # fiber-served handler would park the fiber and resume
                # it on another pthread (the PyGILState crash) — the
                # same rule that keeps replicator/shipper traffic on
                # dedicated threads.
                rep.resync_peers()
                peers = self._peers()
                timeout_ms = self.repl_timeout_ms
                ack_s = self.repl_ack_timeout_s

                def _open_backups() -> None:
                    try:
                        rep.flush(gen, timeout_s=ack_s)
                    except rpc.RpcError:
                        pass   # a dead backup stays importing; reads
                        #        route around it (replica-level miss)
                    for a in peers:
                        ch = rpc.Channel(a, timeout_ms=timeout_ms)
                        try:
                            ch.call("Ps", "CompleteImport", b"",
                                    timeout_ms=timeout_ms)
                        except rpc.RpcError:
                            if obs.enabled():
                                obs.counter(
                                    "ps_import_open_errors").add(1)
                        finally:
                            ch.close()

                threading.Thread(target=_open_backups, daemon=True,
                                 name="brt-import-open").start()
            if obs.enabled() and was:
                obs.counter("ps_imports_completed").add(1)
            return struct.pack("<q", gen)
        raise ValueError(f"unknown method {method}")

    def _serve(self, method: str, payload: bytes,
               deadline_us: int = 0) -> bytes:
        if method in ("ReplicaState", "Promote", "Sync", "WriterSeq",
                      "Flush", "SchemeInfo", "MigrateStart",
                      "MigrateSpec", "MigrateState", "MigrateStop",
                      "SchemeFence", "SchemeUnfence", "MigrateSync",
                      "CompleteImport"):
            return self._serve_control(method, payload)
        if method == "ApplyGradId":
            return self._serve_apply_id(payload, deadline_us)
        if method not in ("Lookup", "ApplyGrad"):
            raise ValueError(f"unknown method {method}")
        # Guarded header (wire schemas lookup_req/apply_req): a negative
        # count would make frombuffer re-interpret the whole payload; an
        # oversized one must reject cleanly, and Lookup mirrors the
        # native handler's EXACT-length contract (ps_shard.cc).
        (count,) = wire.read("<i", payload, 0, f"{method}.count")
        wire.check_count(count, (len(payload) - 4) // 4,
                         f"{method}.count")
        if method == "Lookup" and len(payload) != 4 + 4 * count:
            raise wire.WireError(
                f"Lookup request length mismatch (count={count}, "
                f"{len(payload)} bytes)")
        if method == "ApplyGrad":
            wire.need(payload, 4 + 4 * count, count * self.dim * 4,
                      "ApplyGrad.grads")
        ids = np.frombuffer(payload, np.int32, count, 4) - self.base
        if ids.size and (ids.min() < 0 or ids.max() >= self.rows_per):
            # Out-of-range ids would wrap to wrong rows via negative indexing.
            raise ValueError(
                f"ids outside shard [{self.base}, "
                f"{self.base + self.rows_per}) for shard base {self.base}"
            )
        if method == "Lookup":
            if self._importing:
                # The range is still streaming in: answer a scheme-aware
                # miss so the client falls back to the source scheme.
                self._check_scheme()
            with self._seq_mu:
                self._read_count += 1
            with self._mu.read():
                gathered = self.table[ids]
            # The gather above is the ONE unavoidable copy (fancy
            # indexing materializes the rows); zero-copy mode responds
            # with the gathered array pinned as a borrowed block instead
            # of paying tobytes + the respond append on top of it.
            if zerocopy_enabled() and gathered.nbytes >= _ZC_MIN_BYTES:
                out = rpc.IOBuf()
                out.append_pinned(gathered)
                return out
            return gathered.tobytes()
        if method == "ApplyGrad":
            # Writes belong to the primary: a demoted/backup replica
            # rejects so the client re-resolves and fails over.  A
            # cutover-fenced or importing shard redirects instead.
            self._check_primary()
            self._check_scheme()
            grads = np.frombuffer(payload, np.float32,
                                  count * self.dim, 4 + 4 * count)
            if self.combine:
                # Combined write path: enqueue and wait for the batch —
                # the combiner's leader applies once per drained batch.
                self._combiner.add(ids,
                                   grads.reshape(count, self.dim),
                                   deadline_us=deadline_us)
            else:
                self._apply_batch(ids, grads.reshape(count, self.dim))
            if self._replica_set is not None:
                # Replicated: answer the gen this write is covered by
                # (>= the batch it landed in).  The client records it as
                # its acked floor — failover refuses any candidate whose
                # gen is behind it, so "acked then lost" becomes "acked
                # or loudly refused".
                with self._mu.read():
                    return struct.pack("<q", self._install_gen)
            return b""
        raise ValueError(f"unknown method {method}")

    @property
    def native_lookups(self) -> int:
        """Lookups served with zero Python in the loop (0 unless
        ``native_read``)."""
        return 0 if self._shard is None else self._shard.native_lookups

    def close(self):
        # Replicator first (stop shipping; its streams point at OTHER
        # servers).  Then the server: its native Lookup handlers gather
        # from the shard's snapshots and must drain before the shard
        # dies.  Then the combiner: a dying stream's receiver teardown
        # can still flush into it after Join (its delivery queue outlives
        # the connection), and an applying drain must not race shard
        # death.
        with self._repl_mu:
            rep, self._replicator = self._replicator, None
            mig, self._migrator = self._migrator, None
        if rep is not None:
            rep.stop()
        if mig is not None:
            mig.stop()
        self.server.close()
        if self._combiner is not None:
            self._combiner.shutdown()
        if self._shard is not None:
            self._shard.close()
            self._shard = None
        for name in self._gauge_names:
            obs.drop_var(name)
        self._gauge_names = ()
        for name in self._sig_names:
            obs.drop_var(name)
        self._sig_names = ()


class _TableGen:
    """One generation of the device-resident table: the buffer handle plus
    the pins keeping it alive.  A retired generation's handle is released
    when the last pin drops (never while a Lookup gathers from it)."""

    __slots__ = ("handle", "pins", "retired")

    def __init__(self, handle: int):
        self.handle = handle
        self.pins = 0
        self.retired = False


class DevicePsShardServer(PsShardServer):
    """Embedding shard whose SERVING table is RESIDENT IN DEVICE HBM —
    and, since ISSUE 20, a first-class citizen of the CPU tier's
    replication / migration / rebalance machinery: it subclasses
    :class:`PsShardServer` and reuses its wire contracts verbatim
    (``ReplicaApply`` framing, ``Promote``/``EFENCED`` fencing, the
    ``MigrateSync``/``MigrateApply`` handoff, the ``CheckpointStore``
    delta tee), so ``configure_replication(quorum=)``, failover,
    live splits and cold-restart replay all behave identically on the
    device tier.

    The table keeps living behind a native device-buffer handle (the
    RDMA-lkey analog, cpp/device/pjrt_device.h); Lookup/ApplyGrad are
    compiled gather / scatter-sub launches (cpp/device/
    pjrt_executable.cc).  Request ids and gradients DMA host->HBM
    through the registered block pool; looked-up rows DMA back into
    pooled blocks.  No JAX anywhere in the serving path — this is the
    reference's "transport swap is invisible above Socket" contract
    with PJRT as the transport (docs/en/rdma.md:34 analog).

    **Two serving modes.**  A PRIMARY that is open for business serves
    from HBM (``_dev_serving``): updates are functional on-device
    (scatter-sub emits a fresh table buffer), so the tiny
    ``ps.device_shard`` leaf lock guards only the pin map; Lookup pins
    the current buffer, gathers/fetches OUTSIDE the locks, unpins.
    Everyone else — backups, importing split destinations, demoted
    ex-primaries — runs the inherited CPU paths against the cheap HOST
    MIRROR (``_host_table``): ReplicaApply deltas, Sync installs,
    MigrateSync range writes and checkpoint replay all mutate it in
    place exactly as on the CPU tier.  Mode flips happen under the
    table write lock: promotion (and CompleteImport on a primary)
    stages the mirror into HBM (``_on_promoted`` /
    ``_install_full``); demotion and fence adoption DMA the live
    table down into the mirror first (``_mirror_down``) so nothing
    applied on-device is lost.

    **Replication off the write path**: the serving ``_apply_batch``
    launches the scatter outside the table lock against a pinned
    buffer, then — under the write lock, exactly like the CPU tier —
    installs the new handle and tees ONE ``replica_apply_body`` frame
    (ids + grads + writer windows, NOT the table) to the replicator,
    the checkpoint delta log and any migration shipper, so backups and
    the durable ledger see device batches in apply order.  Snapshot
    reads (Sync wholesale, MigrateSync range handoffs, checkpoint
    re-bases) pin one generation under the lock and DMA it down
    OUTSIDE the lock — no blocking ``brt_device_*`` call ever runs
    under a checked lock (RACECHECK-clean by construction).

    The optimistic install keeps its pre-parity cost model under write
    FAN-IN: k racing writers scatter k candidate tables but only one
    installs — the rest discard and redo (``ps_device_wasted_launches``
    counts them).  ``combine=True`` routes ApplyGrad through the
    inherited :class:`GradCombiner` so the leader launches ONE scatter
    per drained batch; ``stream=True`` serves ``StreamApply`` into the
    same combiner.
    """

    def __init__(self, vocab: int, dim: int, shard_index: int,
                 num_shards: int, lr: float = 0.1, seed: int = 0,
                 device_client: "rpc.DeviceClient | None" = None,
                 device_index: int = 0, combine: bool = False,
                 stream: bool = False, importing: bool = False,
                 scheme_version: int = 0, limiter=None):
        self._owns_dev = device_client is None
        self.dev = device_client or rpc.DeviceClient()
        self.device_index = device_index
        # Device state must exist before the base constructor runs: it
        # assigns ``self.table`` (routed through the property setter
        # into the host mirror) and starts the server — early requests
        # simply serve from the mirror until the stage-up below.
        self._dev_mu = checked_lock("ps.device_shard")
        self._dev_serving = False
        self._dev_cur: Optional[int] = None
        self._dev_seq = 0
        self._tables: Dict[int, _TableGen] = {}
        self._host_table: Optional[np.ndarray] = None
        self._rebase_pending = False
        self._gather = {}   # bucket size -> compiled gather executable
        self._scatter = {}  # bucket size -> compiled scatter-sub exe
        # Guards the executable caches; held across the (cold,
        # per-bucket) compile but never across execute/fetch.
        self._exe_mu = checked_lock("ps.device_shard.exe")
        self.lr_h = 0
        super().__init__(vocab, dim, shard_index, num_shards, lr=lr,
                         seed=seed, lock_mode="rw", native_read=False,
                         combine=combine, stream=stream,
                         importing=importing,
                         scheme_version=scheme_version,
                         limiter=limiter)
        # Resident lr scalar: scatter_sub's 4th operand (stays in HBM).
        self.lr_h = self.dev.stage(np.array(lr, np.float32),
                                   device_index)
        if not self._importing:
            # Open for business from HBM immediately (a server starts
            # in the legacy single-owner primary mode); an importing
            # split destination stays on the host mirror until
            # CompleteImport opens it.
            with self._mu.write():
                self._stage_up_locked()

    # -- pin map / serving-mode machinery ---------------------------------

    def _pin_current(self):
        """Pin the live device table: ``(key, handle)`` with the handle
        guaranteed alive until the matching :meth:`_unpin`, or None
        when the shard is not serving from HBM.  Pin under the table
        read (or write) lock whenever the pinned buffer must
        correspond to ``_install_gen`` — installs hold the write lock,
        so the pair is consistent there."""
        with self._dev_mu:
            key = self._dev_cur
            if key is None:
                return None
            entry = self._tables[key]
            entry.pins += 1
            return key, entry.handle

    def _unpin(self, key: int) -> None:
        release = 0
        with self._dev_mu:
            entry = self._tables[key]
            entry.pins -= 1
            if entry.retired and entry.pins == 0:
                del self._tables[key]
                release = entry.handle
        if release:
            self.dev.release(release)

    def _stage_up_locked(self) -> None:
        """Stage the host mirror into HBM and serve from it.  Caller
        holds the table WRITE lock.  Already serving: the fresh host
        image replaces the resident table (a wholesale install landed
        while staged, e.g. a re-issued checkpoint restore)."""
        handle = self.dev.stage(self._host_table, self.device_index)
        if self._dev_serving:
            self._swap_dev_locked(handle)
            return
        with self._dev_mu:
            self._dev_seq += 1
            self._dev_cur = self._dev_seq
            self._tables[self._dev_cur] = _TableGen(handle)
        self._dev_serving = True

    def _swap_dev_locked(self, handle: int) -> None:
        """Install a fresh table buffer as the current generation.
        Caller holds the table WRITE lock; the retiring buffer is
        released once its last pin drops."""
        release = 0
        with self._dev_mu:
            old = self._tables[self._dev_cur]
            old.retired = True
            if old.pins == 0:
                del self._tables[self._dev_cur]
                release = old.handle
            self._dev_seq += 1
            self._dev_cur = self._dev_seq
            self._tables[self._dev_cur] = _TableGen(handle)
        if release:
            self.dev.release(release)

    def _retire_dev_locked(self) -> None:
        """Retire every device generation (mirror-down / close).
        Caller holds the table write lock; pinned entries release when
        their last pin drops."""
        release = []
        with self._dev_mu:
            self._dev_cur = None
            for k in list(self._tables):
                entry = self._tables[k]
                entry.retired = True
                if entry.pins == 0:
                    del self._tables[k]
                    release.append(entry.handle)
        for h in release:
            self.dev.release(h)

    def _mirror_down(self) -> None:
        """Leave HBM-serving mode: DMA the live table into the host
        mirror and retire every device generation, so the inherited
        CPU paths (Sync installs, ReplicaApply deltas, checkpoint
        replay) mutate a live array again.  The fetch runs OUTSIDE the
        lock against a pinned buffer; an install racing the fetch
        restarts it — the loop terminates because callers mirror down
        exactly when writes are stopping (demotion, fence adoption, a
        checkpoint attach serializing with appliers)."""
        while True:
            with self._mu.write():
                if not self._dev_serving:
                    return
                pinned = self._pin_current()
            key, table_h = pinned
            raw = None
            try:
                raw = self.dev.fetch(table_h)
            finally:
                if raw is None:
                    self._unpin(key)
            with self._mu.write():
                if not self._dev_serving:
                    self._unpin(key)
                    return
                with self._dev_mu:
                    moved = self._dev_cur != key
                if moved:
                    self._unpin(key)
                    continue
                self._host_table[:] = np.frombuffer(
                    raw, np.float32).reshape(self.rows_per, self.dim)
                self._dev_serving = False
                self._retire_dev_locked()
            self._unpin(key)
            if obs.enabled():
                obs.counter("ps_device_mirror_downs").add(1)
            return

    @property
    def table(self) -> np.ndarray:
        """Host view of the table.  In host-mirror mode (backup /
        importing / demoted) this IS the live mutable array — the base
        class applies into it in place under the write lock.  In
        HBM-serving mode it is a pinned DMA snapshot COPY (test/debug
        use; never called on a locked path while serving)."""
        if not self._dev_serving:
            return self._host_table
        pinned = self._pin_current()
        if pinned is None:
            return self._host_table
        key, table_h = pinned
        try:
            raw = self.dev.fetch(table_h)
        finally:
            self._unpin(key)
        return np.frombuffer(raw, np.float32).reshape(self.rows_per,
                                                      self.dim).copy()

    @table.setter
    def table(self, value: np.ndarray) -> None:
        self._host_table = value

    def _gather_exe(self, k: int):
        with self._exe_mu:
            exe = self._gather.get(k)
            if exe is None:
                mlir = self.dev.mlir("gather_rows", self.rows_per,
                                     self.dim, k)
                exe = self._gather[k] = self.dev.compile(mlir)
            return exe

    def _scatter_exe(self, k: int):
        with self._exe_mu:
            exe = self._scatter.get(k)
            if exe is None:
                mlir = self.dev.mlir("scatter_sub", self.rows_per,
                                     self.dim, k)
                exe = self._scatter[k] = self.dev.compile(mlir)
            return exe

    @staticmethod
    def _bucket(count: int) -> int:
        """Round the batch size up to a power of two so the executable
        cache stays log-bounded instead of compiling per distinct count
        (padding: extra ids hit row 0 with zero gradients — a no-op)."""
        return 1 << max(0, count - 1).bit_length()

    # -- replication / migration / durability parity ----------------------

    def _install_full(self, gen: int) -> None:
        """A wholesale host-image install landed (under the write
        lock).  On the device tier 'publish' means stage the fresh
        host mirror into HBM — but only for a PRIMARY that is open for
        business; backups and importing split destinations keep the
        cheap host mirror (promotion / CompleteImport stages later)."""
        super()._install_full(gen)
        if self._primary_flag and not self._importing:
            self._stage_up_locked()

    def _on_promoted(self) -> None:
        """Promotion point: the backup's host mirror (hydrated by the
        ReplicaApply stream) becomes the serving table — stage it into
        HBM before the promote response releases clients to retry."""
        staged = False
        with self._mu.write():
            if not self._dev_serving and not self._importing:
                self._stage_up_locked()
                staged = True
        if staged and obs.enabled():
            obs.counter("ps_device_promote_stages").add(1)

    def configure_replication(self, replica_set: ReplicaSet,
                              replica_index: int, *,
                              timeout_ms: Optional[int] = None,
                              ack_timeout_s: Optional[float] = None,
                              quorum: "int | str | None" = "auto"
                              ) -> None:
        super().configure_replication(replica_set, replica_index,
                                      timeout_ms=timeout_ms,
                                      ack_timeout_s=ack_timeout_s,
                                      quorum=quorum)
        if not self._primary_flag:
            # Demoted to backup: fold the live HBM table into the host
            # mirror so the inherited Sync/ReplicaApply paths mutate a
            # live array.
            self._mirror_down()

    def _check_repl_epoch(self, epoch: int) -> None:
        super()._check_repl_epoch(epoch)
        if not self._primary_flag:
            # Adopted a newer epoch (self-demotion): same fold as an
            # explicit demotion.  Runs lock-free, exactly like the
            # base's demote.stop() at this point.
            self._mirror_down()

    def _demote_on_fence(self) -> None:
        super()._demote_on_fence()
        if not self._primary_flag:
            self._mirror_down()

    def attach_checkpoint(self, store, *, recover: bool = True):
        """Attach the checkpoint store, device edition: restore/replay
        mutate the host image in place, so leave HBM-serving mode for
        the duration (the mirror-down folds the live table into the
        host mirror first — nothing applied before the attach is
        lost).  The restore's install hook re-stages a primary; a
        shard with nothing to recover re-stages here."""
        self._mirror_down()
        point = super().attach_checkpoint(store, recover=recover)
        with self._repl_mu:
            with self._mu.write():
                if (not self._dev_serving and not self._importing
                        and self._primary_flag):
                    self._stage_up_locked()
        return point

    def _tee_delta(self, dur, gen: int, body: bytes) -> None:
        if not self._dev_serving:
            return super()._tee_delta(dur, gen, body)
        if (not dur.append_delta(gen, body, epoch=self._epoch)
                or dur.should_compact()):
            # The base helper folds the table into a fresh base HERE,
            # under the write lock — but this table is in HBM and the
            # DMA must not run under a checked lock.  Defer: the
            # applier re-bases outside the lock before acking.
            self._rebase_pending = True

    def _maybe_device_rebase(self) -> None:
        """Perform a deferred checkpoint re-base (set by the serving
        tee): capture (epoch, gen, windows) + a pin under the write
        lock, DMA the table down outside it, write the base.
        Concurrent appliers may interleave re-bases out of order; the
        store converges — restore picks the NEWEST valid base and the
        chain check skips deltas already folded in — and every acked
        batch runs this before its ack, so the durable image always
        covers the acked generation."""
        dur = self._durable
        if dur is None or not self._rebase_pending:
            return
        with self._mu.write():
            if not self._rebase_pending:
                return
            self._rebase_pending = False
            if not self._dev_serving:
                self._snapshot_to(dur, self._install_gen)
                return
            epoch = self._epoch
            gen = self._install_gen
            with self._seq_mu:
                windows = dict(self._writer_applied)
            key, table_h = self._pin_current()
        try:
            raw = self.dev.fetch(table_h)
        finally:
            self._unpin(key)
        dur.save_snapshot(
            epoch, gen,
            np.frombuffer(raw, np.float32).reshape(self.rows_per,
                                                   self.dim),
            windows, seeded=self._seeded or self._primary_flag)

    def _replication_snapshot(self):
        """Device-aware Sync snapshot: (epoch, gen, table bytes,
        windows), consistent because installs hold the table write
        lock.  In HBM-serving mode the generation is pinned under the
        locks and FETCHED OUTSIDE them (a blocking DMA under a checked
        lock is a RACECHECK violation) — safe because a pinned
        buffer is immutable (updates are functional) and the pin keeps
        it alive across the fetch."""
        with self._repl_mu:
            epoch = self._epoch
            with self._mu.read():
                with self._seq_mu:
                    windows = dict(self._writer_applied)
                gen = self._install_gen
                if not self._dev_serving:
                    return (epoch, gen, self._host_table.tobytes(),
                            windows)
                key, table_h = self._pin_current()
        try:
            raw = self.dev.fetch(table_h)
        finally:
            self._unpin(key)
        return (epoch, gen, bytes(raw), windows)

    def _migration_snapshot(self, row0: int, count: int):
        """Generation-pinned MigrateSync source read: pin one table
        generation under the read lock, DMA it down outside the lock,
        slice the requested range host-side.  Fetching the WHOLE table
        per range sync is an honest cost (no range-gather launch yet —
        see ROADMAP residue); correctness matches the CPU tier: the
        (gen, rows, windows) triple is consistent because installs
        hold the write lock."""
        lo = row0 - self.base
        if lo < 0 or row0 + count > self.base + self.rows_per:
            raise ValueError(
                f"migration range [{row0}, {row0 + count}) outside "
                f"shard [{self.base}, {self.base + self.rows_per})")
        with self._mu.read():
            with self._seq_mu:
                windows = dict(self._writer_applied)
            gen = self._install_gen
            if not self._dev_serving:
                return (gen,
                        self._host_table[lo:lo + count].tobytes(),
                        windows)
            key, table_h = self._pin_current()
        try:
            raw = self.dev.fetch(table_h)
        finally:
            self._unpin(key)
        rows = np.frombuffer(raw, np.float32).reshape(
            self.rows_per, self.dim)[lo:lo + count]
        return (gen, rows.tobytes(), windows)

    def _apply_batch(self, ids: np.ndarray, grads: np.ndarray,
                     metas=()) -> None:
        """ONE combined application for a drained batch, device
        edition: the scatter-sub launches OUTSIDE the table lock
        against a pinned generation; the install + the replication /
        durability / migration tee run under the write lock — so
        backups, the delta log and migration shippers see device
        batches in exactly apply order, framed identically to the CPU
        tier (schema replica_apply_body).  The on-chip scatter sums
        duplicate ids, so the concatenated batch applies exactly;
        padding ids hit row 0 with zero grads (a no-op).

        The launch races other appliers exactly like the pre-parity
        optimistic loop: a lost install discards the candidate table
        and redoes the scatter (``ps_device_wasted_launches``); the
        combiner exists to keep that counter flat under fan-in.  When
        the shard is NOT serving from HBM (backup host mirror,
        importing destination, demoted), the inherited CPU-tier apply
        runs unchanged against the host mirror."""
        if not ids.size:
            return
        with self._repl_mu:
            if self._replica_set is not None and not self._primary_flag:
                raise rpc.RpcError(
                    resilience.ENOTPRIMARY,
                    f"shard {self.shard_index} replica "
                    f"{self._replica_index} was demoted (epoch "
                    f"{self._epoch}); refusing the apply")
        if not self._dev_serving:
            return super()._apply_batch(ids, grads, metas=metas)
        updates: Dict[str, int] = {}
        for m in metas:
            if m[1] > updates.get(m[0], 0):
                updates[m[0]] = m[1]
        bucket = self._bucket(int(ids.size))
        padded_ids = np.zeros(bucket, np.int32)
        padded_ids[:ids.size] = ids
        padded_g = np.zeros((bucket, self.dim), np.float32)
        padded_g[:ids.size] = grads
        rep = mig = dur = None
        gen = 0
        ids_h = self.dev.stage(padded_ids, self.device_index)
        try:
            g_h = self.dev.stage(padded_g, self.device_index)
            try:
                while True:
                    pinned = self._pin_current()
                    if pinned is None:
                        # Raced a mirror-down (demotion / checkpoint
                        # attach): the host path owns the table now.
                        return super()._apply_batch(ids, grads,
                                                    metas=metas)
                    key, table_h = pinned
                    try:
                        # scatter_sub scales by the resident lr scalar
                        # on-chip: out = table - scatter(lr * grads);
                        # functional — the output is a CANDIDATE table.
                        outs = self._scatter_exe(bucket).execute(
                            [table_h, ids_h, g_h, self.lr_h])
                    finally:
                        self._unpin(key)
                    new_table = outs[0][0]
                    installed = False
                    serving = True
                    with self._mu.write():
                        # Same fence discipline as the CPU tier: an
                        # apply that raced SchemeFence refuses inside
                        # the lock and the caller re-resolves.
                        if self._scheme_fenced:
                            self.dev.release(new_table)
                            raise rpc.RpcError(
                                resilience.ESCHEMEMOVED,
                                f"shard {self.shard_index} scheme "
                                f"v{self.scheme_version} was fenced "
                                f"mid-apply; refusing the write")
                        serving = self._dev_serving
                        if serving:
                            with self._dev_mu:
                                stale = self._dev_cur != key
                            if not stale:
                                self._install_gen += 1
                                gen = self._install_gen
                                self._swap_dev_locked(new_table)
                                if updates:
                                    with self._seq_mu:
                                        for w, q in updates.items():
                                            if q > self._writer_applied\
                                                    .get(w, 0):
                                                self._writer_applied[
                                                    w] = q
                                rep = self._replicator
                                mig = self._migrator
                                dur = self._durable
                                if (rep is not None or mig is not None
                                        or dur is not None):
                                    gids = (ids + self.base).astype(
                                        np.int32)
                                if rep is not None or dur is not None:
                                    body = _pack_windows(
                                        updates) + bytes(
                                        _pack_apply_req(gids, grads))
                                if rep is not None:
                                    rep.ship(gen, body)
                                if dur is not None:
                                    self._tee_delta(dur, gen, body)
                                if mig is not None:
                                    mig.ship(gen, gids, grads, updates)
                                installed = True
                    if installed:
                        break
                    self.dev.release(new_table)
                    if not serving:
                        return super()._apply_batch(ids, grads,
                                                    metas=metas)
                    # Install race lost: a concurrent applier swapped
                    # first and our output was computed against a
                    # stale table.  Discard and redo — the winner made
                    # progress, so this terminates.
                    if obs.enabled():
                        obs.counter("ps_device_wasted_launches").add(1)
            finally:
                self.dev.release(g_h)
        finally:
            self.dev.release(ids_h)
        # Durability before the ack: a pending re-base (refused append
        # or compaction threshold) folds the HBM table into a fresh
        # base now, outside the lock, before the replication barrier
        # releases the caller.
        self._maybe_device_rebase()
        if rep is not None:
            rep.flush(gen, timeout_s=self.repl_ack_timeout_s)

    def _serve(self, method: str, payload: bytes,
               deadline_us: int = 0) -> bytes:
        # Control plane (Sync / Promote / MigrateSync / ApplyGradId /
        # WriterSeq / ...) is the inherited CPU machinery verbatim —
        # it mutates the host mirror and the shared replication state.
        if method not in ("Lookup", "ApplyGrad"):
            return super()._serve(method, payload, deadline_us)
        # Same wire guards as the CPU shard (schemas lookup_req /
        # apply_req): counts bounded by the bytes present BEFORE any
        # staging allocation or device launch.
        (count,) = wire.read("<i", payload, 0, f"{method}.count")
        wire.check_count(count, (len(payload) - 4) // 4,
                         f"{method}.count")
        if method == "Lookup" and len(payload) != 4 + 4 * count:
            raise wire.WireError(
                f"Lookup request length mismatch (count={count}, "
                f"{len(payload)} bytes)")
        if method == "ApplyGrad":
            wire.need(payload, 4 + 4 * count, count * self.dim * 4,
                      "ApplyGrad.grads")
        ids = np.frombuffer(payload, np.int32, count, 4) - self.base
        if ids.size and (ids.min() < 0 or ids.max() >= self.rows_per):
            raise ValueError(
                f"ids outside shard [{self.base}, "
                f"{self.base + self.rows_per}) for shard base {self.base}"
            )
        if method == "Lookup":
            if self._importing:
                self._check_scheme()
            with self._seq_mu:
                self._read_count += 1
            pinned = None
            with self._mu.read():
                if self._dev_serving:
                    pinned = self._pin_current()
                else:
                    gathered = self._host_table[ids]
            if pinned is None:
                # Host-mirror read (backup serving a failover window /
                # importing destination): identical to the CPU tier.
                if zerocopy_enabled() and \
                        gathered.nbytes >= _ZC_MIN_BYTES:
                    out = rpc.IOBuf()
                    out.append_pinned(gathered)
                    return out
                return gathered.tobytes()
            key, table_h = pinned
            bucket = self._bucket(count)
            padded_ids = np.zeros(bucket, np.int32)
            padded_ids[:count] = ids
            ids_h = self.dev.stage(padded_ids, self.device_index)
            try:
                outs = self._gather_exe(bucket).execute(
                    [table_h, ids_h])
            finally:
                self.dev.release(ids_h)
                self._unpin(key)
            rows_h = outs[0][0]
            try:
                raw = self.dev.fetch(rows_h)
            finally:
                self.dev.release(rows_h)
            if zerocopy_enabled() and \
                    count * self.dim * 4 >= _ZC_MIN_BYTES:
                # Borrow the fetched bytes (pinning them) instead of
                # slicing off a truncated copy + the respond append.
                out = rpc.IOBuf()
                out.append_pinned(
                    memoryview(raw)[:count * self.dim * 4])
                return out
            return raw[:count * self.dim * 4]
        # ApplyGrad: writes belong to the primary of the current
        # scheme, identical contract to the CPU tier.
        self._check_primary()
        self._check_scheme()
        grads = np.frombuffer(payload, np.float32, count * self.dim,
                              4 + 4 * count)
        if self.combine:
            # Combined write path: no per-request staging/launch — the
            # combiner's leader stages and launches once per batch.
            self._combiner.add(ids, grads.reshape(count, self.dim),
                               deadline_us=deadline_us)
        else:
            self._apply_batch(ids, grads.reshape(count, self.dim))
        if self._replica_set is not None:
            with self._mu.read():
                return struct.pack("<q", self._install_gen)
        return b""

    def close(self):
        # Server + combiner + replicator/migrator latch first (the
        # inherited close), so late frames drop instead of scattering
        # into released buffers; device teardown after.
        super().close()
        for exe in list(self._gather.values()) + list(
                self._scatter.values()):
            exe.close()
        self._gather = {}
        self._scatter = {}
        with self._mu.write():
            self._dev_serving = False
            self._retire_dev_locked()
        if self.lr_h:
            self.dev.release(self.lr_h)
            self.lr_h = 0
        if self._owns_dev:
            self.dev.close()


class _PushStreamReceiver:
    """Client read half of a gradient push stream: the only frame the
    server ever writes back is a FENCE notification (a negative int64 —
    -1: the primary was demoted mid-stream and dropped frames; -2: the
    partition scheme was retired by a cutover).  Seeing it flips
    ``fenced`` so the pusher fails over (or refreshes its scheme)
    instead of trusting the close barrier."""

    __slots__ = ("fenced", "scheme_moved")

    def __init__(self):
        self.fenced = False
        self.scheme_moved = False

    def on_data(self, data: bytes) -> None:
        if len(data) >= 8:
            (val,) = struct.unpack_from("<q", data, 0)
            if val < 0:
                self.fenced = True
                if val == -2:
                    self.scheme_moved = True

    def on_closed(self) -> None:
        pass


class _SchemeMovedError(Exception):
    """A write batch hit a scheme boundary mid-flight (cutover fence or
    a still-importing destination): ``remainder`` holds the UNAPPLIED
    units ``(global_ids, grads, guards)`` to re-route once the write
    view settles; everything else in the batch is already acked."""

    def __init__(self, code: int, remainder):
        super().__init__(f"partition scheme moved (code {code})")
        self.code = code
        self.remainder = remainder


class _SchemeView:
    """Per-scheme routing state inside :class:`RemoteEmbedding`: the
    scheme's replica sets plus everything the router tracks per shard —
    believed primary, observed fencing epochs, acked-gen floors, unary
    write seq counters — and a scheme-scoped scorer so one scheme's
    latency history never poisons another's (the ISSUE's "breaker/
    scorer keyed per scheme-replica").  Usually one view exists; during
    a live reshard two serve reads side by side with traffic weighted
    by ``scheme.weight``."""

    __slots__ = ("scheme", "version", "replica_sets", "n", "rows_per",
                 "bounds", "weight", "state", "addresses", "scorer",
                 "useq", "_primary_idx", "_epoch_seen", "_gen_seen")

    def __init__(self, emb: "RemoteEmbedding", scheme: PartitionScheme):
        self.scheme = scheme
        self.version = scheme.version
        self.replica_sets: List[ReplicaSet] = list(scheme.replica_sets)
        self.n = len(self.replica_sets)
        if scheme.bounds is not None:
            if scheme.bounds[-1] != emb.vocab:
                raise ValueError(
                    f"scheme v{scheme.version} bounds end at "
                    f"{scheme.bounds[-1]}, vocab is {emb.vocab}")
            self.bounds = np.asarray(scheme.bounds, np.int64)
            self.rows_per = 0
        else:
            if emb.vocab % self.n:
                raise ValueError(
                    f"scheme v{scheme.version}: {self.n} shards must "
                    f"divide vocab {emb.vocab} (or carry bounds)")
            self.bounds = None
            self.rows_per = emb.vocab // self.n
        self.weight = float(scheme.weight)
        self.state = scheme.state
        #: boot-time primary addresses (the legacy per-shard surface)
        self.addresses = [rs.addresses[rs.primary]
                          for rs in self.replica_sets]
        self.scorer = emb.scorer.scoped(
            "" if scheme.version == 0 else f"v{scheme.version}")
        #: per-shard unary write seq counters (ApplyGradId windows)
        self.useq: Dict[int, int] = {}
        self._primary_idx = [rs.primary for rs in self.replica_sets]
        self._epoch_seen = [0] * self.n
        self._gen_seen = [0] * self.n

    def update(self, scheme: PartitionScheme) -> None:
        """Adopt a re-published record's weight/state (the topology of
        a version never changes — a new topology is a new version)."""
        self.scheme = scheme
        self.weight = float(scheme.weight)
        self.state = scheme.state

    def shard_bounds(self, s: int, vocab: int):
        return self.scheme.shard_bounds(s, vocab)


class _SchemeWatcher(threading.Thread):
    """Registry watcher feeding a :class:`RemoteEmbedding`: blocks on
    the cluster's version and ingests scheme records (weight/state
    transitions drive the dual-scheme read router) and primary/epoch
    claims (failover adopts the claimed primary instead of sweeping).
    ``refresh()`` is the synchronous poke used by the scheme-moved
    write path — it lists the cluster on the CALLER's thread (the
    NamingClient keeps one connection per thread), so a redirect error
    converges without waiting out the watch cadence."""

    def __init__(self, emb: "RemoteEmbedding", registry_addr: str,
                 cluster: str, wait_ms: int = 2000):
        super().__init__(daemon=True, name="brt-scheme-watcher")
        from brpc_tpu.naming import NamingClient
        self._emb = emb
        self._cluster = cluster
        self._wait_ms = wait_ms
        self._reg = NamingClient(registry_addr)
        self._stop = threading.Event()

    def run(self) -> None:
        version = 0
        while not self._stop.is_set():
            try:
                nodes, version = self._reg.watch(
                    self._cluster, known_version=version,
                    wait_ms=self._wait_ms)
            except Exception:  # noqa: BLE001 — registry outage: retry
                if self._stop.wait(0.2):
                    break
                continue
            try:
                self._emb._ingest_nodes(nodes)
            except Exception:  # noqa: BLE001 — a bad published record
                # must not kill the watch loop: the client would then
                # silently miss every later cutover/retire/claim.
                if obs.enabled():
                    obs.counter("ps_scheme_ingest_errors").add(1)

    def refresh(self) -> None:
        try:
            nodes, _ = self._reg.list(self._cluster)
            self._emb._ingest_nodes(nodes)
        except Exception:  # noqa: BLE001 — caller keeps its stale view
            return

    def stop(self) -> None:
        self._stop.set()
        self._reg.close()


class RemoteEmbedding:
    """Client view of a sharded remote table (owner-routed access).

    Per-shard requests fan out CONCURRENTLY via ``Channel.call_async``
    (the ParallelChannel-over-PartitionChannel shape, cpp/cluster/
    parallel_channel.* + partition_channel.*): whole-batch latency is
    max(shard RTT) instead of sum(shard RTT).  ``parallel=False``
    restores the sequential per-shard loop (the bench baseline).

    Fault tolerance (brpc_tpu.resilience) is per shard:

    - ``retry`` — a failed shard attempt is retried with backoff under
      the batch's remaining ``deadline_ms`` budget while the other
      shards' responses are already in; a batch completes despite a
      shard failing its first attempt.
    - ``backup_ms`` — a shard that has not answered in N ms gets a
      hedged second attempt; the first completion wins and the loser is
      cancelled natively.
    - ``breakers`` — a BreakerRegistry keyed by shard address: open
      shards fail fast instead of burning the timeout, every outcome
      feeds the shard's EMA windows, and ``health_check=True`` runs a
      background prober that revives isolated shards via their
      ``_status.health`` builtin.
    - On a non-retriable partial failure the batch abandons its
      straggler shards: still-pending calls are CANCELLED (native
      ``StartCancel``) before being reaped, so the error surfaces at
      max(shard) latency, not sum.
    - Retries of k failed shards re-fan CONCURRENTLY (one backoff sleep,
      one native call group per round), so retry latency is max(shard).

    REPLICATION (availability over fail-fast): pass
    :class:`naming.ReplicaSet` entries (or address sequences) instead of
    bare addresses and the embedding becomes replica-aware — reads route
    to any live replica by latency+inflight score
    (:class:`resilience.ReplicaScorer`), an open breaker REDIRECTS to a
    sibling instead of raising ``BreakerOpen``, and writes follow the
    primary: a failed/demoted primary triggers client-driven failover
    (``ReplicaState`` sweep, fenced ``Promote`` of the freshest backup).
    A non-redirect ``BreakerRegistry(redirect=False)`` restores
    fail-fast.  The health prober revives isolated replicas back into
    the read set.

    The WRITE path additionally has a streaming mode:
    :meth:`push_gradients` ships framed deltas over one persistent
    ordered flow-controlled stream per owner shard (feeding the server's
    gradient combiner directly — no per-call dispatch), with
    :meth:`flush_gradients` as the applied-everything barrier and
    reconnect-under-the-retry-budget on stream breakage.  The unary
    :meth:`apply_gradients` stays as the synchronous path."""

    @classmethod
    def from_registry(cls, registry_addr: str, cluster: str, vocab: int,
                      dim: int, timeout_ms: int = 2000,
                      wait_ms: int = 5000, watch: bool = False,
                      **kwargs) -> "RemoteEmbedding":
        """Resolves the shard topology from the native naming registry
        (brpc_tpu.naming).  PREFERRED form: the cluster carries
        :class:`naming.PartitionScheme` records (``scheme#<version>``
        nodes) — every published scheme becomes a routing view, so a
        client booted mid-reshard serves both schemes immediately.
        Legacy form: shards register with tag "<shard>/<num>" (the boot
        primary) or "<shard>/<num>/<replica>" (backups), and the watch
        blocks until a CONSISTENT full set is present.  ``watch=True``
        attaches a registry watcher after construction: scheme
        transitions (cutover, drain, retire) and primary/epoch claims
        flow into the router live.  ``kwargs`` pass through to the
        constructor (retry/breakers/...)."""
        from brpc_tpu.naming import NamingClient
        reg = NamingClient(registry_addr)
        deadline = time.monotonic() + wait_ms / 1000.0
        version = 0
        groups: dict = {}
        # Each watch IS the poll; its blocking window follows the shared
        # backoff helper (exponential + deterministic jitter, capped by
        # the remaining deadline) instead of a fixed interval — early
        # polls catch a cluster mid-registration fast, later ones stop
        # hammering a registry that clearly isn't filling up.  The
        # NamingClient reuses one connection per thread across polls.
        backoff = resilience.Backoff(base_ms=100.0, multiplier=2.0,
                                     max_ms=2000.0, jitter=0.5)
        poll = 0
        emb: "Optional[RemoteEmbedding]" = None
        while True:
            remaining_ms = (deadline - time.monotonic()) * 1000.0
            watch_ms = max(1, int(min(backoff.delay_ms(poll),
                                      max(remaining_ms, 1.0))))
            poll += 1
            nodes, version = reg.watch(cluster, known_version=version,
                                       wait_ms=watch_ms)
            schemes = parse_schemes(nodes)
            live = [sc for sc in schemes.values()
                    if sc.state != "retired"]
            if any(sc.state == "active" for sc in live):
                emb = cls(sorted(live, key=lambda sc: sc.version),
                          vocab, dim, timeout_ms=timeout_ms, **kwargs)
                break
            # Group by the tag's "/num" so a stale entry from an old
            # sharding cannot block a complete consistent new set.
            groups = {}
            for n in nodes:
                parsed = parse_shard_tag(n.get("tag", ""))
                if parsed is None:
                    continue
                sh, nm, rep = parsed
                # Duplicate (shard, replica) within one sharding: a
                # restarted shard's fresh registration supersedes a
                # TTL-lingering stale one; the registry lists entries in
                # registration order, so the LAST occurrence is newest.
                groups.setdefault(nm, {}).setdefault(sh, {})[rep] = \
                    n["addr"]
            for num, shard_map in sorted(groups.items(), reverse=True):
                if num > 0 and len(shard_map) == num and \
                        all(i in shard_map and 0 in shard_map[i]
                            for i in range(num)):
                    sets = []
                    for i in range(num):
                        reps = shard_map[i]
                        sets.append(ReplicaSet(
                            tuple(reps[r] for r in sorted(reps)),
                            primary=sorted(reps).index(0)))
                    emb = cls(sets, vocab, dim, timeout_ms=timeout_ms,
                              **kwargs)
                    break
            if emb is not None:
                break
            if time.monotonic() > deadline:
                reg.close()
                raise TimeoutError(
                    f"cluster '{cluster}' has no complete sharding: "
                    f"{ {nm: sorted(m) for nm, m in groups.items()} }")
        emb._ingest_nodes(nodes)
        reg.close()
        if watch:
            emb.attach_registry(registry_addr, cluster)
        return emb

    def __init__(self, addresses: Sequence, vocab: int, dim: int,
                 timeout_ms: int = 2000, parallel: bool = True, *,
                 retry: "Optional[resilience.RetryPolicy]" = None,
                 deadline_ms: Optional[float] = None,
                 backup_ms: Optional[float] = None,
                 breakers: "Optional[resilience.BreakerRegistry]" = None,
                 health_check: bool = False,
                 health_interval_ms: float = 200.0,
                 push_window_bytes: int = 0,
                 scorer: "Optional[resilience.ReplicaScorer]" = None,
                 propagate_deadline: bool = True,
                 deadline_mode: str = "absolute"):
        self.vocab = vocab
        self.dim = dim
        self.parallel = parallel
        self.timeout_ms = timeout_ms
        #: deadline propagation: with a ``deadline_ms`` budget set,
        #: every data-plane request (and every retry/hedge leg,
        #: re-stamped at issue time) carries its REMAINING budget as a
        #: wall-clock deadline header, so servers shed queued work that
        #: can no longer answer in time (EDEADLINE) instead of
        #: executing it into a void.  Same-host clocks agree exactly;
        #: cross-host the "absolute" form assumes NTP-grade wall-clock
        #: agreement while "relative" (the v2 header) drops it — the
        #: server arrival-stamps the remaining budget with its own
        #: clock.
        self.propagate_deadline = bool(propagate_deadline)
        if deadline_mode not in ("absolute", "relative"):
            raise ValueError(
                f"deadline_mode {deadline_mode!r}: expected "
                f"'absolute' or 'relative'")
        self.deadline_mode = deadline_mode
        #: per-shard unconsumed-bytes window for push streams (0 = the
        #: native 2MB default) — the backpressure knob of push_gradients
        self.push_window_bytes = push_window_bytes
        self._push_streams: dict = {}
        self._push_addr: Dict[int, str] = {}
        self._push_recv: Dict[int, "_PushStreamReceiver"] = {}
        # Framed idempotent push: one stable writer identity; the wire
        # writer KEYS are per (scheme, shard) so seq spaces from
        # different schemes/shards never collide in a migrated window
        # (see _stream_writer_key / _unary_writer_key).
        self._writer_id = f"w{uuid.uuid4().hex[:12]}"
        self._push_seq: Dict[int, int] = {}
        #: highest seq written to the CURRENT stream per shard (reset to
        #: the server's high-water on every (re)connect — the replay
        #: cursor)
        self._push_sent: Dict[int, int] = {}
        #: frames pushed since the last successful flush barrier, per
        #: shard: (seq, body) in order.  A failover mid-window replays
        #: these above the new primary's inherited high-water — pushed-
        #: but-unflushed deltas survive the primary, not just the
        #: stream.  Cleared only when the flush barrier confirms.  A
        #: SCHEME move re-routes them as guarded unary writes.
        self._push_unacked: Dict[int, List[tuple]] = {}
        #: transfer units (ids, grads, guards) that survived a FAILED
        #: scheme-boundary transfer: the guards make re-driving them
        #: idempotent, and the next flush/transfer must drain them
        #: before it may report success — a failed transfer never
        #: silently drops pushed deltas.
        self._push_carry: List[tuple] = []
        self.retry = retry
        self.deadline_ms = deadline_ms
        self.backup_ms = backup_ms
        self.scorer = scorer or resilience.ReplicaScorer()
        # Partition-scheme views (the DynamicPartitionChannel shape):
        # `addresses` is either the legacy form — one entry per shard
        # range (bare address / ReplicaSet / address sequence), wrapped
        # into scheme version 0 — or a sequence of PartitionScheme
        # records (a client booted mid-reshard serves them all).
        items = list(addresses)
        if items and all(isinstance(a, PartitionScheme) for a in items):
            schemes = sorted(items, key=lambda sc: sc.version)
        else:
            schemes = [PartitionScheme(
                version=0,
                replica_sets=tuple(ReplicaSet.of(a) for a in items))]
        self._view_mu = checked_lock("ps.views")
        self._views: List[_SchemeView] = []
        self._claims: Dict[tuple, tuple] = {}
        self._watcher: Optional[_SchemeWatcher] = None
        self._read_seq = 0
        self._chans: Dict[str, rpc.Channel] = {}
        views = [_SchemeView(self, sc) for sc in schemes]
        self.replicated = any(len(rs.addresses) > 1
                              for v in views for rs in v.replica_sets)
        self.breakers = breakers
        if health_check and breakers is None:
            self.breakers = breakers = resilience.BreakerRegistry(
                redirect=self.replicated)
        # REDIRECT mode (the SelectiveChannel behavior): reads route to
        # any live replica by latency+inflight score, an open breaker
        # re-routes instead of rejecting, and a failed/isolated primary
        # fails WRITES over via fenced promotion.  On by default when
        # replicas exist, unless a non-redirect BreakerRegistry
        # explicitly asks for fail-fast.
        self._redirect = self.replicated and (
            self.breakers is None or self.breakers.redirect)
        for v in views:
            self._admit_view(v)
        with self._view_mu:
            self._views = views
            # newest ACTIVE scheme owns writes
            act = [v for v in views if v.state == "active"] or views
            self._wv = max(act, key=lambda v: v.version)
        self._prober: "Optional[resilience.HealthProber]" = None
        if health_check:
            self._prober = resilience.HealthProber(
                self.breakers, interval_ms=health_interval_ms)
            self._prober.start()

    def _admit_view(self, view: _SchemeView) -> None:
        """Channels + breakers for every replica of a (new) view: the
        cluster-recover guard counts working endpoints, so the breaker
        registry must know the full cluster up front."""
        for rs in view.replica_sets:
            for a in rs.addresses:
                if a not in self._chans:
                    self._chans[a] = rpc.Channel(
                        a, timeout_ms=self.timeout_ms)
                if self.breakers is not None:
                    self.breakers.breaker_for(a)

    # -- legacy single-scheme surface (delegates to the write view) -------

    @property
    def _wview(self) -> _SchemeView:
        return self._wv

    @property
    def replica_sets(self) -> List[ReplicaSet]:
        return self._wv.replica_sets

    @property
    def n(self) -> int:
        return self._wv.n

    @property
    def rows_per(self) -> int:
        return self._wv.rows_per

    @property
    def addresses(self) -> List[str]:
        return self._wv.addresses

    @property
    def channels(self) -> List[rpc.Channel]:
        return [self._chans[a] for a in self._wv.addresses]

    @property
    def _primary_idx(self) -> List[int]:
        return self._wv._primary_idx

    @property
    def _epoch_seen(self) -> List[int]:
        return self._wv._epoch_seen

    @property
    def _gen_seen(self) -> List[int]:
        return self._wv._gen_seen

    # -- scheme lifecycle (the dual-scheme router's control surface) ------

    def schemes(self) -> List[PartitionScheme]:
        with self._view_mu:
            return [v.scheme for v in self._views]

    def set_schemes(self, schemes: Sequence[PartitionScheme],
                    strict: bool = True) -> None:
        """Adopt the given scheme records: known versions take the new
        weight/state (topology per version is immutable), unknown ones
        become routing views, RETIRED ones are dropped — after which no
        read or write ever routes to them again.  Safe to call from a
        watcher thread; the write view itself only switches on the
        writer's thread (see ``_write_view``).  With ``strict=False``
        (the registry-ingest path) a record this client cannot build a
        view for is skipped instead of raising, so one bad publication
        never blocks the usable ones."""
        by_ver = {sc.version: sc for sc in schemes}
        fresh: List[_SchemeView] = []
        with self._view_mu:
            known = {v.version: v for v in self._views}
            for ver, sc in by_ver.items():
                if ver in known:
                    known[ver].update(sc)
                elif sc.state != "retired":
                    try:
                        fresh.append(_SchemeView(self, sc))
                    except ValueError:
                        if strict:
                            raise
                        if obs.enabled():
                            obs.counter("ps_scheme_rejects").add(1)
        for v in fresh:
            self._admit_view(v)
            if obs.enabled():
                obs.counter("ps_scheme_refreshes").add(1)
        with self._view_mu:
            allv = self._views + fresh
            cur = self._wv
            if cur.state == "retired" and not any(
                    self._push_unacked.values()):
                # a read-only client's write view never moves through
                # _write_view(); when its scheme retires with no push
                # window pending, hop to the successor here so the
                # retired view can actually drop
                act = [v for v in allv if v.state == "active"] or allv
                cur = self._wv = max(act, key=lambda v: v.version)
            self._views = [v for v in allv
                           if v.state != "retired" or v is cur]
            self.replicated = self.replicated or any(
                len(rs.addresses) > 1
                for v in fresh for rs in v.replica_sets)
            self._redirect = self.replicated and (
                self.breakers is None or self.breakers.redirect)

    def add_scheme(self, scheme: PartitionScheme) -> None:
        self.set_schemes([scheme])

    def attach_registry(self, registry_addr: str, cluster: str,
                        wait_ms: int = 2000) -> None:
        """Start watching the naming registry: published scheme
        transitions and primary/epoch claims flow into this router
        live (cutover redirects then only pay one refresh round
        trip)."""
        if self._watcher is not None:
            return
        self._watcher = _SchemeWatcher(self, registry_addr, cluster,
                                       wait_ms=wait_ms)
        self._watcher.start()

    def _ingest_nodes(self, nodes) -> None:
        """Registry listing → scheme views + primary claims.  Ingest is
        non-strict: a published scheme this client cannot route (bounds
        not ending at its vocab, shard count not dividing it) is
        counted and skipped — the watcher must keep consuming the
        records it CAN use."""
        schemes = parse_schemes(nodes)
        if schemes:
            self.set_schemes(list(schemes.values()), strict=False)
        claims = parse_claims(nodes)
        if claims:
            with self._view_mu:
                self._claims.update(claims)

    def _claim_for(self, view: _SchemeView, s: int):
        """This view's claim for shard ``s`` — claims are keyed per
        scheme VERSION so coexisting schemes with equal shard counts
        never mask each other; a legacy unscoped claim (``scheme``
        ``None``) is accepted only when no scoped one exists."""
        with self._view_mu:
            claim = self._claims.get((view.version, view.n, s))
            if claim is None:
                claim = self._claims.get((None, view.n, s))
            return claim

    def _write_view(self) -> _SchemeView:
        """The view owning WRITES: the newest active scheme.  Switching
        away from a view transfers its unacked push window onto the
        successor (guarded unary re-splits — exactly-once across the
        scheme boundary) before any new write routes there."""
        while True:
            with self._view_mu:
                act = [v for v in self._views if v.state == "active"] \
                    or list(self._views)
                best = max(act, key=lambda v: v.version)
                cur = self._wv
                if best is cur:
                    return cur
                self._wv = best
            if obs.enabled():
                obs.counter("ps_scheme_switches").add(1)
            self._transfer_pushes(cur, best)

    def _on_stale_scheme(self, view: _SchemeView,
                         err: BaseException) -> None:
        """A write was redirected with ESCHEMEMOVED.  The redirect is
        AUTHORITATIVE: the server declared this scheme fenced, so
        demote the view locally (the write view moves even before the
        registry publication lands) and poke the registry for the
        successor; with nothing newer known the redirect error
        propagates (a stale client with no discovery path must fail
        loudly, not spin)."""
        with self._view_mu:
            if view.state == "active":
                view.state = "draining"
        if self._watcher is not None:
            self._watcher.refresh()
        with self._view_mu:
            newest = max(self._views, key=lambda v: v.version)
        if newest.version <= view.version:
            raise err

    def _stream_writer_key(self, view: _SchemeView, s: int) -> str:
        """Per-(client, scheme, shard) stream writer key: seq spaces
        from different schemes/shards must never collide inside a
        migrated dedup window (a merge destination inherits windows
        from several sources)."""
        return f"{self._writer_id}/s{view.version}.{s}"

    def _unary_writer_key(self, view: _SchemeView, s: int) -> str:
        return f"{self._writer_id}/u{view.version}.{s}"

    # -- replica routing (SelectiveChannel / locality-aware LB analog) ----

    def _chan(self, addr: str) -> rpc.Channel:
        ch = self._chans.get(addr)
        if ch is None:
            ch = self._chans[addr] = rpc.Channel(
                addr, timeout_ms=self.timeout_ms)
        return ch

    def _addr_breaker(self, addr: str
                      ) -> "Optional[resilience.CircuitBreaker]":
        if self.breakers is None:
            return None
        return self.breakers.breaker_for(addr)

    def _isolated(self, addr: str) -> bool:
        if self.breakers is None:
            return False
        return self.breakers.breaker_for(addr).isolated()

    def _breaker(self, view: _SchemeView, s: int
                 ) -> "Optional[resilience.CircuitBreaker]":
        if self.breakers is None:
            return None
        return self.breakers.breaker_for(view.addresses[s])

    def _ctl_timeout_ms(self) -> int:
        """Control-plane calls (ReplicaState/Promote) stay snappy: they
        run inside a failing data call's recovery path."""
        return max(50, min(self.timeout_ms, 1000))

    def _route_read(self, view: _SchemeView, s: int,
                    exclude=frozenset()) -> str:
        """Pick the replica serving shard ``s``'s next READ under
        ``view``: in redirect mode, the lowest latency*(inflight+1)
        score among live (not isolated, not just-failed) replicas — an
        open breaker on one replica REDIRECTS traffic to its siblings;
        only when every replica is isolated does the shard fail fast.
        Outside redirect mode reads stick to the primary (the legacy
        reject behavior)."""
        rs = view.replica_sets[s]
        if len(rs.addresses) > 1 and self._redirect:
            cands = [a for a in rs.addresses if a not in exclude]
            if not cands:
                cands = list(rs.addresses)   # tried everyone: start over
            live = [a for a in cands if not self._isolated(a)]
            if not live:
                raise rpc.RpcError(
                    resilience.EBREAKEROPEN,
                    f"shard {s}: every replica isolated by circuit "
                    f"breaker ({', '.join(rs.addresses)})")
            if len(live) < len(cands) and obs.enabled():
                # an open breaker pushed this read to a sibling —
                # redirected, not rejected
                obs.counter("rpc_breaker_redirects").add(1)
            return view.scorer.pick(live)
        return self._route_write(view, s, exclude)

    def _route_write(self, view: _SchemeView, s: int,
                     exclude=frozenset()) -> str:
        """WRITES go to the primary.  In redirect mode a failed or
        breaker-isolated primary triggers failover (fenced promotion of
        a backup); otherwise an isolated primary rejects, exactly the
        single-owner behavior."""
        rs = view.replica_sets[s]
        addr = rs.addresses[view._primary_idx[s]]
        if len(rs.addresses) > 1 and self._redirect and \
                (addr in exclude or self._isolated(addr)):
            return self._failover(view, s, exclude)
        if self._isolated(addr):
            raise rpc.RpcError(
                resilience.EBREAKEROPEN,
                f"shard {s} ({addr}) isolated by circuit breaker")
        return addr

    def _adopt_claim(self, view: _SchemeView, s: int,
                     exclude=frozenset()) -> Optional[str]:
        """The registry-claim fast path (PR-9 deferral): when the
        naming heartbeat carries a primary claim for this range at or
        above every epoch we've seen, verify it with ONE ReplicaState
        call and adopt — no replica sweep, no promote race.  Returns
        the adopted address or None (fall back to sweeping)."""
        claim = self._claim_for(view, s)
        if claim is None:
            return None
        epoch_c, addr = claim
        rs = view.replica_sets[s]
        if addr not in rs.addresses or addr in exclude or \
                epoch_c < view._epoch_seen[s] or self._isolated(addr):
            return None
        try:
            st = json.loads(self._chan(addr).call(
                "Ps", "ReplicaState", b"",
                timeout_ms=self._ctl_timeout_ms()))
        except rpc.RpcError:
            return None
        if not st.get("primary") or st["epoch"] < epoch_c or \
                st["gen"] < view._gen_seen[s]:
            return None
        view._epoch_seen[s] = max(view._epoch_seen[s], int(st["epoch"]))
        view._primary_idx[s] = rs.addresses.index(addr)
        if obs.enabled():
            obs.counter("ps_claim_adoptions").add(1)
        return addr

    def _failover(self, view: _SchemeView, s: int,
                  exclude=frozenset()) -> str:
        """Re-resolve — and, when nobody owns the range, PROMOTE — shard
        ``s``'s primary among reachable replicas.  A primary claim
        published through the registry heartbeat short-circuits the
        sweep.  Promotion carries a fencing epoch above every epoch
        observed in the sweep, so a concurrent stale primary is fenced
        the moment it next touches a fenced replica; losing a promote
        race (EFENCED back) just re-resolves.  Returns the new
        primary's address."""
        adopted = self._adopt_claim(view, s, exclude)
        if adopted is not None:
            if obs.enabled():
                obs.counter("ps_client_failovers").add(1)
            return adopted
        rs = view.replica_sets[s]
        last_err: Optional[rpc.RpcError] = None
        for _ in range(3):
            states: Dict[str, dict] = {}
            for a in rs.addresses:
                if a in exclude or self._isolated(a):
                    continue
                try:
                    states[a] = json.loads(self._chan(a).call(
                        "Ps", "ReplicaState", b"",
                        timeout_ms=self._ctl_timeout_ms()))
                except rpc.RpcError as e:
                    last_err = e
            if not states:
                raise rpc.RpcError(
                    resilience.EBREAKEROPEN,
                    f"shard {s}: no reachable replica to fail over to "
                    f"(candidates {', '.join(rs.addresses)}; last error: "
                    f"{last_err})")
            seen = max([view._epoch_seen[s]]
                       + [st["epoch"] for st in states.values()])
            view._epoch_seen[s] = seen
            # Claims and candidates BEHIND the highest epoch this client
            # has observed are stale — a blackholed new primary must not
            # be undercut by its demoted predecessor (that would lose
            # acked updates).
            claims = [(st["epoch"], a) for a, st in states.items()
                      if st.get("primary") and st["epoch"] >= seen]
            if claims:
                _, addr = max(claims)
                if states[addr]["gen"] < view._gen_seen[s]:
                    # A primary whose table is behind writes this client
                    # was ACKED can only exist through a lossy promotion
                    # elsewhere — refuse to adopt it silently.
                    raise rpc.RpcError(
                        resilience.EBREAKEROPEN,
                        f"shard {s}: claimed primary {addr} is at gen "
                        f"{states[addr]['gen']} < acked gen "
                        f"{view._gen_seen[s]} — acked updates are "
                        f"missing, refusing the lossy adoption")
            else:
                # Quorum intersection: for >=3-replica groups a
                # promotion may only happen off a MAJORITY sweep — an
                # acked write holds on a write quorum, and any majority
                # of replicas intersects that quorum in at least one
                # member, so the freshest candidate of a majority sweep
                # provably carries every acked update.  A sub-majority
                # sweep refuses loudly instead of guessing.
                majority = len(rs.addresses) // 2 + 1
                if len(rs.addresses) >= 3 and len(states) < majority:
                    raise rpc.RpcError(
                        resilience.EBREAKEROPEN,
                        f"shard {s}: only {len(states)} of "
                        f"{len(rs.addresses)} replicas reachable — a "
                        f"majority sweep is required before promoting "
                        f"(acked quorum writes must intersect it)")
                cands = {a: st for a, st in states.items()
                         if st["epoch"] >= seen
                         and st["gen"] >= view._gen_seen[s]}
                if not cands:
                    raise rpc.RpcError(
                        resilience.EBREAKEROPEN,
                        f"shard {s}: every reachable replica is behind "
                        f"epoch {seen} or acked gen "
                        f"{view._gen_seen[s]} — the authoritative "
                        f"replica is unreachable, refusing a lossy "
                        f"promotion")
                # Nobody owns the range: promote the freshest current-
                # epoch replica (highest generation; index breaks ties
                # deterministically) with a fencing epoch above all.
                addr = max(cands, key=lambda a: (
                    cands[a]["gen"], -rs.addresses.index(a)))
                epoch = seen + 1
                try:
                    self._chan(addr).call(
                        "Ps", "Promote", struct.pack("<q", epoch),
                        timeout_ms=self._ctl_timeout_ms())
                except rpc.RpcError as e:
                    if e.code != resilience.EFENCED:
                        raise
                    continue   # promote race lost: re-resolve
                view._epoch_seen[s] = epoch
                if obs.enabled():
                    obs.counter("ps_client_promotes").add(1)
            view._primary_idx[s] = rs.addresses.index(addr)
            if obs.enabled():
                obs.counter("ps_client_failovers").add(1)
            return addr
        raise rpc.RpcError(
            resilience.EFENCED,
            f"shard {s}: lost the promote race on every attempt")

    def _note_acked_gen(self, view: _SchemeView, s: int, rsp) -> None:
        """A replicated shard answers writes with the covering gen —
        the client's acked floor for failover's lossy-promotion guard."""
        if rsp is not None and len(rsp) >= 8:
            (gen,) = struct.unpack_from("<q", rsp, 0)
            if gen > view._gen_seen[s]:
                view._gen_seen[s] = gen

    def _stamp(self, req, deadline: Optional[float]):
        """Deadline propagation for one request LEG: prefix ``req``
        with the batch's remaining budget (``deadline`` is the batch's
        ``time.monotonic`` instant).  Called per attempt — a retry or
        hedge leg carries what is left NOW, not the original budget.
        ``deadline_mode="absolute"`` converts to a wall-clock deadline
        (same-host/NTP assumption); ``"relative"`` ships the remaining
        budget itself (v2 header) and the server arrival-stamps with
        its own clock — no cross-host wall-clock agreement needed."""
        if deadline is None or not self.propagate_deadline:
            return req
        remaining_s = deadline - time.monotonic()
        if isinstance(req, rpc.IOBuf):
            # Zero-copy stamp: the 12-byte header rides as a prepended
            # owned block and the body's blocks are SHARED — the old
            # path re-copied the whole request to prepend 12 bytes.
            # The caller closes the stamped wrapper after the leg
            # starts (_close_stamped); `req` itself stays intact for
            # further attempts.
            if self.deadline_mode == "relative":
                return _pack_deadline_rel_iobuf(int(remaining_s * 1e6),
                                                req)
            return _pack_deadline_iobuf(
                int((time.time() + remaining_s) * 1e6), req)
        if self.deadline_mode == "relative":
            return _pack_deadline_rel(int(remaining_s * 1e6), req)
        return _pack_deadline(int((time.time() + remaining_s) * 1e6),
                              req)

    @staticmethod
    def _close_stamped(req, stamped) -> None:
        """Release a per-leg stamped IOBuf once its call has started or
        finished — the native request shares the blocks, so the wrapper
        handle is no longer needed (and ``req`` is untouched)."""
        if stamped is not req and isinstance(stamped, rpc.IOBuf):
            stamped.close()

    def _reroutable(self, view: _SchemeView, s: int,
                    exc: rpc.RpcError) -> bool:
        """True for routing-correction errors (the write reached a
        demoted/fenced replica) that re-route via failover immediately,
        outside the retry policy's attempt budget."""
        return exc.code in (resilience.ENOTPRIMARY, resilience.EFENCED) \
            and len(view.replica_sets[s].addresses) > 1

    @staticmethod
    def _scheme_miss(exc: rpc.RpcError) -> bool:
        """A scheme-boundary error: the shard exists and answered, but
        the SCHEME this client routed under is stale (fenced cutover)
        or not yet open (importing destination)."""
        return exc.code in (resilience.ESCHEMEMOVED,
                            resilience.EMIGRATING)

    def _retry_shard(self, view: _SchemeView, s: int, method: str,
                     req: bytes, exc: rpc.RpcError,
                     deadline: Optional[float],
                     tried: Optional[set] = None) -> bytes:
        """A shard's attempt failed on the hedged/sequential path:
        classify, back off, re-route (a replica that just failed is
        excluded, so the retry lands on a SIBLING when one exists), and
        retry under the batch's remaining budget.  Scheme-boundary
        errors escape immediately — they are view-level, not
        replica-level."""
        read = method == "Lookup"
        tried = set() if tried is None else tried
        e = exc
        attempt = 0
        reroutes = 0
        while True:
            # a READ answered EMIGRATING with siblings untried is a
            # replica-level miss (a lagging destination backup): route
            # around it; only an all-replicas miss is a view miss
            miss_reroute = (read and e.code == resilience.EMIGRATING
                            and len(tried)
                            < len(view.replica_sets[s].addresses))
            if self._scheme_miss(e) and not miss_reroute:
                raise e
            reroute = miss_reroute or (
                not read and self._reroutable(view, s, e))
            if reroute:
                reroutes += 1
                if reroutes > len(view.replica_sets[s].addresses) + 1:
                    raise e
            else:
                policy = self.retry
                if policy is None or not policy.do_retry(e, attempt):
                    raise e
            remaining_ms: Optional[float] = None
            if deadline is not None:
                remaining_ms = (deadline - time.monotonic()) * 1000.0
                if remaining_ms < 2.0:
                    raise e
            if not reroute:
                # ELIMIT sheds take the MANDATORY backoff floor
                # (retry_delay_ms): never re-issue immediately into the
                # overload that just shed us.
                delay = policy.retry_delay_ms(e, attempt)
                if remaining_ms is not None:
                    delay = min(delay, remaining_ms - 1.0)
                resilience.sleep_ms(delay)
                attempt += 1
                if obs.enabled():
                    obs.counter("rpc_retries").add(1)
            addr = self._route_read(view, s, tried) if read \
                else self._route_write(view, s, tried)
            tried.add(addr)
            t = None
            if deadline is not None:
                t = max(1, int((deadline - time.monotonic()) * 1000.0))
            if self.retry is not None:
                t = self.retry.cap_attempt_timeout(t)
            b = self._addr_breaker(addr)
            view.scorer.note_start(addr)
            t0 = time.monotonic()
            stamped = self._stamp(req, deadline)
            try:
                rsp = self._chan(addr).call(
                    "Ps", method, stamped,
                    timeout_ms=t, backup_ms=self.backup_ms)
            except rpc.RpcError as e2:
                routing = e2.code in (resilience.ENOTPRIMARY,
                                      resilience.EFENCED,
                                      resilience.EMIGRATING,
                                      resilience.ESCHEMEMOVED)
                view.scorer.note_end(addr, time.monotonic() - t0,
                                     routing)
                if b is not None:
                    b.on_call_end(0 if routing else e2.code)
                e = e2
                continue
            finally:
                self._close_stamped(req, stamped)
            view.scorer.note_end(addr, time.monotonic() - t0, True)
            if b is not None:
                b.on_call_end(0)
            return rsp

    def _fan_out(self, view: _SchemeView, method: str,
                 items: List[tuple], on_done=None) -> List[bytes]:
        """Issue every (shard, req) concurrently under ``view`` — each
        routed to a replica (reads: best live score; writes: the
        primary) — then collect with the resilience policy applied per
        shard.  Responses align with ``items``; ``on_done(i, rsp)``
        fires as each lands, so a caller interrupted by a scheme
        boundary knows exactly which items are acked.  Failed shards
        retry as a CONCURRENT re-fan: each round re-issues the whole
        failed subset as one native call group after a single backoff
        sleep, so k failing shards pay max(shard) retry latency, not
        sum — and each retry is re-routed AWAY from the replica that
        just failed.  On an unrecoverable shard failure the remaining
        in-flight calls are cancelled (straggler abandonment) before
        the error propagates."""
        deadline = time.monotonic() + self.deadline_ms / 1000.0 \
            if self.deadline_ms is not None else None
        read = method == "Lookup"

        def _budget() -> Optional[int]:
            t = None
            if deadline is not None:
                t = max(1, int((deadline - time.monotonic()) * 1000.0))
            if self.retry is not None:
                t = self.retry.cap_attempt_timeout(t)
            return t

        # per item: a PendingCall in flight, an RpcError whose start
        # failed (client fault / local transport error — handled like a
        # failed attempt in the join phase), or None once consumed
        pending: List[object] = [None] * len(items)
        addrs: List[Optional[str]] = [None] * len(items)
        t0s: List[float] = [0.0] * len(items)
        tried: List[set] = [set() for _ in items]
        attempts: List[int] = [0] * len(items)
        reroutes: List[int] = [0] * len(items)
        out: List[Optional[bytes]] = [None] * len(items)
        group: "Optional[rpc.CallGroup]" = None

        def _start(i: int, s: int, req) -> None:
            """Route item i and start its call; a start failure parks
            the RpcError in pending[i] for classification."""
            addr = self._route_read(view, s, tried[i]) if read \
                else self._route_write(view, s, tried[i])
            addrs[i] = addr
            tried[i].add(addr)
            view.scorer.note_start(addr)
            t0s[i] = time.monotonic()
            stamped = self._stamp(req, deadline)
            try:
                # managed fan-out set: every entry is joined or
                # cancelled+closed in the finally below; each leg is
                # stamped with the budget remaining at ITS issue
                pending[i] = self._chan(addr).call_async(  # lint: allow-handle-escape
                    "Ps", method, stamped,
                    timeout_ms=_budget(), tag=f"attempt={attempts[i]}")
            except rpc.RpcError as e:
                pending[i] = e
            finally:
                # the started call shares the blocks; the stamped
                # wrapper handle is done its job
                self._close_stamped(req, stamped)

        def _settle(i: int, pc: object, ok: bool, code: int = 0) -> None:
            """Feed one finished attempt to the scorer + breaker.
            Routing corrections (ENOTPRIMARY/EFENCED) and scheme
            boundaries (EMIGRATING/ESCHEMEMOVED) are PROOF the endpoint
            is alive — they must not open its breaker or poison its
            latency score."""
            addr = addrs[i]
            routing = code in (resilience.ENOTPRIMARY,
                               resilience.EFENCED,
                               resilience.EMIGRATING,
                               resilience.ESCHEMEMOVED)
            lat = time.monotonic() - t0s[i] \
                if isinstance(pc, rpc.PendingCall) else None
            view.scorer.note_end(addr, lat, ok or routing)
            b = self._addr_breaker(addr)
            if b is not None:
                b.on_call_end(0 if routing else code)

        try:
            for i, (s, req) in enumerate(items):
                _start(i, s, req)
            if self.backup_ms is not None:
                # Hedged path: ordered per-shard collection — each hedge
                # arms backup_ms on its in-flight primary and waits on its
                # OWN native call group inside backup_call (exact wakes,
                # no polling slices).
                for i, (s, req) in enumerate(items):
                    pc, pending[i] = pending[i], None
                    try:
                        if isinstance(pc, rpc.RpcError):
                            raise pc
                        # the hedge leg re-stamps: a backup fired
                        # backup_ms late carries the budget left THEN
                        stamped = self._stamp(req, deadline)
                        try:
                            rsp = resilience.backup_call(
                                self._chan(addrs[i]), "Ps", method,
                                stamped,
                                backup_ms=self.backup_ms,
                                timeout_ms=_budget(), primary=pc)
                        finally:
                            self._close_stamped(req, stamped)
                    except rpc.RpcError as e:
                        _settle(i, pc, False, e.code)
                        rsp = self._retry_shard(view, s, method, req,
                                                e, deadline, tried[i])
                    else:
                        _settle(i, pc, True)
                    out[i] = rsp
                    if on_done is not None:
                        on_done(i, rsp)
                return out  # type: ignore[return-value]
            # Unhedged path: completion-ORDER collection over one native
            # fan-in group (the ParallelChannel CountdownEvent shape).
            # Every wait_any wakes on exactly one shard completing — no
            # time slices.  Failures collect into `failed` and re-fan
            # concurrently once the round drains; non-retriable errors
            # abort the batch the moment they surface.
            group = rpc.CallGroup()
            waiting: List[int] = []
            failed: List[int] = []
            excs: List[Optional[rpc.RpcError]] = [None] * len(items)

            def _classify(i: int, e: rpc.RpcError) -> None:
                """Queue item i for the next re-fan round, or abort.
                Scheme-boundary errors abort immediately — the caller
                re-routes the remainder through the successor view.
                Exception: a READ answered EMIGRATING with sibling
                replicas untried is a REPLICA-level miss (a destination
                backup that lagged the cutover open), not a view-level
                one — try a sibling before declaring the view a miss."""
                s = items[i][0]
                if self._scheme_miss(e):
                    if read and e.code == resilience.EMIGRATING and \
                            len(tried[i]) < len(
                                view.replica_sets[s].addresses):
                        reroutes[i] += 1
                        excs[i] = e
                        failed.append(i)
                        return
                    raise e
                if not read and self._reroutable(view, s, e):
                    reroutes[i] += 1
                    if reroutes[i] <= \
                            len(view.replica_sets[s].addresses) + 1:
                        excs[i] = e
                        failed.append(i)
                        return
                    raise e
                policy = self.retry
                if policy is None or not policy.do_retry(e, attempts[i]):
                    raise e
                excs[i] = e
                failed.append(i)

            def _enqueue(i: int) -> None:
                pc = pending[i]
                if isinstance(pc, rpc.PendingCall):
                    group.add(pc)
                    waiting.append(i)
                else:   # start failure: already complete — classify now
                    e: rpc.RpcError = pc  # type: ignore[assignment]
                    pending[i] = None
                    _settle(i, pc, False, e.code)
                    _classify(i, e)

            for i in range(len(items)):
                _enqueue(i)
            while waiting or failed:
                while waiting:
                    group.wait_any()
                    done_i = next((i for i in waiting
                                   if pending[i].wait(0.0)), None)
                    if done_i is None:  # pragma: no cover — wait_any
                        continue
                    waiting.remove(done_i)
                    pc, pending[done_i] = pending[done_i], None
                    try:
                        rsp = pc.join()
                    except rpc.RpcError as e:
                        _settle(done_i, pc, False, e.code)
                        _classify(done_i, e)
                    else:
                        _settle(done_i, pc, True)
                        out[done_i] = rsp
                        if on_done is not None:
                            on_done(done_i, rsp)
                if not failed:
                    break
                # ---- concurrent re-fan of the failed subset: ONE
                # backoff sleep (the max of the round's delays, capped
                # by the remaining budget), then every failed shard
                # re-issues together and collects by completion order —
                # retry latency is max(shard), not sum(shard).
                refan, failed = failed, []
                round_delay = 0.0
                for i in refan:
                    s = items[i][0]
                    if self._scheme_miss(excs[i]) or (
                            not read
                            and self._reroutable(view, s, excs[i])):
                        continue   # routing correction: no backoff
                    # retry_delay_ms floors ELIMIT sheds (mandatory
                    # backoff — never re-fan straight into overload)
                    round_delay = max(round_delay,
                                      self.retry.retry_delay_ms(
                                          excs[i], attempts[i]))
                if deadline is not None:
                    remaining_ms = (deadline
                                    - time.monotonic()) * 1000.0
                    if remaining_ms < 2.0:
                        raise excs[refan[0]]  # type: ignore[misc]
                    round_delay = min(round_delay, remaining_ms - 1.0)
                if round_delay > 0:
                    resilience.sleep_ms(round_delay)
                for i in refan:
                    s, req = items[i]
                    if not (self._scheme_miss(excs[i])
                            or (not read and self._reroutable(
                                view, s, excs[i]))):
                        attempts[i] += 1
                        if obs.enabled():
                            obs.counter("rpc_retries").add(1)
                    _start(i, s, req)
                    _enqueue(i)
            return out  # type: ignore[return-value]
        except BaseException:
            # Aborted batch: the caller never sees `out`, so close any
            # already-collected IOBuf responses — the propagating
            # traceback pins this frame (and with it `out`), which
            # would otherwise hold the handles past the test/leak
            # ledger's horizon.  With on_done the caller owns delivered
            # responses and closes them itself.
            if on_done is None:
                for rsp in out:
                    if isinstance(rsp, rpc.IOBuf):
                        rsp.close()
            raise
        finally:
            if group is not None:
                group.close()
            # Partial failure: cancel the stragglers so close() reaps
            # them at cancel speed, not at their full timeout.
            for pc in pending:
                if isinstance(pc, rpc.PendingCall):
                    pc.cancel()
                    pc.close()

    def _call_shard(self, view: _SchemeView, s: int, method: str,
                    req: bytes) -> bytes:
        """Sequential-path shard call with the same per-shard policy
        (routed; a routing-correction error fails over once)."""
        deadline = time.monotonic() + self.deadline_ms / 1000.0 \
            if self.deadline_ms is not None else None
        addr = self._route_read(view, s) if method == "Lookup" \
            else self._route_write(view, s)
        stamped = self._stamp(req, deadline)
        try:
            return self._chan(addr).call(
                "Ps", method, stamped,
                retry=self.retry, deadline_ms=self.deadline_ms,
                backup_ms=self.backup_ms,
                breaker=self._addr_breaker(addr))
        except rpc.RpcError as e:
            if method != "Lookup" and not self._scheme_miss(e) and \
                    self._reroutable(view, s, e):
                addr = self._route_write(view, s, {addr})
                restamped = self._stamp(req, deadline)
                try:
                    return self._chan(addr).call(
                        "Ps", method, restamped,
                        retry=self.retry, deadline_ms=self.deadline_ms,
                        backup_ms=self.backup_ms,
                        breaker=self._addr_breaker(addr))
                finally:
                    self._close_stamped(req, restamped)
            raise
        finally:
            self._close_stamped(req, stamped)

    def _owner_split(self, view: _SchemeView, flat_ids: np.ndarray):
        if flat_ids.size and (flat_ids.min() < 0
                              or flat_ids.max() >= self.vocab):
            # An out-of-range id matches no shard: lookup() would otherwise
            # return uninitialized rows for it.
            raise ValueError(
                f"ids must be in [0, {self.vocab}); got "
                f"[{flat_ids.min()}, {flat_ids.max()}]"
            )
        if view.bounds is None:
            owners = flat_ids // view.rows_per
        else:
            # Explicit row-range map: bounds[s] <= id < bounds[s+1].
            owners = np.searchsorted(view.bounds, flat_ids,
                                     side="right") - 1
        for s in range(view.n):
            mask = owners == s
            if mask.any():
                yield s, np.nonzero(mask)[0], flat_ids[mask]

    def _read_views(self) -> List[_SchemeView]:
        """Read routing order: the weighted pick first (traffic share
        follows each scheme's live capacity weight — the dynpart load
        balancer's contract), then every other non-retired view newest
        first as FALLBACKS — a miss on the picked scheme (importing
        destination, dead retiring shard) re-runs the batch on the
        next view instead of failing the read."""
        with self._view_mu:
            views = [v for v in self._views if v.state != "retired"]
            self._read_seq += 1
            seq = self._read_seq
        order = sorted(views, key=lambda v: -v.version)
        if len(order) <= 1:
            return order
        # only ACTIVE schemes join the weighted pick; preparing (still
        # importing) and draining schemes serve as fallbacks only
        active = [v for v in order if v.state == "active"]
        total = sum(v.weight for v in active)
        if total <= 0:
            return order
        r = resilience._hash01(0x5EED, seq) * total
        pick = active[0]
        for v in active:
            if r < v.weight:
                pick = v
                break
            r -= v.weight
        return [pick] + [v for v in order if v is not pick]

    def _lookup_view(self, view: _SchemeView, flat: np.ndarray,
                     out: np.ndarray):
        """One whole-batch lookup under one scheme view.  Returns
        ``(bytes_out, bytes_in)``; raises on any shard miss (the caller
        falls back across schemes)."""
        nbytes_in = 0
        nbytes_out = 0
        zc = zerocopy_enabled()

        def _consume(rsp, owned):
            """Response rows as float32 — zero-copy for single-block
            IOBuf replies (one gather for multi-block), plain
            frombuffer for the bytes path."""
            if isinstance(rsp, rpc.IOBuf):
                try:
                    return np.frombuffer(rsp.as_memoryview(),
                                         np.float32).reshape(
                                             owned.size, self.dim)
                finally:
                    # A live view defers actual destruction; the rows
                    # are copied into `out` before the array dies.
                    rsp.close()
            return np.frombuffer(rsp, np.float32).reshape(
                owned.size, self.dim)

        if self.parallel:
            # Start every owner-shard call before joining any: the
            # shards serve concurrently and the batch pays max(shard),
            # not sum(shard).  _fan_out applies the per-shard
            # resilience policy (retry/hedge/breaker) and cancels
            # stragglers on an unrecoverable partial failure.
            split = list(self._owner_split(view, flat))
            items = []
            rsps: List[object] = []
            try:
                for s, positions, owned in split:
                    req = _pack_lookup_req_iobuf(owned) \
                        if zc and owned.nbytes >= _ZC_MIN_BYTES \
                        else _pack_lookup_req(owned)
                    nbytes_out += len(req)
                    items.append((s, req))
                rsps = self._fan_out(view, "Lookup", items)
                for (s, positions, owned), rsp in zip(split, rsps):
                    nbytes_in += len(rsp)
                    out[positions] = _consume(rsp, owned)
            finally:
                for _, req in items:
                    if isinstance(req, rpc.IOBuf):
                        req.close()
                # a consume interrupted mid-batch must not strand the
                # remaining response handles (close() is idempotent)
                for rsp in rsps:
                    if isinstance(rsp, rpc.IOBuf):
                        rsp.close()
        else:
            for s, positions, owned in self._owner_split(view, flat):
                req = _pack_lookup_req_iobuf(owned) \
                    if zc and owned.nbytes >= _ZC_MIN_BYTES \
                    else _pack_lookup_req(owned)
                nbytes_out += len(req)
                try:
                    rsp = self._call_shard(view, s, "Lookup", req)
                finally:
                    if isinstance(req, rpc.IOBuf):
                        req.close()
                nbytes_in += len(rsp)
                out[positions] = _consume(rsp, owned)
        return nbytes_out, nbytes_in

    def lookup(self, ids: np.ndarray) -> np.ndarray:
        rec = obs.enabled()
        if rec:
            t0 = time.monotonic_ns()
        flat = np.asarray(ids, np.int32).reshape(-1)
        out = np.empty((flat.size, self.dim), np.float32)
        # Dual-scheme reads: weighted pick, then fall back across the
        # remaining schemes on ANY failure — during a live reshard the
        # other scheme holds the same rows (an importing destination
        # answers EMIGRATING; a draining scheme's tables are frozen at
        # exactly the cutover state, so its answers stay correct).
        views = self._read_views()
        nbytes_out = nbytes_in = 0
        for i, view in enumerate(views):
            try:
                nbytes_out, nbytes_in = self._lookup_view(view, flat,
                                                          out)
                break
            except rpc.RpcError:
                if i + 1 >= len(views):
                    raise
                if obs.enabled():
                    obs.counter("ps_scheme_fallback_reads").add(1)
        if rec:
            # Whole-batch latency across all owner shards (each per-shard
            # RPC is additionally recorded by Channel.call/call_async).
            obs.recorder("ps_client_lookup").record(
                (time.monotonic_ns() - t0) / 1e9)
            obs.counter("ps_client_lookup_keys").add(int(flat.size))
            obs.counter("ps_client_bytes_out").add(nbytes_out)
            obs.counter("ps_client_bytes_in").add(nbytes_in)
        return out.reshape(*np.shape(ids), self.dim)

    def _apply_unit(self, view: _SchemeView, uids: np.ndarray,
                    ugrads: np.ndarray, guards: tuple) -> int:
        """Apply one write unit (global ids + grads + scheme guards)
        under ``view`` via idempotent ``ApplyGradId`` items, one per
        owner shard.  Returns bytes sent.  A scheme boundary raises
        :class:`_SchemeMovedError` carrying the UNAPPLIED remainder —
        each unacked item becomes a unit whose guard chain grows by its
        own (writer key, seq), so re-routing it through the successor
        scheme can never double-apply content that already migrated."""
        split = list(self._owner_split(view, uids))
        items = []
        meta = []
        nbytes = 0
        for s, positions, owned in split:
            wkey = self._unary_writer_key(view, s)
            seq = view.useq.get(s, 0) + 1
            view.useq[s] = seq
            item_guards = guards + ((wkey, seq),)
            req = bytes(_pack_apply_id_req(wkey, seq, guards, owned,
                                           ugrads[positions]))
            nbytes += len(req)
            items.append((s, req))
            meta.append((owned, ugrads[positions], item_guards))
        done: List[Optional[bytes]] = [None] * len(items)

        def _on_done(i: int, rsp) -> None:
            done[i] = rsp
            self._note_acked_gen(view, items[i][0], rsp)

        try:
            if self.parallel:
                self._fan_out(view, "ApplyGradId", items,
                              on_done=_on_done)
            else:
                for i, (s, req) in enumerate(items):
                    _on_done(i, self._call_shard(view, s, "ApplyGradId",
                                                 req))
        except rpc.RpcError as e:
            if not self._scheme_miss(e):
                raise
            remainder = [(meta[i][0], meta[i][1], meta[i][2])
                         for i in range(len(items)) if done[i] is None]
            raise _SchemeMovedError(e.code, remainder) from e
        return nbytes

    def _apply_units(self, units: List[tuple]) -> int:
        """Drive write units to completion across scheme moves: a unit
        interrupted by a cutover re-splits through the refreshed write
        view (guard chain intact), an EMIGRATING unit waits out the
        fence→open window with bounded backoff.  Units issue
        SEQUENTIALLY so per-(scheme, shard) seqs stay in arrival order
        (one batch normally is one unit — the fan-out inside it is
        still concurrent)."""
        nbytes = 0
        moves = 0
        backoff = resilience.Backoff(base_ms=5.0, max_ms=100.0)
        queue = list(units)
        while queue:
            view = self._write_view()
            uids, ugrads, guards = queue[0]
            try:
                nbytes += self._apply_unit(view, uids, ugrads, guards)
            except _SchemeMovedError as e:
                moves += 1
                if moves > 16:
                    raise rpc.RpcError(
                        e.code, "write could not settle across the "
                                "scheme cutover (16 rounds)") from e
                queue[0:1] = e.remainder
                if e.code == resilience.ESCHEMEMOVED:
                    if obs.enabled():
                        obs.counter("ps_scheme_moved_writes").add(1)
                    self._on_stale_scheme(view, e.__cause__ or e)
                else:
                    # cutover window: destinations fenced open shortly
                    resilience.sleep_ms(backoff.delay_ms(min(moves, 6)))
                continue
            queue.pop(0)
        return nbytes

    def apply_gradients(self, ids: np.ndarray, grads: np.ndarray) -> None:
        rec = obs.enabled()
        if rec:
            t0 = time.monotonic_ns()
        flat = np.asarray(ids, np.int32).reshape(-1)
        g = np.asarray(grads, np.float32).reshape(flat.size, self.dim)
        nbytes_out = self._apply_units([(flat, g, ())])
        if rec:
            obs.recorder("ps_client_apply").record(
                (time.monotonic_ns() - t0) / 1e9)
            obs.counter("ps_client_apply_keys").add(int(flat.size))
            obs.counter("ps_client_bytes_out").add(nbytes_out)

    # -- streaming gradient push (the write-path mirror of the native
    # -- read path: framed deltas over one ordered flow-controlled
    # -- stream per owner shard, feeding the server combiner directly)

    def _push_stream(self, view: _SchemeView, s: int,
                     exclude=frozenset()) -> "rpc.Stream":
        st = self._push_streams.get(s)
        if st is None:
            addr = self._route_write(view, s, exclude)
            # The setup request carries the writer key (scheme- and
            # shard-qualified): the server opens (or re-opens) this
            # writer's monotonic seq window and answers its high-water
            # mark — the replay cursor.  The receiver is the fence
            # channel: a primary demoted (or scheme-fenced) while this
            # stream is up notifies instead of silently dropping.
            recv = _PushStreamReceiver()
            st = self._chan(addr).stream(
                "Ps", "StreamApply",
                self._stream_writer_key(view, s).encode(),
                max_buf_size=self.push_window_bytes, receiver=recv)
            self._push_streams[s] = st
            self._push_addr[s] = addr
            self._push_recv[s] = recv
            high = 0
            if len(st.response) >= 8:
                (high,) = struct.unpack_from("<q", st.response, 0)
            self._push_sent[s] = high
            if obs.enabled():
                # frames this server already holds (the write that
                # "failed" reached it before the break) are not resent
                nskip = sum(1 for q, _ in self._push_unacked.get(s, ())
                            if q <= high)
                if nskip:
                    obs.counter("ps_stream_replay_skips").add(nskip)
        return st

    def _drop_push_stream(self, s: int) -> Optional[str]:
        """Tear down shard ``s``'s push stream state (reconnect/error
        path).  Returns the address it was bound to, if any."""
        st = self._push_streams.pop(s, None)
        if st is not None:
            # rx stream: close, never abort (the closed callback is
            # what frees the native read relay)
            st.close()
        self._push_recv.pop(s, None)
        self._push_sent.pop(s, None)
        return self._push_addr.pop(s, None)

    def _fence_code(self, recv) -> int:
        return resilience.ESCHEMEMOVED \
            if recv is not None and recv.scheme_moved \
            else resilience.ENOTPRIMARY

    def _push_frames(self, view: _SchemeView, s: int) -> None:
        """Write every unacked frame past the replay cursor to shard
        ``s``'s push stream, RECONNECTING under the embedding's retry
        policy on error: the broken stream is torn down, a fresh one is
        created (the setup RPC pays the shard's real state — timeouts
        included), and the unacked TAIL above the server's high-water
        mark is replayed on it.  The per-writer seq in every frame makes
        replay IDEMPOTENT (the server's window drops anything at or
        below its mark), and because the window a promoted backup
        inherits covers exactly the frames whose data it holds, the same
        replay is also LOSSLESS across failover.  A failed or demoted
        primary re-routes: ENOTPRIMARY/EFENCED (including the fence
        notification on the stream's reply half) fails over immediately;
        a dead endpoint is excluded from the reconnect's routing
        (redirect mode).  A SCHEME fence (cutover) raises ESCHEMEMOVED
        to the caller — the unacked window transfers to the successor
        scheme instead of replaying here."""
        attempt = 0
        fails = 0
        exclude: set = set()
        while True:
            try:
                st = self._push_stream(view, s, exclude)
                recv = self._push_recv.get(s)
                sent = self._push_sent.get(s, 0)
                frames = self._push_unacked.get(s, [])
                # seqs are contiguous per shard: the unsent tail starts
                # right past the cursor
                start = max(0, sent - frames[0][0] + 1) if frames else 0
                if zerocopy_enabled():
                    # Batched zero-copy replay: every eligible frame in
                    # ONE native crossing (header blocks owned, bodies
                    # borrowed).  The fence check moves to batch
                    # granularity — a fence landing mid-batch is the
                    # same race the per-frame path had between check
                    # and write.
                    if recv is not None and recv.fenced:
                        raise rpc.RpcError(
                            self._fence_code(recv),
                            f"shard {s} push stream fenced")
                    seqs = []
                    batch = []
                    try:
                        for seq, body in frames[start:]:
                            if seq <= sent:
                                continue
                            seqs.append(seq)
                            batch.append(
                                _pack_stream_frame_iobuf(seq, 0, 0,
                                                         body))
                        if batch:
                            try:
                                st.writev(batch)
                            except rpc.RpcError as e:
                                nw = getattr(e, "frames_written", 0)
                                if nw:
                                    # frames before the break ARE on
                                    # the wire: advance the cursor so
                                    # the reconnect replays the tail
                                    self._push_sent[s] = sent = \
                                        seqs[nw - 1]
                                raise
                            self._push_sent[s] = sent = seqs[-1]
                    finally:
                        for io in batch:
                            io.close()
                else:
                    for seq, body in frames[start:]:
                        if recv is not None and recv.fenced:
                            raise rpc.RpcError(
                                self._fence_code(recv),
                                f"shard {s} push stream fenced")
                        if seq <= sent:
                            continue
                        st.write(_pack_stream_frame(seq, 0, 0, body))
                        self._push_sent[s] = sent = seq
                if recv is not None and recv.fenced:
                    raise rpc.RpcError(
                        self._fence_code(recv),
                        f"shard {s} push stream fenced")
                return
            except rpc.RpcError as e:
                addr = self._drop_push_stream(s)
                if e.code == resilience.ESCHEMEMOVED:
                    raise   # cutover: the caller transfers the window
                rs = view.replica_sets[s]
                if self._reroutable(view, s, e):
                    fails += 1
                    if fails > len(rs.addresses) + 1:
                        raise
                    self._failover(view, s)
                    continue
                policy = self.retry
                # Stream breakage (EPIPE/EINVAL/EFAILEDSOCKET) means
                # reconnect regardless of the unary retriable set; an
                # EMIGRATING destination (cutover still opening) also
                # retries under the same budget.  The policy still owns
                # the ATTEMPT budget and backoff.
                reconnectable = e.code in (32, 22, 1009,
                                           resilience.EMIGRATING) or \
                    (policy is not None and
                     e.code in policy.retriable)
                if policy is None or not reconnectable or \
                        not attempt + 1 < policy.max_attempts:
                    raise
                if addr is not None and len(rs.addresses) > 1 \
                        and self._redirect:
                    exclude.add(addr)   # prefer a surviving replica
                if obs.enabled():
                    obs.counter("ps_stream_reconnects").add(1)
                resilience.sleep_ms(policy.backoff.delay_ms(attempt))
                attempt += 1

    def push_gradients(self, ids: np.ndarray, grads: np.ndarray) -> None:
        """Streaming gradient push: ships this batch's per-owner-shard
        deltas as ONE framed message per shard over a persistent
        ordered stream (opened lazily, kept across batches) — no unary
        dispatch/response per apply, and a shard whose combiner falls
        behind back-pressures THIS call through the stream's
        flow-control window (``push_window_bytes``;
        ``stream_stall_ms`` counts the stalls).  Fire-and-forget:
        application is guaranteed only after :meth:`flush_gradients`.
        Requires shards serving ``StreamApply``
        (``PsShardServer(stream=True)``); the unary
        :meth:`apply_gradients` remains the synchronous/fallback path.

        Across a live reshard: a cutover fence (``ESCHEMEMOVED``, as a
        setup rejection or a -2 fence frame) transfers the ENTIRE
        unacked window — this batch included — onto the successor
        scheme as guarded unary writes (exactly-once either side of the
        boundary), after which pushes stream to the new shards."""
        rec = obs.enabled()
        if rec:
            t0 = time.monotonic_ns()
        flat = np.asarray(ids, np.int32).reshape(-1)
        g = np.asarray(grads, np.float32).reshape(flat.size, self.dim)
        view = self._write_view()
        nbytes_out = 0
        shards = []
        # Frame every owner shard FIRST: a scheme fence hit while
        # writing shard k must transfer the whole batch, not a prefix.
        for s, positions, owned in self._owner_split(view, flat):
            body = bytes(_pack_apply_req(owned, g[positions]))
            nbytes_out += len(body)
            seq = self._push_seq.get(s, 0) + 1
            self._push_seq[s] = seq
            # Unacked until the flush barrier confirms: the window is
            # what a mid-push failover replays onto the new primary.
            self._push_unacked.setdefault(s, []).append((seq, body))
            shards.append(s)
        try:
            for s in shards:
                self._push_frames(view, s)
        except rpc.RpcError as e:
            if e.code != resilience.ESCHEMEMOVED:
                raise
            self._transfer_pushes(view, None)
        if rec:
            obs.recorder("ps_client_push").record(
                (time.monotonic_ns() - t0) / 1e9)
            obs.counter("ps_client_push_keys").add(int(flat.size))
            obs.counter("ps_client_bytes_out").add(nbytes_out)

    def _transfer_pushes(self, old_view: _SchemeView,
                         new_view: Optional[_SchemeView]) -> None:
        """Carry the unacked push window across a scheme boundary: for
        every shard, ask the OLD primary's applied window (WriterSeq —
        a scheme-fenced primary still answers; its data is frozen and
        complete) and drop the acked prefix; whatever remains — or the
        whole window when the old primary is unreachable — re-routes
        through the successor scheme as GUARDED unary writes: each
        frame's guard names its (stream writer key, seq), and the
        destinations inherited the old windows with the migrated rows,
        so a frame that DID land (and migrated) is dropped server-side
        while a frame that died with the fence applies exactly once.

        FAILURE SAFETY: the unacked window is consumed only once a
        successor view is known, and the transfer units re-stash into
        ``_push_carry`` if applying them fails partway — either way a
        later :meth:`flush_gradients` still holds (and must drain) the
        full window, so a failed transfer can never turn into a
        vacuously successful flush over dropped deltas."""
        # The fenced streams are dead either way; the unacked WINDOW is
        # the source of truth and must survive any failure below.
        for s in list(self._push_streams):
            self._drop_push_stream(s)
        if new_view is None:
            # Resolve a successor BEFORE consuming the window: with no
            # discovery path this raises (window intact — the caller
            # retries once a successor is published).
            self._on_stale_scheme(
                old_view, rpc.RpcError(
                    resilience.ESCHEMEMOVED,
                    f"scheme v{old_view.version} fenced with no known "
                    f"successor"))
        # units from a PREVIOUS failed transfer re-drive first (guards
        # keep them exactly-once)
        tails: List[tuple] = self._push_carry   # (ids, grads, guards)
        self._push_carry = []
        for s, frames in sorted(self._push_unacked.items()):
            if not frames:
                continue
            wkey = self._stream_writer_key(old_view, s)
            applied = None
            try:
                rs = old_view.replica_sets[s]
                addr = rs.addresses[old_view._primary_idx[s]]
                rsp = self._chan(addr).call(
                    "Ps", "WriterSeq", wkey.encode(),
                    timeout_ms=self._ctl_timeout_ms())
                applied = struct.unpack_from("<qq", rsp, 0)[0]
            except rpc.RpcError:
                applied = None   # unreachable: transfer guarded, blind
            for seq, body in frames:
                if applied is not None and seq <= applied:
                    continue
                # our own unacked window, but the same guarded parse as
                # the servers — a corrupt stash must fail loudly, not
                # re-split garbage through numpy's count=-1 semantics
                (count,) = wire.read("<i", body, 0, "transfer.count")
                wire.check_count(count,
                                 (len(body) - 4) // (4 + 4 * self.dim),
                                 "transfer.count")
                gids = np.frombuffer(body, np.int32, count, 4)
                grads = np.frombuffer(
                    body, np.float32, count * self.dim,
                    4 + 4 * count).reshape(count, self.dim)
                tails.append((gids, grads, ((wkey, seq),)))
        self._push_unacked.clear()
        self._push_seq.clear()
        self._push_sent.clear()
        if tails:
            if obs.enabled():
                obs.counter("ps_push_transfers").add(len(tails))
            try:
                self._apply_units(tails)
            except BaseException:
                # Re-stash the WHOLE batch (applied units are dropped
                # server-side by their guards) so the next flush
                # re-drives it instead of succeeding over a hole.
                self._push_carry = tails
                raise

    def flush_gradients(self) -> None:
        """Closes every push stream and waits until each shard has
        consumed AND applied everything pushed so far (the server
        flushes its combiner before answering the close).  On a
        REPLICATED shard the close barrier alone is not trusted: a
        primary demoted mid-stream drops frames, so the barrier then
        verifies the CURRENT primary's applied window covers the last
        pushed seq, replaying the unacked tail (failover included) on a
        shortfall — a flush that returns means every pushed delta is
        applied on the live primary and its synced backups; a flush
        that cannot prove it raises.  A scheme CUTOVER racing the flush
        transfers the unacked window to the successor scheme instead
        (guarded — exactly-once).  The next :meth:`push_gradients`
        opens fresh streams.  Raises :class:`rpc.RpcError`
        (ERPCTIMEDOUT) if a shard fails to drain within the embedding's
        timeout."""
        view = self._wv
        streams, self._push_streams = self._push_streams, {}
        push_addr, self._push_addr = self._push_addr, {}
        recvs, self._push_recv = self._push_recv, {}
        self._push_sent.clear()
        for st in streams.values():
            st.close()
        deadline_s = max(1.0, self.timeout_ms / 1000.0)
        moved = any(r.scheme_moved for r in recvs.values())
        for s, st in streams.items():
            drained = st.join(timeout_s=deadline_s)
            replicated = len(view.replica_sets[s].addresses) > 1
            if not drained and not replicated and not moved:
                raise rpc.RpcError(
                    1008, f"shard {s} ({push_addr.get(s, '?')}) did not "
                          f"drain its push stream within {deadline_s:.1f}s")
            # a wedged/fenced stream is recovered below — the verify
            # barrier replays onto the live primary / successor scheme
        if moved:
            self._transfer_pushes(view, None)
            return
        for s in sorted(set(streams) | set(self._push_unacked)):
            # EVERY pushed shard verifies the applied window — the
            # close barrier alone cannot be trusted even unreplicated:
            # a scheme fence racing the close drops frames server-side
            # and its -2 notification can land after the client's full
            # close (discarded); the WriterSeq shortfall is what
            # reliably routes the tail to the successor scheme.  Shards
            # holding unacked frames with NO live stream (a transfer
            # that failed before consuming the window) verify too —
            # their replay is what re-drives the stranded window.
            self._confirm_push(view, s)
            self._push_unacked.pop(s, None)
        self._drain_carry()

    def _drain_carry(self) -> None:
        """Re-drive transfer units stranded by a FAILED scheme-boundary
        transfer.  Part of the flush barrier: a flush may only report
        success once the carry is empty (the guards make a re-drive of
        already-applied units exactly-once)."""
        if not self._push_carry:
            return
        tails, self._push_carry = self._push_carry, []
        try:
            self._apply_units(tails)
        except BaseException:
            self._push_carry = tails
            raise

    def _confirm_push(self, view: _SchemeView, s: int) -> None:
        """The zero-lost-acked half of the push barrier on a replicated
        shard: the CURRENT primary's applied window for this writer must
        reach the last pushed seq.  A shortfall means frames died with a
        demoted primary — replay the unacked tail (the reconnect routes
        through failover) and run the close barrier again.  Raises when
        the window cannot be confirmed within the retry budget; the
        caller's push window stays intact for a later retry.  A scheme
        cutover discovered here transfers the window instead."""
        last = self._push_seq.get(s, 0)
        if not last:
            return
        wkey = self._stream_writer_key(view, s)
        policy = self.retry
        rounds = max(2, policy.max_attempts if policy is not None else 2)
        err: Optional[rpc.RpcError] = None
        for _ in range(rounds):
            addr = None
            try:
                addr = self._route_write(view, s)
                rsp = self._chan(addr).call(
                    "Ps", "WriterSeq", wkey.encode(),
                    timeout_ms=self._ctl_timeout_ms())
            except rpc.RpcError as e:
                err = e
                if e.code == resilience.ESCHEMEMOVED:
                    self._transfer_pushes(view, None)
                    return
                if len(view.replica_sets[s].addresses) > 1 and \
                        self._redirect:
                    # demoted (reroutable) or dead primary: re-resolve;
                    # a dead endpoint is excluded from the sweep
                    exclude = frozenset()
                    if addr is not None and \
                            not self._reroutable(view, s, e):
                        exclude = frozenset({addr})
                    self._failover(view, s, exclude)
                    continue
                raise
            applied, gen = struct.unpack_from("<qq", rsp, 0)
            if applied >= last:
                # confirmed on the live primary — NOW the covering gen
                # is an acked floor for the lossy-promotion guard
                if gen > view._gen_seen[s]:
                    view._gen_seen[s] = gen
                return
            if obs.enabled():
                obs.counter("ps_push_replays").add(1)
            err = rpc.RpcError(
                resilience.ENOTPRIMARY,
                f"shard {s}: applied window {applied} < last pushed "
                f"seq {last} after the close barrier")
            try:
                self._push_frames(view, s)   # replay tail, failover-aware
            except rpc.RpcError as e:
                if e.code != resilience.ESCHEMEMOVED:
                    raise
                self._transfer_pushes(view, None)
                return
            st = self._push_streams.pop(s, None)
            self._push_addr.pop(s, None)
            self._push_recv.pop(s, None)
            self._push_sent.pop(s, None)
            if st is not None:
                st.close()
                st.join(timeout_s=max(1.0, self.timeout_ms / 1000.0))
        raise err  # type: ignore[misc]

    def close(self):
        if self._watcher is not None:
            self._watcher.stop()
            self._watcher = None
        if self._prober is not None:
            self._prober.stop()
            self._prober = None
        for st in self._push_streams.values():
            # Teardown, not a flush barrier — callers wanting the
            # guarantee use flush_gradients() first.  close(), not
            # abort(): these carry a read half whose native relay is
            # freed by the close handshake.
            st.close()
        self._push_streams.clear()
        self._push_addr.clear()
        self._push_recv.clear()
        self._push_sent.clear()
        self._push_unacked.clear()
        self._push_carry.clear()
        for c in self._chans.values():
            c.close()
        self._chans.clear()
