"""Remote parameter-server tier: embedding shards served over the native
RPC fabric, driven from JAX training loops.

This is the DCN tier of the BASELINE #5 workload ("param-server serving
embedding shards, allreduce grads"): each shard is a native Server
(cpp/rpc) holding rows [i*rows_per, (i+1)*rows_per); the client routes ids
to owners (the PartitionChannel "i/N" contract, cpp/cluster/
partition_channel.*) and runs Lookup / ApplyGrad calls. The intra-pod tier
— where the table fits in pod HBM — is brpc_tpu.ps (compiled collectives).

Wire format (little-endian): Lookup req = int32 count ++ int32 ids;
rsp = float32 rows [count, dim]. ApplyGrad req = int32 count ++ int32 ids
++ float32 grads [count, dim]; rsp = empty.  The streaming push
(``StreamApply``) reuses the ApplyGrad framing: the setup RPC carries an
empty request and every stream FRAME is one framed delta — no per-frame
response; application order/completion ride the stream close.
"""

from __future__ import annotations

import dataclasses
import struct
import threading
import time
from typing import List, Optional, Sequence

import numpy as np

from brpc_tpu import obs, resilience, rpc
from brpc_tpu.analysis.race import checked_lock, checked_rwlock


def _record_ps_server(shard_index: int, method: str, count: int,
                      req_len: int, rsp_len: int, t0: int) -> None:
    """PS-side counters: keys/s, bytes in/out, per-shard handler latency
    (the ``add_service`` trampoline separately records the full RPC
    latency; this recorder isolates the table work)."""
    obs.recorder(f"ps_server_shard{shard_index}_{method}").record(
        (time.monotonic_ns() - t0) / 1e9)
    obs.counter("ps_server_keys").add(count)
    obs.counter("ps_server_bytes_in").add(req_len)
    obs.counter("ps_server_bytes_out").add(rsp_len)


class _ExclusiveAsRw:
    """Presents a plain mutex through the ``read()``/``write()`` surface
    (the pre-parallel single-lock serving model — kept as the bench
    baseline for ``bench_ps.py``'s mutex-vs-rwlock comparison)."""

    __slots__ = ("_lock",)

    def __init__(self, lock):
        self._lock = lock

    def read(self):
        return self._lock

    def write(self):
        return self._lock


def _pack_lookup_req(owned: np.ndarray) -> bytearray:
    """Frame a Lookup request into ONE pre-sized buffer, written in place
    (the old ``struct.pack + tobytes + concat`` built three intermediate
    buffers per shard — measurable at 8-client fan-out even after the
    native read path).  The native call paths accept writable buffers
    zero-copy (:func:`rpc._req_ptr`)."""
    req = bytearray(4 + 4 * owned.size)
    struct.pack_into("<i", req, 0, owned.size)
    np.frombuffer(req, np.int32, owned.size, 4)[:] = owned
    return req


def _pack_apply_req(owned: np.ndarray, grads: np.ndarray) -> bytearray:
    """Frame an ApplyGrad request (count ++ ids ++ grads) into one
    pre-sized buffer — same discipline as :func:`_pack_lookup_req`."""
    n = owned.size
    req = bytearray(4 + 4 * n + 4 * grads.size)
    struct.pack_into("<i", req, 0, n)
    np.frombuffer(req, np.int32, n, 4)[:] = owned
    np.frombuffer(req, np.float32, grads.size, 4 + 4 * n)[:] = \
        grads.reshape(-1)
    return req


def _unpack_apply(payload: bytes, base: int, rows_per: int, dim: int):
    """Parse + validate one ApplyGrad-framed delta (unary request body or
    stream frame): returns ``(local_ids, grads[count, dim])``.  Raises
    ``ValueError`` on out-of-range ids BEFORE anything is enqueued, so a
    bad contribution can never poison a combined batch."""
    (count,) = struct.unpack_from("<i", payload, 0)
    ids = np.frombuffer(payload, np.int32, count, 4) - base
    if ids.size and (ids.min() < 0 or ids.max() >= rows_per):
        raise ValueError(
            f"ids outside shard [{base}, {base + rows_per}) "
            f"for shard base {base}")
    grads = np.frombuffer(payload, np.float32, count * dim, 4 + 4 * count)
    return ids, grads.reshape(count, dim)


class GradCombiner:
    """Per-shard server-side write combiner (the execution-queue
    write-combining shape, cpp/fiber/execution_queue.h, applied to
    gradient application).

    ApplyGrad contributions ENQUEUE here instead of applying
    individually; whoever finds the combiner idle becomes the LEADER and
    drains every pending contribution into ONE concatenated application
    per drained batch — ``apply_fn`` runs once per batch, so write-lock
    hold time, snapshot installs (CPU shard) and scatter launches (device
    shard) are paid per BATCH, not per request.  Duplicate-id
    contributions sum exactly: both ``np.subtract.at`` and the device
    scatter (``unique_indices = false``) accumulate repeated indices, so
    concatenation IS the combine — commutative, order-independent up to
    float addition order.

    ``add(wait=True)`` (unary handlers) blocks until the caller's batch
    is applied and re-raises the batch's failure; ``add(wait=False)``
    (stream frames — no per-frame response exists) returns immediately,
    and :meth:`flush` provides the "everything before this point is
    applied" barrier by riding the queue as an empty contribution.
    Followers never lead and the leader never waits on followers, so
    there is no circular wait even on a single worker."""

    __slots__ = ("_apply", "_dim", "_mu", "_q", "_draining", "_shut",
                 "last_error")

    def __init__(self, apply_fn, dim: int):
        self._apply = apply_fn          # apply_fn(local_ids, grads): ONE
        self._dim = dim                 # combined application
        self._mu = checked_lock("ps.combine")
        self._q: list = []
        self._draining = False
        self._shut = False
        self.last_error: Optional[BaseException] = None

    def add(self, ids: np.ndarray, grads: np.ndarray,
            wait: bool = True) -> None:
        # [ids, grads, done-event, error] — error is filled by whichever
        # leader applies the batch this entry lands in.
        entry = [ids, grads, threading.Event() if wait else None, None]
        with self._mu:
            if self._shut:
                # Server teardown: late contributions (a dead client's
                # stream receiver being torn down by the socket-failure
                # hook, frames still in its delivery queue) are dropped —
                # the shard/device behind apply_fn may already be gone.
                return
            self._q.append(entry)
            leader = not self._draining
            if leader:
                self._draining = True
        if not leader:
            ev = entry[2]
            if ev is not None:
                ev.wait()
                if entry[3] is not None:
                    raise entry[3]
            return
        self._drain()
        if entry[3] is not None:
            raise entry[3]

    def _drain(self) -> None:
        """Leader loop: drain batches until the queue is empty (entries
        enqueued while a batch applies land in the next one)."""
        while True:
            with self._mu:
                batch = self._q
                if not batch:
                    self._draining = False
                    return
                self._q = []
            err: Optional[BaseException] = None
            try:
                if len(batch) == 1:
                    ids, grads = batch[0][0], batch[0][1]
                else:
                    ids = np.concatenate([e[0] for e in batch])
                    grads = np.concatenate([e[1] for e in batch])
                if ids.size:
                    self._apply(ids, grads)
                    if obs.enabled():
                        obs.counter("ps_combined_applies").add(1)
                        obs.counter("ps_combined_keys").add(int(ids.size))
                        obs.maxer("ps_combine_depth").update(len(batch))
            except Exception as e:  # noqa: BLE001 — delivered per entry
                err = e
                with self._mu:
                    self.last_error = e
                if obs.enabled():
                    obs.counter("ps_combine_errors").add(1)
            for e_ in batch:
                e_[3] = err
                if e_[2] is not None:
                    e_[2].set()

    def flush(self) -> None:
        """Returns once every contribution enqueued BEFORE this call has
        been applied (the stream-close barrier).  Raises the failure of
        the flush batch, if any.  A no-op after :meth:`shutdown`."""
        self.add(np.empty(0, np.int32),
                 np.empty((0, self._dim), np.float32), wait=True)

    def shutdown(self) -> None:
        """Stops accepting contributions and waits for any in-flight
        drain to finish.  Server close paths call this BEFORE destroying
        the table/shard/device behind ``apply_fn``, so a drain can never
        race resource teardown — late frames from dying streams are
        dropped instead of applied to freed state."""
        with self._mu:
            self._shut = True
            draining = self._draining
        while draining:
            time.sleep(0.001)
            with self._mu:
                draining = self._draining


class _ApplyStreamReceiver:
    """Server half of the streaming gradient push: each frame is one
    ApplyGrad-framed delta fed straight into the shard's combiner (no
    per-frame response).  Runs serialized on the stream's native
    delivery fiber — a combiner drain happening here delays the
    consumed-bytes feedback, which is exactly how server-side apply cost
    back-pressures the pushing trainer.  ``on_closed`` flushes the
    combiner BEFORE the server's half closes, so a client's
    ``close(); join()`` is an "every pushed delta is applied" barrier."""

    __slots__ = ("_server",)

    def __init__(self, server):
        self._server = server

    def on_data(self, data: bytes) -> None:
        self._server._apply_frame(data)

    def on_closed(self) -> None:
        self._server._combiner.flush()


class PsShardServer:
    """One embedding shard behind a native RPC server.

    ``native_read=True`` serves ``Lookup`` with ZERO Python in the loop:
    a native generation-versioned shard (:class:`rpc.PsShard`) is
    attached to the same service, and the Python tier keeps the whole
    write path — ``ApplyGrad`` mutates the numpy table under the write
    lock, then publishes an immutable snapshot via ``install``.  Both
    paths serve ONE table; reads never see a torn row because snapshots
    are immutable and generation-pinned (the device shard's
    handle-generation scheme, moved into the native core).  Note that
    server-side fault injection and obs hooks live in the Python
    trampoline, so with ``native_read`` they apply to the write path
    only — the reference's position (SURVEY §3.1) is that the read hot
    path IS the native handler.

    Write-path scale (the read path's mirror image):

    - ``combine=True`` routes unary ApplyGrad through a
      :class:`GradCombiner` — concurrent writers' grads coalesce and the
      write lock / snapshot install is paid once per DRAINED BATCH
      instead of once per request (the dominant unary cost under
      ``native_read``, where every apply memcpy's the whole table).
    - ``stream=True`` additionally serves ``StreamApply``: a client
      opens an ordered flow-controlled stream (``Channel.stream`` /
      ``RemoteEmbedding.push_gradients``) and ships framed deltas at
      wire rate, no per-call dispatch; frames feed the combiner
      directly and the client's ``close(); join()`` barrier guarantees
      application.  Because the combiner sums duplicate ids exactly and
      float addition is commutative here, unary / combined / streamed
      orderings land byte-identical tables for exactly-representable
      gradients (proven in tests/test_ps_stream.py)."""

    def __init__(self, vocab: int, dim: int, shard_index: int,
                 num_shards: int, lr: float = 0.1, seed: int = 0,
                 lock_mode: str = "rw", native_read: bool = False,
                 combine: bool = False, stream: bool = False):
        if vocab % num_shards:
            raise ValueError("num_shards must divide vocab")
        self.shard_index = shard_index
        self.rows_per = vocab // num_shards
        self.base = shard_index * self.rows_per
        self.dim = dim
        self.lr = lr
        rng = np.random.default_rng(seed + shard_index)
        self.table = (rng.standard_normal((self.rows_per, dim)) * 0.02
                      ).astype(np.float32)
        # Handlers run concurrently on fiber workers (the trampoline
        # releases the GIL, and numpy releases it again for big ops): a
        # Lookup gather racing an ApplyGrad scatter-sub on overlapping
        # rows reads torn updates.  Reads share, writes exclude: hot read
        # loads gather in parallel while ApplyGrad takes the write side.
        # lock_mode="mutex" restores the old fully-serialized model (the
        # bench baseline).
        if lock_mode == "rw":
            self._mu = checked_rwlock("ps.shard")
        elif lock_mode == "mutex":
            self._mu = _ExclusiveAsRw(checked_lock("ps.shard"))
        else:
            raise ValueError(f"unknown lock_mode {lock_mode!r}")
        self.native_read = bool(native_read)
        self.combine = bool(combine)
        self.stream = bool(stream)
        self._shard: "Optional[rpc.PsShard]" = None
        self._install_gen = 0
        # The combiner exists whenever anything feeds it: unary combining
        # (combine) or streamed deltas (stream — frames ALWAYS combine,
        # they have no per-frame response to serialize on).
        self._combiner: Optional[GradCombiner] = (
            GradCombiner(self._apply_batch, dim)
            if (self.combine or self.stream) else None)
        self.server = rpc.Server()
        if self.native_read:
            self._shard = rpc.PsShard(vocab, dim, shard_index, num_shards)
            self._shard.install(self.table, 0)
            self.server.add_ps_service(
                "Ps", self._shard,
                self._handle_stream if self.stream else self._handle,
                stream=self.stream)
        elif self.stream:
            self.server.add_stream_handler("Ps", self._handle_stream)
        else:
            self.server.add_service("Ps", self._handle)
        # `_status` rides along so the health-check prober can revive
        # this shard after a circuit-breaker isolation (resilience tier).
        self.server.add_status_service()
        self.port = self.server.start("127.0.0.1:0")

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    def _handle(self, method: str, payload: bytes) -> bytes:
        if not obs.enabled():
            return self._serve(method, payload)
        t0 = time.monotonic_ns()
        rsp = self._serve(method, payload)
        (count,) = struct.unpack_from("<i", payload, 0)
        _record_ps_server(self.shard_index, method, count, len(payload),
                          len(rsp), t0)
        return rsp

    def _handle_stream(self, method: str, payload: bytes, accept) -> bytes:
        """Stream-capable trampoline target: ``StreamApply`` binds the
        client's push stream to this shard's combiner; everything else is
        the plain :meth:`_handle` contract."""
        if method == "StreamApply":
            accept(_ApplyStreamReceiver(self))
            return b""
        return self._handle(method, payload)

    def _apply_frame(self, payload: bytes) -> None:
        """One streamed delta: parse/validate, enqueue without waiting
        (frames have no response; the close barrier flushes)."""
        t0 = time.monotonic_ns() if obs.enabled() else 0
        ids, grads = _unpack_apply(payload, self.base, self.rows_per,
                                   self.dim)
        self._combiner.add(ids, grads, wait=False)
        if t0:
            _record_ps_server(self.shard_index, "StreamApply",
                              int(ids.size), len(payload), 0, t0)

    def _apply_batch(self, ids: np.ndarray, grads: np.ndarray) -> None:
        """ONE combined application for a drained batch: a single
        unbuffered ``subtract.at`` (duplicate ids sum exactly) and — under
        ``native_read`` — a single snapshot install, regardless of how
        many requests combined into the batch."""
        with self._mu.write():
            np.subtract.at(self.table, ids, self.lr * grads)
            if self._shard is not None:
                self._install_gen += 1
                self._shard.install(self.table, self._install_gen)

    def _serve(self, method: str, payload: bytes) -> bytes:
        (count,) = struct.unpack_from("<i", payload, 0)
        ids = np.frombuffer(payload, np.int32, count, 4) - self.base
        if ids.size and (ids.min() < 0 or ids.max() >= self.rows_per):
            # Out-of-range ids would wrap to wrong rows via negative indexing.
            raise ValueError(
                f"ids outside shard [{self.base}, "
                f"{self.base + self.rows_per}) for shard base {self.base}"
            )
        if method == "Lookup":
            with self._mu.read():
                return self.table[ids].tobytes()
        if method == "ApplyGrad":
            grads = np.frombuffer(payload, np.float32,
                                  count * self.dim, 4 + 4 * count)
            if self.combine:
                # Combined write path: enqueue and wait for the batch —
                # the combiner's leader applies once per drained batch.
                self._combiner.add(ids,
                                   grads.reshape(count, self.dim))
                return b""
            with self._mu.write():
                np.subtract.at(self.table, ids,
                               self.lr * grads.reshape(count, self.dim))
                if self._shard is not None:
                    # Publish the post-update table as a fresh immutable
                    # generation; the install snapshot happens under the
                    # write lock so concurrent appliers serialize and no
                    # update is ever skipped by a stale publish.
                    self._install_gen += 1
                    self._shard.install(self.table, self._install_gen)
            return b""
        raise ValueError(f"unknown method {method}")

    @property
    def native_lookups(self) -> int:
        """Lookups served with zero Python in the loop (0 unless
        ``native_read``)."""
        return 0 if self._shard is None else self._shard.native_lookups

    def close(self):
        # Server first: its native Lookup handlers gather from the
        # shard's snapshots and must drain before the shard dies.  Then
        # the combiner: a dying stream's receiver teardown can still
        # flush into it after Join (its delivery queue outlives the
        # connection), and an applying drain must not race shard death.
        self.server.close()
        if self._combiner is not None:
            self._combiner.shutdown()
        if self._shard is not None:
            self._shard.close()
            self._shard = None


class _TableGen:
    """One generation of the device-resident table: the buffer handle plus
    the pins keeping it alive.  A retired generation's handle is released
    when the last pin drops (never while a Lookup gathers from it)."""

    __slots__ = ("handle", "pins", "retired")

    def __init__(self, handle: int):
        self.handle = handle
        self.pins = 0
        self.retired = False


class DevicePsShardServer:
    """Embedding shard whose table is RESIDENT IN DEVICE HBM.

    The CPU variant above holds its table in host numpy; this one keeps it
    behind a native device-buffer handle (the RDMA-lkey analog,
    cpp/device/pjrt_device.h) and serves Lookup/ApplyGrad as compiled
    gather / scatter-sub launches (cpp/device/pjrt_executable.cc). Request
    ids and gradients DMA host->HBM through the registered block pool;
    looked-up rows DMA back into pooled blocks. No JAX anywhere in the
    serving path — this is the reference's "transport swap is invisible
    above Socket" contract with PJRT as the transport
    (docs/en/rdma.md:34 analog).

    Concurrency is a handle-GENERATION scheme, not a big lock: the update
    is functional on-device (scatter-sub emits a fresh table buffer), so
    ``ps.device_shard`` guards only the tiny generation map.  Lookup pins
    the current generation, gathers/fetches OUTSIDE the lock, unpins.
    ApplyGrad pins a snapshot, scatters outside the lock, then installs
    the output under the lock IF its snapshot is still current — a lost
    install race (concurrent ApplyGrad got there first) discards the
    stale output and redoes the scatter against the new table, so no
    update is ever lost and at least one writer makes progress per round.
    Lookups overlap ApplyGrads and each other; no lock is ever held
    across a blocking ``brt_device_*`` call (RACECHECK-clean by
    construction).

    The optimistic install has a cost under write FAN-IN: k racing
    writers scatter k candidate tables but only one installs — the rest
    discard whole scatter outputs and redo (``ps_device_wasted_launches``
    counts them; ~linear in writers).  ``combine=True`` routes ApplyGrad
    through a :class:`GradCombiner` instead: racing writers coalesce and
    the leader launches ONE scatter per drained batch (the device
    scatter sums duplicate ids — ``unique_indices = false``), so wasted
    launches drop to at most one per batch (only a Lookup-free
    concurrent installer could still race, and appliers all ride the
    combiner).  ``stream=True`` serves ``StreamApply`` into the same
    combiner.
    """

    def __init__(self, vocab: int, dim: int, shard_index: int,
                 num_shards: int, lr: float = 0.1, seed: int = 0,
                 device_client: "rpc.DeviceClient | None" = None,
                 device_index: int = 0, combine: bool = False,
                 stream: bool = False):
        if vocab % num_shards:
            raise ValueError("num_shards must divide vocab")
        self.shard_index = shard_index
        self.rows_per = vocab // num_shards
        self.base = shard_index * self.rows_per
        self.dim = dim
        self.lr = lr
        self._owns_dev = device_client is None
        self.dev = device_client or rpc.DeviceClient()
        self.device_index = device_index
        rng = np.random.default_rng(seed + shard_index)
        table = (rng.standard_normal((self.rows_per, dim)) * 0.02
                 ).astype(np.float32)
        # The table lives on-device from here on; the handle is the table,
        # versioned by generation (see class docstring).
        self._gen = 0
        self._tables = {0: _TableGen(self.dev.stage(table, device_index))}
        # Resident lr scalar: scatter_sub's 4th operand (stays in HBM).
        self.lr_h = self.dev.stage(np.array(lr, np.float32), device_index)
        self._gather = {}   # bucket size -> compiled gather executable
        self._scatter = {}  # bucket size -> compiled scatter-sub executable
        # Guards ONLY the generation map (_gen/_tables pins) — never held
        # across a device call, so handlers on fiber workers overlap.
        self._mu = checked_lock("ps.device_shard")
        # Guards the executable caches; held across the (cold, per-bucket)
        # compile but never across execute/fetch.
        self._exe_mu = checked_lock("ps.device_shard.exe")
        self.combine = bool(combine)
        self.stream = bool(stream)
        self._combiner: Optional[GradCombiner] = (
            GradCombiner(self._apply_batch, dim)
            if (self.combine or self.stream) else None)
        self.server = rpc.Server()
        if self.stream:
            self.server.add_stream_handler("Ps", self._handle_stream)
        else:
            self.server.add_service("Ps", self._handle)
        self.server.add_status_service()
        self.port = self.server.start("127.0.0.1:0")

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    def _pin_current(self):
        """Pin the live table generation: returns ``(gen, handle)`` with
        the handle guaranteed alive until the matching :meth:`_unpin`."""
        with self._mu:
            gen = self._gen
            entry = self._tables[gen]
            entry.pins += 1
            return gen, entry.handle

    def _unpin(self, gen: int) -> None:
        release = 0
        with self._mu:
            entry = self._tables[gen]
            entry.pins -= 1
            if entry.retired and entry.pins == 0:
                del self._tables[gen]
                release = entry.handle
        if release:
            self.dev.release(release)

    @property
    def table(self) -> np.ndarray:
        """Host snapshot (DMAs the resident table down; test/debug use).
        The pin keeps the snapshot generation alive across the DMA — a
        concurrent ApplyGrad swap retires it, never frees it mid-fetch."""
        gen, table_h = self._pin_current()
        try:
            raw = self.dev.fetch(table_h)
        finally:
            self._unpin(gen)
        return np.frombuffer(raw, np.float32).reshape(self.rows_per,
                                                      self.dim).copy()

    def _gather_exe(self, k: int):
        with self._exe_mu:
            exe = self._gather.get(k)
            if exe is None:
                mlir = self.dev.mlir("gather_rows", self.rows_per,
                                     self.dim, k)
                exe = self._gather[k] = self.dev.compile(mlir)
            return exe

    def _scatter_exe(self, k: int):
        with self._exe_mu:
            exe = self._scatter.get(k)
            if exe is None:
                mlir = self.dev.mlir("scatter_sub", self.rows_per,
                                     self.dim, k)
                exe = self._scatter[k] = self.dev.compile(mlir)
            return exe

    @staticmethod
    def _bucket(count: int) -> int:
        """Round the batch size up to a power of two so the executable
        cache stays log-bounded instead of compiling per distinct count
        (padding: extra ids hit row 0 with zero gradients — a no-op)."""
        return 1 << max(0, count - 1).bit_length()

    def _handle(self, method: str, payload: bytes) -> bytes:
        if not obs.enabled():
            return self._serve(method, payload)
        t0 = time.monotonic_ns()
        rsp = self._serve(method, payload)
        (count,) = struct.unpack_from("<i", payload, 0)
        _record_ps_server(self.shard_index, method, count, len(payload),
                          len(rsp), t0)
        return rsp

    def _handle_stream(self, method: str, payload: bytes, accept) -> bytes:
        if method == "StreamApply":
            accept(_ApplyStreamReceiver(self))
            return b""
        return self._handle(method, payload)

    def _apply_frame(self, payload: bytes) -> None:
        t0 = time.monotonic_ns() if obs.enabled() else 0
        ids, grads = _unpack_apply(payload, self.base, self.rows_per,
                                   self.dim)
        self._combiner.add(ids, grads, wait=False)
        if t0:
            _record_ps_server(self.shard_index, "StreamApply",
                              int(ids.size), len(payload), 0, t0)

    def _apply_batch(self, ids: np.ndarray, grads: np.ndarray) -> None:
        """ONE combined scatter launch + install for a drained batch:
        the on-chip scatter sums duplicate ids, so the concatenated
        batch applies exactly; padding ids hit row 0 with zero grads
        (a no-op, same trick as the unary path)."""
        bucket = self._bucket(int(ids.size))
        padded_ids = np.zeros(bucket, np.int32)
        padded_ids[:ids.size] = ids
        padded_g = np.zeros((bucket, self.dim), np.float32)
        padded_g[:ids.size] = grads
        ids_h = self.dev.stage(padded_ids, self.device_index)
        try:
            g_h = self.dev.stage(padded_g, self.device_index)
            try:
                self._apply_grad(bucket, ids_h, g_h)
            finally:
                self.dev.release(g_h)
        finally:
            self.dev.release(ids_h)

    def _serve(self, method: str, payload: bytes) -> bytes:
        (count,) = struct.unpack_from("<i", payload, 0)
        ids = np.frombuffer(payload, np.int32, count, 4) - self.base
        if ids.size and (ids.min() < 0 or ids.max() >= self.rows_per):
            raise ValueError(
                f"ids outside shard [{self.base}, "
                f"{self.base + self.rows_per}) for shard base {self.base}"
            )
        if method == "ApplyGrad" and self.combine:
            # Combined write path: no per-request staging/launch — the
            # combiner's leader stages and launches once per batch.
            grads = np.frombuffer(payload, np.float32, count * self.dim,
                                  4 + 4 * count).reshape(count, self.dim)
            self._combiner.add(ids, grads)
            return b""
        bucket = self._bucket(count)
        padded_ids = np.zeros(bucket, np.int32)
        padded_ids[:count] = ids
        ids_h = self.dev.stage(padded_ids, self.device_index)
        try:
            if method == "Lookup":
                gen, table_h = self._pin_current()
                try:
                    outs = self._gather_exe(bucket).execute(
                        [table_h, ids_h])
                finally:
                    self._unpin(gen)
                rows_h = outs[0][0]
                try:
                    raw = self.dev.fetch(rows_h)
                finally:
                    self.dev.release(rows_h)
                return raw[:count * self.dim * 4]
            if method == "ApplyGrad":
                grads = np.zeros((bucket, self.dim), np.float32)
                grads[:count] = np.frombuffer(
                    payload, np.float32, count * self.dim,
                    4 + 4 * count).reshape(count, self.dim)
                g_h = self.dev.stage(grads, self.device_index)
                try:
                    return self._apply_grad(bucket, ids_h, g_h)
                finally:
                    self.dev.release(g_h)
            raise ValueError(f"unknown method {method}")
        finally:
            self.dev.release(ids_h)

    def _apply_grad(self, bucket: int, ids_h: int, g_h: int) -> bytes:
        while True:
            gen, table_h = self._pin_current()
            try:
                # scatter_sub scales by the resident lr scalar on-chip:
                # out = table - scatter(lr * grads); functional — the
                # output buffer is a CANDIDATE new table.
                outs = self._scatter_exe(bucket).execute(
                    [table_h, ids_h, g_h, self.lr_h])
            finally:
                self._unpin(gen)
            new_table = outs[0][0]
            release_old = 0
            with self._mu:
                installed = self._gen == gen
                if installed:
                    old = self._tables[gen]
                    old.retired = True
                    if old.pins == 0:
                        del self._tables[gen]
                        release_old = old.handle
                    self._gen = gen + 1
                    self._tables[gen + 1] = _TableGen(new_table)
            if installed:
                if release_old:
                    self.dev.release(release_old)
                return b""
            # Install race lost: a concurrent ApplyGrad swapped first and
            # our output was computed against a stale table.  Discard it
            # and redo against the new current generation — the winner
            # already made progress, so this terminates.  Each discard is
            # a whole wasted scatter launch; the combiner exists to make
            # this counter stop scaling with write fan-in.
            if obs.enabled():
                obs.counter("ps_device_wasted_launches").add(1)
            self.dev.release(new_table)

    def close(self):
        self.server.close()
        # Latch the combiner before device teardown (same reasoning as
        # PsShardServer.close: late stream frames must drop, not scatter
        # into released buffers).
        if self._combiner is not None:
            self._combiner.shutdown()
        for exe in list(self._gather.values()) + list(
                self._scatter.values()):
            exe.close()
        with self._mu:
            entries = list(self._tables.values())
            self._tables.clear()
        for entry in entries:
            self.dev.release(entry.handle)
        self.dev.release(self.lr_h)
        if self._owns_dev:
            self.dev.close()


class RemoteEmbedding:
    """Client view of a sharded remote table (owner-routed access).

    Per-shard requests fan out CONCURRENTLY via ``Channel.call_async``
    (the ParallelChannel-over-PartitionChannel shape, cpp/cluster/
    parallel_channel.* + partition_channel.*): whole-batch latency is
    max(shard RTT) instead of sum(shard RTT).  ``parallel=False``
    restores the sequential per-shard loop (the bench baseline).

    Fault tolerance (brpc_tpu.resilience) is per shard:

    - ``retry`` — a failed shard attempt is retried with backoff under
      the batch's remaining ``deadline_ms`` budget while the other
      shards' responses are already in; a batch completes despite a
      shard failing its first attempt.
    - ``backup_ms`` — a shard that has not answered in N ms gets a
      hedged second attempt; the first completion wins and the loser is
      cancelled natively.
    - ``breakers`` — a BreakerRegistry keyed by shard address: open
      shards fail fast instead of burning the timeout, every outcome
      feeds the shard's EMA windows, and ``health_check=True`` runs a
      background prober that revives isolated shards via their
      ``_status.health`` builtin.
    - On a non-retriable partial failure the batch abandons its
      straggler shards: still-pending calls are CANCELLED (native
      ``StartCancel``) before being reaped, so the error surfaces at
      max(shard) latency, not sum.

    The WRITE path additionally has a streaming mode:
    :meth:`push_gradients` ships framed deltas over one persistent
    ordered flow-controlled stream per owner shard (feeding the server's
    gradient combiner directly — no per-call dispatch), with
    :meth:`flush_gradients` as the applied-everything barrier and
    reconnect-under-the-retry-budget on stream breakage.  The unary
    :meth:`apply_gradients` stays as the synchronous path."""

    @classmethod
    def from_registry(cls, registry_addr: str, cluster: str, vocab: int,
                      dim: int, timeout_ms: int = 2000,
                      wait_ms: int = 5000) -> "RemoteEmbedding":
        """Resolves the shard list from the native naming registry
        (brpc_tpu.naming): shards register with tag "<shard>/<num>", and
        the watch blocks until a CONSISTENT full set is present (all
        shards 0..num-1 with one num). Service discovery for the PS tier
        — no static address list."""
        from brpc_tpu.naming import NamingClient
        reg = NamingClient(registry_addr)
        deadline = time.monotonic() + wait_ms / 1000.0
        version = 0
        groups: dict = {}
        # Each watch IS the poll; its blocking window follows the shared
        # backoff helper (exponential + deterministic jitter, capped by
        # the remaining deadline) instead of a fixed interval — early
        # polls catch a cluster mid-registration fast, later ones stop
        # hammering a registry that clearly isn't filling up.  The
        # NamingClient reuses one connection per thread across polls.
        backoff = resilience.Backoff(base_ms=100.0, multiplier=2.0,
                                     max_ms=2000.0, jitter=0.5)
        poll = 0
        while True:
            remaining_ms = (deadline - time.monotonic()) * 1000.0
            watch_ms = max(1, int(min(backoff.delay_ms(poll),
                                      max(remaining_ms, 1.0))))
            poll += 1
            nodes, version = reg.watch(cluster, known_version=version,
                                       wait_ms=watch_ms)
            # Group by the tag's "/num" so a stale entry from an old
            # sharding cannot block a complete consistent new set.
            groups = {}
            for n in nodes:
                tag = n.get("tag", "")
                if "/" not in tag:
                    continue
                s_str, num_str = tag.split("/", 1)
                try:
                    sh, nm = int(s_str), int(num_str)
                except ValueError:
                    continue
                shard_map = groups.setdefault(nm, {})
                # Duplicate index within one sharding: a restarted shard's
                # fresh registration supersedes a TTL-lingering stale one;
                # the registry lists entries in registration order, so the
                # LAST occurrence is the newest.
                shard_map[sh] = n["addr"]
            for num, shard_map in sorted(groups.items(), reverse=True):
                if num > 0 and len(shard_map) == num and \
                        all(i in shard_map for i in range(num)):
                    addrs = [shard_map[i] for i in range(num)]
                    reg.close()
                    return cls(addrs, vocab, dim, timeout_ms=timeout_ms)
            if time.monotonic() > deadline:
                reg.close()
                raise TimeoutError(
                    f"cluster '{cluster}' has no complete sharding: "
                    f"{ {nm: sorted(m) for nm, m in groups.items()} }")

    def __init__(self, addresses: Sequence[str], vocab: int, dim: int,
                 timeout_ms: int = 2000, parallel: bool = True, *,
                 retry: "Optional[resilience.RetryPolicy]" = None,
                 deadline_ms: Optional[float] = None,
                 backup_ms: Optional[float] = None,
                 breakers: "Optional[resilience.BreakerRegistry]" = None,
                 health_check: bool = False,
                 health_interval_ms: float = 200.0,
                 push_window_bytes: int = 0):
        self.vocab = vocab
        self.dim = dim
        self.n = len(addresses)
        self.rows_per = vocab // self.n
        self.parallel = parallel
        self.timeout_ms = timeout_ms
        #: per-shard unconsumed-bytes window for push streams (0 = the
        #: native 2MB default) — the backpressure knob of push_gradients
        self.push_window_bytes = push_window_bytes
        self._push_streams: dict = {}
        self.addresses = [str(a) for a in addresses]
        self.retry = retry
        self.deadline_ms = deadline_ms
        self.backup_ms = backup_ms
        self.breakers = breakers
        if health_check and breakers is None:
            self.breakers = breakers = resilience.BreakerRegistry()
        if self.breakers is not None:
            # Register every shard up front: the cluster-recover guard
            # counts working endpoints, so the registry must know the
            # full cluster, not just the shards that have failed.
            for a in self.addresses:
                self.breakers.breaker_for(a)
        self._prober: "Optional[resilience.HealthProber]" = None
        if health_check:
            self._prober = resilience.HealthProber(
                self.breakers, interval_ms=health_interval_ms)
            self._prober.start()
        self.channels: List[rpc.Channel] = [
            rpc.Channel(a, timeout_ms=timeout_ms) for a in addresses
        ]

    def _breaker(self, s: int) -> "Optional[resilience.CircuitBreaker]":
        if self.breakers is None:
            return None
        return self.breakers.breaker_for(self.addresses[s])

    def _retry_shard(self, s: int, method: str, req: bytes,
                     exc: Exception, deadline: Optional[float]) -> bytes:
        """A shard's first (fan-out) attempt failed: classify, back off,
        and retry it under the batch's remaining budget — the other
        shards' work is already done, so only this shard re-runs."""
        policy = self.retry
        if policy is None or not policy.do_retry(exc, 0):
            raise exc
        remaining_ms: Optional[float] = None
        if deadline is not None:
            remaining_ms = (deadline - time.monotonic()) * 1000.0
            if remaining_ms < 2.0:
                raise exc
        delay = policy.backoff.delay_ms(0)
        if remaining_ms is not None:
            delay = min(delay, remaining_ms - 1.0)
        resilience.sleep_ms(delay)
        if remaining_ms is not None:
            remaining_ms = max(1.0, (deadline - time.monotonic()) * 1000.0)
        follow = dataclasses.replace(
            policy, max_attempts=max(1, policy.max_attempts - 1))
        return resilience.call_with_retry(
            self.channels[s], "Ps", method, req, policy=follow,
            deadline_ms=remaining_ms, breaker=self._breaker(s),
            backup_ms=self.backup_ms)

    def _fan_out(self, method: str, items: List[tuple]) -> List[bytes]:
        """Issue every (shard, req) concurrently, then collect with the
        resilience policy applied per shard.  Responses align with
        ``items``.  On an unrecoverable shard failure the remaining
        in-flight calls are cancelled (straggler abandonment) before the
        error propagates."""
        deadline = time.monotonic() + self.deadline_ms / 1000.0 \
            if self.deadline_ms is not None else None

        def _budget() -> Optional[int]:
            t = None
            if deadline is not None:
                t = max(1, int((deadline - time.monotonic()) * 1000.0))
            if self.retry is not None:
                t = self.retry.cap_attempt_timeout(t)
            return t

        # per item: a PendingCall in flight, an RpcError whose start
        # failed (client fault / local transport error — handled like a
        # failed attempt in the join phase), or None once consumed
        pending: List[object] = [None] * len(items)
        out: List[Optional[bytes]] = [None] * len(items)
        group: "Optional[rpc.CallGroup]" = None
        try:
            for i, (s, req) in enumerate(items):
                b = self._breaker(s)
                if b is not None and b.isolated():
                    if obs.enabled():
                        obs.counter("rpc_breaker_fastfail").add(1)
                    raise rpc.RpcError(
                        resilience.EBREAKEROPEN,
                        f"shard {s} ({self.addresses[s]}) isolated by "
                        f"circuit breaker")
                try:
                    # managed fan-out set: every entry is joined or
                    # cancelled+closed in the finally below
                    pending[i] = self.channels[s].call_async(  # lint: allow-handle-escape
                        "Ps", method, req, timeout_ms=_budget(),
                        tag="attempt=0")
                except rpc.RpcError as e:
                    pending[i] = e  # keep fanning out; retried below
            if self.backup_ms is not None:
                # Hedged path: ordered per-shard collection — each hedge
                # arms backup_ms on its in-flight primary and waits on its
                # OWN native call group inside backup_call (exact wakes,
                # no polling slices).
                for i, (s, req) in enumerate(items):
                    pc, pending[i] = pending[i], None
                    b = self._breaker(s)
                    try:
                        if isinstance(pc, rpc.RpcError):
                            raise pc
                        rsp = resilience.backup_call(
                            self.channels[s], "Ps", method, req,
                            backup_ms=self.backup_ms,
                            timeout_ms=_budget(), primary=pc)
                    except rpc.RpcError as e:
                        if b is not None:
                            b.on_call_end(e.code)
                        rsp = self._retry_shard(s, method, req, e,
                                                deadline)
                    else:
                        if b is not None:
                            b.on_call_end(0)
                    out[i] = rsp
                return out  # type: ignore[return-value]
            # Unhedged path: completion-ORDER collection over one native
            # fan-in group (the ParallelChannel CountdownEvent shape).
            # Every wait_any wakes on exactly one shard completing — no
            # time slices — and a failing shard starts its retry (or
            # aborts the batch) the moment it fails, never behind a
            # slower sibling.  Start-failures are already complete, so
            # they are classified first (fail fast / retry immediately).
            group = rpc.CallGroup()
            waiting: List[int] = []
            for i, pc in enumerate(pending):
                if isinstance(pc, rpc.PendingCall):
                    group.add(pc)
                    waiting.append(i)
            for i, (s, req) in enumerate(items):
                if isinstance(pending[i], rpc.RpcError):
                    e, pending[i] = pending[i], None
                    b = self._breaker(s)
                    if b is not None:
                        b.on_call_end(e.code)
                    out[i] = self._retry_shard(s, method, req, e, deadline)
            while waiting:
                group.wait_any()
                done_i = next((i for i in waiting
                               if pending[i].wait(0.0)), None)
                if done_i is None:  # pragma: no cover — wait_any contract
                    continue
                waiting.remove(done_i)
                s, req = items[done_i]
                pc, pending[done_i] = pending[done_i], None
                b = self._breaker(s)
                try:
                    rsp = pc.join()
                except rpc.RpcError as e:
                    if b is not None:
                        b.on_call_end(e.code)
                    rsp = self._retry_shard(s, method, req, e, deadline)
                else:
                    if b is not None:
                        b.on_call_end(0)
                out[done_i] = rsp
            return out  # type: ignore[return-value]
        finally:
            if group is not None:
                group.close()
            # Partial failure: cancel the stragglers so close() reaps
            # them at cancel speed, not at their full timeout.
            for pc in pending:
                if isinstance(pc, rpc.PendingCall):
                    pc.cancel()
                    pc.close()

    def _call_shard(self, s: int, method: str, req: bytes) -> bytes:
        """Sequential-path shard call with the same per-shard policy."""
        return self.channels[s].call(
            "Ps", method, req, retry=self.retry,
            deadline_ms=self.deadline_ms, backup_ms=self.backup_ms,
            breaker=self._breaker(s))

    def _owner_split(self, flat_ids: np.ndarray):
        if flat_ids.size and (flat_ids.min() < 0
                              or flat_ids.max() >= self.vocab):
            # An out-of-range id matches no shard: lookup() would otherwise
            # return uninitialized rows for it.
            raise ValueError(
                f"ids must be in [0, {self.vocab}); got "
                f"[{flat_ids.min()}, {flat_ids.max()}]"
            )
        owners = flat_ids // self.rows_per
        for s in range(self.n):
            mask = owners == s
            if mask.any():
                yield s, np.nonzero(mask)[0], flat_ids[mask]

    def lookup(self, ids: np.ndarray) -> np.ndarray:
        rec = obs.enabled()
        if rec:
            t0 = time.monotonic_ns()
        flat = np.asarray(ids, np.int32).reshape(-1)
        out = np.empty((flat.size, self.dim), np.float32)
        nbytes_in = 0
        nbytes_out = 0
        if self.parallel:
            # Start every owner-shard call before joining any: the shards
            # serve concurrently and the batch pays max(shard), not
            # sum(shard).  _fan_out applies the per-shard resilience
            # policy (retry/hedge/breaker) and cancels stragglers on an
            # unrecoverable partial failure.
            split = list(self._owner_split(flat))
            items = []
            for s, positions, owned in split:
                req = _pack_lookup_req(owned)
                nbytes_out += len(req)
                items.append((s, req))
            for (s, positions, owned), rsp in zip(
                    split, self._fan_out("Lookup", items)):
                nbytes_in += len(rsp)
                out[positions] = np.frombuffer(
                    rsp, np.float32).reshape(owned.size, self.dim)
        else:
            for s, positions, owned in self._owner_split(flat):
                req = _pack_lookup_req(owned)
                rsp = self._call_shard(s, "Lookup", req)
                out[positions] = np.frombuffer(rsp, np.float32).reshape(
                    owned.size, self.dim)
                nbytes_out += len(req)
                nbytes_in += len(rsp)
        if rec:
            # Whole-batch latency across all owner shards (each per-shard
            # RPC is additionally recorded by Channel.call/call_async).
            obs.recorder("ps_client_lookup").record(
                (time.monotonic_ns() - t0) / 1e9)
            obs.counter("ps_client_lookup_keys").add(int(flat.size))
            obs.counter("ps_client_bytes_out").add(nbytes_out)
            obs.counter("ps_client_bytes_in").add(nbytes_in)
        return out.reshape(*np.shape(ids), self.dim)

    def apply_gradients(self, ids: np.ndarray, grads: np.ndarray) -> None:
        rec = obs.enabled()
        if rec:
            t0 = time.monotonic_ns()
        flat = np.asarray(ids, np.int32).reshape(-1)
        g = np.asarray(grads, np.float32).reshape(flat.size, self.dim)
        nbytes_out = 0
        if self.parallel:
            items = []
            for s, positions, owned in self._owner_split(flat):
                req = _pack_apply_req(owned, g[positions])
                nbytes_out += len(req)
                items.append((s, req))
            self._fan_out("ApplyGrad", items)
        else:
            for s, positions, owned in self._owner_split(flat):
                req = _pack_apply_req(owned, g[positions])
                self._call_shard(s, "ApplyGrad", req)
                nbytes_out += len(req)
        if rec:
            obs.recorder("ps_client_apply").record(
                (time.monotonic_ns() - t0) / 1e9)
            obs.counter("ps_client_apply_keys").add(int(flat.size))
            obs.counter("ps_client_bytes_out").add(nbytes_out)

    # -- streaming gradient push (the write-path mirror of the native
    # -- read path: framed deltas over one ordered flow-controlled
    # -- stream per owner shard, feeding the server combiner directly)

    def _push_stream(self, s: int) -> "rpc.Stream":
        st = self._push_streams.get(s)
        if st is None:
            st = self.channels[s].stream(
                "Ps", "StreamApply",
                max_buf_size=self.push_window_bytes)
            self._push_streams[s] = st
        return st

    def _push_frame(self, s: int, frame) -> None:
        """Write one framed delta to shard ``s``'s push stream,
        RECONNECTING under the embedding's retry policy on error: the
        broken stream is aborted, a fresh one is created (the setup RPC
        pays the shard's real state — timeouts included), and THIS frame
        is replayed on it.  A frame whose write was reported failed may
        still have reached the server before the break, so the streamed
        push is at-least-once across reconnects — exactly-once holds on
        a fault-free stream (ordered, flow-controlled, no retransmits)."""
        attempt = 0
        while True:
            try:
                self._push_stream(s).write(frame)
                return
            except rpc.RpcError as e:
                st = self._push_streams.pop(s, None)
                if st is not None:
                    st.abort()
                policy = self.retry
                # Stream breakage (EPIPE/EINVAL/EFAILEDSOCKET) means
                # reconnect regardless of the unary retriable set; the
                # policy still owns the ATTEMPT budget and backoff.
                reconnectable = e.code in (32, 22, 1009) or \
                    (policy is not None and
                     e.code in policy.retriable)
                if policy is None or not reconnectable or \
                        not attempt + 1 < policy.max_attempts:
                    raise
                if obs.enabled():
                    obs.counter("ps_stream_reconnects").add(1)
                resilience.sleep_ms(policy.backoff.delay_ms(attempt))
                attempt += 1

    def push_gradients(self, ids: np.ndarray, grads: np.ndarray) -> None:
        """Streaming gradient push: ships this batch's per-owner-shard
        deltas as ONE framed message per shard over a persistent
        ordered stream (opened lazily, kept across batches) — no unary
        dispatch/response per apply, and a shard whose combiner falls
        behind back-pressures THIS call through the stream's
        flow-control window (``push_window_bytes``;
        ``stream_stall_ms`` counts the stalls).  Fire-and-forget:
        application is guaranteed only after :meth:`flush_gradients`.
        Requires shards serving ``StreamApply``
        (``PsShardServer(stream=True)``); the unary
        :meth:`apply_gradients` remains the synchronous/fallback path."""
        rec = obs.enabled()
        if rec:
            t0 = time.monotonic_ns()
        flat = np.asarray(ids, np.int32).reshape(-1)
        g = np.asarray(grads, np.float32).reshape(flat.size, self.dim)
        nbytes_out = 0
        for s, positions, owned in self._owner_split(flat):
            frame = _pack_apply_req(owned, g[positions])
            nbytes_out += len(frame)
            self._push_frame(s, frame)
        if rec:
            obs.recorder("ps_client_push").record(
                (time.monotonic_ns() - t0) / 1e9)
            obs.counter("ps_client_push_keys").add(int(flat.size))
            obs.counter("ps_client_bytes_out").add(nbytes_out)

    def flush_gradients(self) -> None:
        """Closes every push stream and waits until each shard has
        consumed AND applied everything pushed so far (the server
        flushes its combiner before answering the close).  The next
        :meth:`push_gradients` opens fresh streams.  Raises
        :class:`rpc.RpcError` (ERPCTIMEDOUT) if a shard fails to drain
        within the embedding's timeout."""
        streams, self._push_streams = self._push_streams, {}
        for st in streams.values():
            st.close()
        deadline_s = max(1.0, self.timeout_ms / 1000.0)
        for s, st in streams.items():
            if not st.join(timeout_s=deadline_s):
                st.abort()
                raise rpc.RpcError(
                    1008, f"shard {s} ({self.addresses[s]}) did not drain "
                          f"its push stream within {deadline_s:.1f}s")

    def close(self):
        if self._prober is not None:
            self._prober.stop()
            self._prober = None
        for st in self._push_streams.values():
            # Abrupt: close() is teardown, not a flush barrier — callers
            # wanting the guarantee use flush_gradients() first.
            st.abort()
        self._push_streams.clear()
        for c in self.channels:
            c.close()
