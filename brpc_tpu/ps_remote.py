"""Remote parameter-server tier: embedding shards served over the native
RPC fabric, driven from JAX training loops.

This is the DCN tier of the BASELINE #5 workload ("param-server serving
embedding shards, allreduce grads"): each shard is a native Server
(cpp/rpc) holding rows [i*rows_per, (i+1)*rows_per); the client routes ids
to owners (the PartitionChannel "i/N" contract, cpp/cluster/
partition_channel.*) and runs Lookup / ApplyGrad calls. The intra-pod tier
— where the table fits in pod HBM — is brpc_tpu.ps (compiled collectives).

Wire format (little-endian): Lookup req = int32 count ++ int32 ids;
rsp = float32 rows [count, dim]. ApplyGrad req = int32 count ++ int32 ids
++ float32 grads [count, dim]; rsp = empty.
"""

from __future__ import annotations

import struct
import time
from typing import List, Sequence

import numpy as np

from brpc_tpu import obs, rpc
from brpc_tpu.analysis.race import checked_lock


def _record_ps_server(shard_index: int, method: str, count: int,
                      req_len: int, rsp_len: int, t0: int) -> None:
    """PS-side counters: keys/s, bytes in/out, per-shard handler latency
    (the ``add_service`` trampoline separately records the full RPC
    latency; this recorder isolates the table work)."""
    obs.recorder(f"ps_server_shard{shard_index}_{method}").record(
        (time.monotonic_ns() - t0) / 1e9)
    obs.counter("ps_server_keys").add(count)
    obs.counter("ps_server_bytes_in").add(req_len)
    obs.counter("ps_server_bytes_out").add(rsp_len)


class PsShardServer:
    """One embedding shard behind a native RPC server."""

    def __init__(self, vocab: int, dim: int, shard_index: int,
                 num_shards: int, lr: float = 0.1, seed: int = 0):
        if vocab % num_shards:
            raise ValueError("num_shards must divide vocab")
        self.shard_index = shard_index
        self.rows_per = vocab // num_shards
        self.base = shard_index * self.rows_per
        self.dim = dim
        self.lr = lr
        rng = np.random.default_rng(seed + shard_index)
        self.table = (rng.standard_normal((self.rows_per, dim)) * 0.02
                      ).astype(np.float32)
        # Handlers run concurrently on fiber workers (the trampoline
        # releases the GIL, and numpy releases it again for big ops): a
        # Lookup gather racing an ApplyGrad scatter-sub on overlapping
        # rows reads torn updates — serialize table access.
        self._mu = checked_lock("ps.shard")
        self.server = rpc.Server()
        self.server.add_service("Ps", self._handle)
        self.port = self.server.start("127.0.0.1:0")

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    def _handle(self, method: str, payload: bytes) -> bytes:
        if not obs.enabled():
            return self._serve(method, payload)
        t0 = time.monotonic_ns()
        rsp = self._serve(method, payload)
        (count,) = struct.unpack_from("<i", payload, 0)
        _record_ps_server(self.shard_index, method, count, len(payload),
                          len(rsp), t0)
        return rsp

    def _serve(self, method: str, payload: bytes) -> bytes:
        (count,) = struct.unpack_from("<i", payload, 0)
        ids = np.frombuffer(payload, np.int32, count, 4) - self.base
        if ids.size and (ids.min() < 0 or ids.max() >= self.rows_per):
            # Out-of-range ids would wrap to wrong rows via negative indexing.
            raise ValueError(
                f"ids outside shard [{self.base}, "
                f"{self.base + self.rows_per}) for shard base {self.base}"
            )
        if method == "Lookup":
            with self._mu:
                return self.table[ids].tobytes()
        if method == "ApplyGrad":
            grads = np.frombuffer(payload, np.float32,
                                  count * self.dim, 4 + 4 * count)
            with self._mu:
                np.subtract.at(self.table, ids,
                               self.lr * grads.reshape(count, self.dim))
            return b""
        raise ValueError(f"unknown method {method}")

    def close(self):
        self.server.close()


class DevicePsShardServer:
    """Embedding shard whose table is RESIDENT IN DEVICE HBM.

    The CPU variant above holds its table in host numpy; this one keeps it
    behind a native device-buffer handle (the RDMA-lkey analog,
    cpp/device/pjrt_device.h) and serves Lookup/ApplyGrad as compiled
    gather / scatter-sub launches (cpp/device/pjrt_executable.cc). Request
    ids and gradients DMA host->HBM through the registered block pool;
    looked-up rows DMA back into pooled blocks. No JAX anywhere in the
    serving path — this is the reference's "transport swap is invisible
    above Socket" contract with PJRT as the transport
    (docs/en/rdma.md:34 analog).
    """

    def __init__(self, vocab: int, dim: int, shard_index: int,
                 num_shards: int, lr: float = 0.1, seed: int = 0,
                 device_client: "rpc.DeviceClient | None" = None,
                 device_index: int = 0):
        if vocab % num_shards:
            raise ValueError("num_shards must divide vocab")
        self.shard_index = shard_index
        self.rows_per = vocab // num_shards
        self.base = shard_index * self.rows_per
        self.dim = dim
        self.lr = lr
        self._owns_dev = device_client is None
        self.dev = device_client or rpc.DeviceClient()
        self.device_index = device_index
        rng = np.random.default_rng(seed + shard_index)
        table = (rng.standard_normal((self.rows_per, dim)) * 0.02
                 ).astype(np.float32)
        # The table lives on-device from here on; the handle is the table.
        self.table_h = self.dev.stage(table, device_index)
        # Resident lr scalar: scatter_sub's 4th operand (stays in HBM).
        self.lr_h = self.dev.stage(np.array(lr, np.float32), device_index)
        self._gather = {}   # bucket size -> compiled gather executable
        self._scatter = {}  # bucket size -> compiled scatter-sub executable
        # Handlers run concurrently on fiber workers (ctypes releases the
        # GIL across device calls): the read-execute-swap on table_h must
        # be serialized or a concurrent ApplyGrad uses a released handle /
        # drops an update.  (BRPC_TPU_RACECHECK=1 will flag this lock as
        # held across blocking brt_* calls — deliberate: per-shard
        # serialization IS the consistency model; splitting the swap into
        # a handle-generation scheme is a ROADMAP open item.)
        self._mu = checked_lock("ps.device_shard")
        self.server = rpc.Server()
        self.server.add_service("Ps", self._handle)
        self.port = self.server.start("127.0.0.1:0")

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    @property
    def table(self) -> np.ndarray:
        """Host snapshot (DMAs the resident table down; test/debug use)."""
        with self._mu:  # table_h may be mid-swap in a concurrent ApplyGrad
            raw = self.dev.fetch(self.table_h)
        return np.frombuffer(raw, np.float32).reshape(self.rows_per,
                                                      self.dim).copy()

    def _gather_exe(self, k: int):
        exe = self._gather.get(k)
        if exe is None:
            mlir = self.dev.mlir("gather_rows", self.rows_per, self.dim, k)
            exe = self._gather[k] = self.dev.compile(mlir)
        return exe

    def _scatter_exe(self, k: int):
        exe = self._scatter.get(k)
        if exe is None:
            mlir = self.dev.mlir("scatter_sub", self.rows_per, self.dim, k)
            exe = self._scatter[k] = self.dev.compile(mlir)
        return exe

    @staticmethod
    def _bucket(count: int) -> int:
        """Round the batch size up to a power of two so the executable
        cache stays log-bounded instead of compiling per distinct count
        (padding: extra ids hit row 0 with zero gradients — a no-op)."""
        b = 1
        while b < count:
            b *= 2
        return b

    def _handle(self, method: str, payload: bytes) -> bytes:
        if not obs.enabled():
            return self._serve(method, payload)
        t0 = time.monotonic_ns()
        rsp = self._serve(method, payload)
        (count,) = struct.unpack_from("<i", payload, 0)
        _record_ps_server(self.shard_index, method, count, len(payload),
                          len(rsp), t0)
        return rsp

    def _serve(self, method: str, payload: bytes) -> bytes:
        (count,) = struct.unpack_from("<i", payload, 0)
        ids = np.frombuffer(payload, np.int32, count, 4) - self.base
        if ids.size and (ids.min() < 0 or ids.max() >= self.rows_per):
            raise ValueError(
                f"ids outside shard [{self.base}, "
                f"{self.base + self.rows_per}) for shard base {self.base}"
            )
        bucket = self._bucket(count)
        padded_ids = np.zeros(bucket, np.int32)
        padded_ids[:count] = ids
        with self._mu:
            ids_h = self.dev.stage(padded_ids, self.device_index)
            try:
                if method == "Lookup":
                    outs = self._gather_exe(bucket).execute(
                        [self.table_h, ids_h])
                    rows_h = outs[0][0]
                    try:
                        raw = self.dev.fetch(rows_h)
                    finally:
                        self.dev.release(rows_h)
                    return raw[:count * self.dim * 4]
                if method == "ApplyGrad":
                    grads = np.zeros((bucket, self.dim), np.float32)
                    grads[:count] = np.frombuffer(
                        payload, np.float32, count * self.dim,
                        4 + 4 * count).reshape(count, self.dim)
                    g_h = self.dev.stage(grads, self.device_index)
                    try:
                        # scatter_sub scales by the resident lr scalar
                        # on-chip: table[ids] -= lr * grads.
                        outs = self._scatter_exe(bucket).execute(
                            [self.table_h, ids_h, g_h, self.lr_h])
                    finally:
                        self.dev.release(g_h)
                    # The update is functional on-device: the output buffer
                    # IS the new resident table; the old one retires.
                    new_table = outs[0][0]
                    self.dev.release(self.table_h)
                    self.table_h = new_table
                    return b""
                raise ValueError(f"unknown method {method}")
            finally:
                self.dev.release(ids_h)

    def close(self):
        self.server.close()
        for exe in list(self._gather.values()) + list(
                self._scatter.values()):
            exe.close()
        self.dev.release(self.table_h)
        self.dev.release(self.lr_h)
        if self._owns_dev:
            self.dev.close()


class RemoteEmbedding:
    """Client view of a sharded remote table (owner-routed access)."""

    @classmethod
    def from_registry(cls, registry_addr: str, cluster: str, vocab: int,
                      dim: int, timeout_ms: int = 2000,
                      wait_ms: int = 5000) -> "RemoteEmbedding":
        """Resolves the shard list from the native naming registry
        (brpc_tpu.naming): shards register with tag "<shard>/<num>", and
        the watch blocks until a CONSISTENT full set is present (all
        shards 0..num-1 with one num). Service discovery for the PS tier
        — no static address list."""
        from brpc_tpu.naming import NamingClient
        reg = NamingClient(registry_addr)
        import time
        deadline = time.monotonic() + wait_ms / 1000.0
        version = 0
        groups: dict = {}
        while True:
            nodes, version = reg.watch(cluster, known_version=version,
                                       wait_ms=1000)
            # Group by the tag's "/num" so a stale entry from an old
            # sharding cannot block a complete consistent new set.
            groups = {}
            for n in nodes:
                tag = n.get("tag", "")
                if "/" not in tag:
                    continue
                s_str, num_str = tag.split("/", 1)
                try:
                    sh, nm = int(s_str), int(num_str)
                except ValueError:
                    continue
                shard_map = groups.setdefault(nm, {})
                # Duplicate index within one sharding: a restarted shard's
                # fresh registration supersedes a TTL-lingering stale one;
                # the registry lists entries in registration order, so the
                # LAST occurrence is the newest.
                shard_map[sh] = n["addr"]
            for num, shard_map in sorted(groups.items(), reverse=True):
                if num > 0 and all(i in shard_map for i in range(num))                         and len(shard_map) == num:
                    addrs = [shard_map[i] for i in range(num)]
                    reg.close()
                    return cls(addrs, vocab, dim, timeout_ms=timeout_ms)
            if time.monotonic() > deadline:
                reg.close()
                raise TimeoutError(
                    f"cluster '{cluster}' has no complete sharding: "
                    f"{ {nm: sorted(m) for nm, m in groups.items()} }")

    def __init__(self, addresses: Sequence[str], vocab: int, dim: int,
                 timeout_ms: int = 2000):
        self.vocab = vocab
        self.dim = dim
        self.n = len(addresses)
        self.rows_per = vocab // self.n
        self.channels: List[rpc.Channel] = [
            rpc.Channel(a, timeout_ms=timeout_ms) for a in addresses
        ]

    def _owner_split(self, flat_ids: np.ndarray):
        if flat_ids.size and (flat_ids.min() < 0
                              or flat_ids.max() >= self.vocab):
            # An out-of-range id matches no shard: lookup() would otherwise
            # return uninitialized rows for it.
            raise ValueError(
                f"ids must be in [0, {self.vocab}); got "
                f"[{flat_ids.min()}, {flat_ids.max()}]"
            )
        owners = flat_ids // self.rows_per
        for s in range(self.n):
            mask = owners == s
            if mask.any():
                yield s, np.nonzero(mask)[0], flat_ids[mask]

    def lookup(self, ids: np.ndarray) -> np.ndarray:
        rec = obs.enabled()
        if rec:
            t0 = time.monotonic_ns()
        flat = np.asarray(ids, np.int32).reshape(-1)
        out = np.empty((flat.size, self.dim), np.float32)
        nbytes_in = 0
        nbytes_out = 0
        for s, positions, owned in self._owner_split(flat):
            req = struct.pack("<i", owned.size) + owned.tobytes()
            rsp = self.channels[s].call("Ps", "Lookup", req)
            out[positions] = np.frombuffer(rsp, np.float32).reshape(
                owned.size, self.dim)
            nbytes_out += len(req)
            nbytes_in += len(rsp)
        if rec:
            # Whole-batch latency across all owner shards (each per-shard
            # RPC is additionally recorded by Channel.call).
            obs.recorder("ps_client_lookup").record(
                (time.monotonic_ns() - t0) / 1e9)
            obs.counter("ps_client_lookup_keys").add(int(flat.size))
            obs.counter("ps_client_bytes_out").add(nbytes_out)
            obs.counter("ps_client_bytes_in").add(nbytes_in)
        return out.reshape(*np.shape(ids), self.dim)

    def apply_gradients(self, ids: np.ndarray, grads: np.ndarray) -> None:
        rec = obs.enabled()
        if rec:
            t0 = time.monotonic_ns()
        flat = np.asarray(ids, np.int32).reshape(-1)
        g = np.asarray(grads, np.float32).reshape(flat.size, self.dim)
        nbytes_out = 0
        for s, positions, owned in self._owner_split(flat):
            req = (struct.pack("<i", owned.size) + owned.tobytes() +
                   g[positions].tobytes())
            self.channels[s].call("Ps", "ApplyGrad", req)
            nbytes_out += len(req)
        if rec:
            obs.recorder("ps_client_apply").record(
                (time.monotonic_ns() - t0) / 1e9)
            obs.counter("ps_client_apply_keys").add(int(flat.size))
            obs.counter("ps_client_bytes_out").add(nbytes_out)

    def close(self):
        for c in self.channels:
            c.close()
