"""Remote parameter-server tier: embedding shards served over the native
RPC fabric, driven from JAX training loops.

This is the DCN tier of the BASELINE #5 workload ("param-server serving
embedding shards, allreduce grads"): each shard is a native Server
(cpp/rpc) holding rows [i*rows_per, (i+1)*rows_per); the client routes ids
to owners (the PartitionChannel "i/N" contract, cpp/cluster/
partition_channel.*) and runs Lookup / ApplyGrad calls. The intra-pod tier
— where the table fits in pod HBM — is brpc_tpu.ps (compiled collectives).

Wire format (little-endian): Lookup req = int32 count ++ int32 ids;
rsp = float32 rows [count, dim]. ApplyGrad req = int32 count ++ int32 ids
++ float32 grads [count, dim]; rsp = empty.
"""

from __future__ import annotations

import struct
from typing import List, Sequence

import numpy as np

from brpc_tpu import rpc


class PsShardServer:
    """One embedding shard behind a native RPC server."""

    def __init__(self, vocab: int, dim: int, shard_index: int,
                 num_shards: int, lr: float = 0.1, seed: int = 0):
        if vocab % num_shards:
            raise ValueError("num_shards must divide vocab")
        self.rows_per = vocab // num_shards
        self.base = shard_index * self.rows_per
        self.dim = dim
        self.lr = lr
        rng = np.random.default_rng(seed + shard_index)
        self.table = (rng.standard_normal((self.rows_per, dim)) * 0.02
                      ).astype(np.float32)
        self.server = rpc.Server()
        self.server.add_service("Ps", self._handle)
        self.port = self.server.start("127.0.0.1:0")

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    def _handle(self, method: str, payload: bytes) -> bytes:
        (count,) = struct.unpack_from("<i", payload, 0)
        ids = np.frombuffer(payload, np.int32, count, 4) - self.base
        if ids.size and (ids.min() < 0 or ids.max() >= self.rows_per):
            # Out-of-range ids would wrap to wrong rows via negative indexing.
            raise ValueError(
                f"ids outside shard [{self.base}, "
                f"{self.base + self.rows_per}) for shard base {self.base}"
            )
        if method == "Lookup":
            return self.table[ids].tobytes()
        if method == "ApplyGrad":
            grads = np.frombuffer(payload, np.float32,
                                  count * self.dim, 4 + 4 * count)
            np.subtract.at(self.table, ids,
                           self.lr * grads.reshape(count, self.dim))
            return b""
        raise ValueError(f"unknown method {method}")

    def close(self):
        self.server.close()


class RemoteEmbedding:
    """Client view of a sharded remote table (owner-routed access)."""

    def __init__(self, addresses: Sequence[str], vocab: int, dim: int,
                 timeout_ms: int = 2000):
        self.vocab = vocab
        self.dim = dim
        self.n = len(addresses)
        self.rows_per = vocab // self.n
        self.channels: List[rpc.Channel] = [
            rpc.Channel(a, timeout_ms=timeout_ms) for a in addresses
        ]

    def _owner_split(self, flat_ids: np.ndarray):
        if flat_ids.size and (flat_ids.min() < 0
                              or flat_ids.max() >= self.vocab):
            # An out-of-range id matches no shard: lookup() would otherwise
            # return uninitialized rows for it.
            raise ValueError(
                f"ids must be in [0, {self.vocab}); got "
                f"[{flat_ids.min()}, {flat_ids.max()}]"
            )
        owners = flat_ids // self.rows_per
        for s in range(self.n):
            mask = owners == s
            if mask.any():
                yield s, np.nonzero(mask)[0], flat_ids[mask]

    def lookup(self, ids: np.ndarray) -> np.ndarray:
        flat = np.asarray(ids, np.int32).reshape(-1)
        out = np.empty((flat.size, self.dim), np.float32)
        for s, positions, owned in self._owner_split(flat):
            req = struct.pack("<i", owned.size) + owned.tobytes()
            rsp = self.channels[s].call("Ps", "Lookup", req)
            out[positions] = np.frombuffer(rsp, np.float32).reshape(
                owned.size, self.dim)
        return out.reshape(*np.shape(ids), self.dim)

    def apply_gradients(self, ids: np.ndarray, grads: np.ndarray) -> None:
        flat = np.asarray(ids, np.int32).reshape(-1)
        g = np.asarray(grads, np.float32).reshape(flat.size, self.dim)
        for s, positions, owned in self._owner_split(flat):
            req = (struct.pack("<i", owned.size) + owned.tobytes() +
                   g[positions].tobytes())
            self.channels[s].call("Ps", "ApplyGrad", req)

    def close(self):
        for c in self.channels:
            c.close()
