"""Durable fabric: per-shard incremental checkpoint/restore (ISSUE 16).

Quorum replication keeps a shard alive through node death, but nothing
survived a FULL fleet restart: every table lived only in process
memory.  The observation this module is built on is that the
replication stream is already a write-ahead log — every applied batch
leaves the primary as a ``replica_apply_body`` frame (writer dedup
windows ++ global-id apply_req), in apply order, under the table write
lock.  Teeing that exact framing to disk gives an incremental
checkpoint for free:

* **base snapshot** (``base-<gen>.snap``): a gen-stamped, crc-guarded
  image of the whole table plus the writer dedup windows at that
  generation (schema ``ckpt_snap``).  Written to a temp file and
  ``os.replace``'d, so a crash mid-write never damages the previous
  base.
* **delta log** (``delta-<gen>.log``, named for the base it extends):
  one ``ckpt_delta`` record per applied generation, containing the
  verbatim ``replica_apply_body`` bytes.  Log order IS apply order;
  the dedup windows ride along in each body, so writer-retry
  semantics survive a cold start too.
* **compaction marker** (``compact.marker``): an advisory
  ``ckpt_marker`` naming the newest base; stale after a crash
  mid-compaction and tolerated (restore trusts the scan, not the
  marker).

Restore scans for the newest VALID base (falling back past a torn or
bit-flipped one), then replays delta records in strict
``base_gen+1, +2, ...`` chain order, stopping cleanly at the first
torn, corrupt or out-of-chain record — the exact acked generation at
the moment of death is recovered, never a byte more or less.  The
server side (``PsShardServer.attach_checkpoint``) replays those bodies
through the SAME parse + ``np.subtract.at`` arithmetic as the live
apply path, so the zero-lost-acked-update ledger extends across the
cold start bit for bit.

The store also powers **snapshot-hydrated provisioning**: a new
replica (``hydrate_replica``) or split destination
(``hydrate_destination``) is seeded from the on-disk base, and the
live source then ships only the delta TAIL over the existing
ReplicaApply/MigrateApply streams (the hydrate-first modes in
``ps_remote._Replicator`` and ``reshard.MigrationShipper``) instead of
a wholesale Sync taxing a serving primary.
"""

from __future__ import annotations

import os
import struct
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from brpc_tpu import obs, rpc, wire
from brpc_tpu.analysis.race import checked_lock
from brpc_tpu.ps_remote import _pack_windows, _unpack_windows

__all__ = [
    "CheckpointStore", "RestorePoint", "hydrate_replica",
    "hydrate_destination",
]

#: on-disk format version stamped into every snapshot and marker.
#: v2 added the ``seeded`` flag to the snapshot header: a gen-0 base
#: written by a chain-seeded server is now distinguishable from a
#: fresh (never-synced) table, so first-boot backups can hydrate the
#: delta tail instead of always falling back to a wholesale Sync.
CKPT_VERSION = 2

_SNAP_HDR = struct.calcsize("<iiqqiiiqq")   # 52
_DELTA_HDR = struct.calcsize("<iqqi")       # 24
_MARKER_LEN = struct.calcsize("<iiq")       # 16


# ---------------------------------------------------------------------------
# on-disk frame parsers (schemas ckpt_snap / ckpt_delta / ckpt_marker)
# ---------------------------------------------------------------------------

def _pack_snapshot(epoch: int, gen: int, table: np.ndarray,
                   windows: Dict[str, int],
                   seeded: bool = False) -> bytes:
    """Pack one base snapshot file (schema ``ckpt_snap``).

    ``seeded`` records whether the writing server's table was
    established by the replication chain (primary, or a backup that
    received a wholesale Sync) — without it a gen-0 base is
    indistinguishable from a fresh random-init table."""
    table = np.ascontiguousarray(table, dtype=np.float32)
    rows, dim = table.shape
    body = table.tobytes() + _pack_windows(windows)
    return struct.pack("<iiqqiiiqq", wire.CKPT_SNAP_MAGIC, CKPT_VERSION,
                       epoch, gen, rows, dim, 1 if seeded else 0,
                       zlib.crc32(body), rows * dim) + body


def _unpack_snapshot(payload):
    """Parse one base snapshot file; returns
    ``(epoch, gen, table, windows, seeded)``.

    The crc covers EVERYTHING after the header (table ++ windows), so a
    bit flip anywhere in the body — or junk appended past the windows —
    rejects before any value is trusted."""
    magic, version, epoch, gen, rows, dim, seeded, crc, count = wire.read(
        "<iiqqiiiqq", payload, 0, "ckpt_snap.hdr")
    if magic != wire.CKPT_SNAP_MAGIC:
        raise wire.WireError("ckpt_snap: bad magic 0x%x" % (magic & 0xffffffff))
    if version != CKPT_VERSION:
        raise wire.WireError("ckpt_snap: unsupported version %d" % version)
    rows = wire.check_count(rows, wire.MAX_WIRE_COUNT, "ckpt_snap.rows")
    dim = wire.check_count(dim, wire.MAX_WIRE_COUNT, "ckpt_snap.dim")
    n = wire.check_count(count, max(0, (len(payload) - _SNAP_HDR) // 4),
                         "ckpt_snap.count")
    if n != rows * dim:
        raise wire.WireError("ckpt_snap: count %d != rows*dim %d"
                             % (n, rows * dim))
    body = bytes(payload[_SNAP_HDR:])
    if zlib.crc32(body) != crc:
        raise wire.WireError("ckpt_snap: checksum mismatch")
    wire.need(payload, _SNAP_HDR, n * 4, "ckpt_snap.table")
    table = np.frombuffer(payload, np.float32, n,
                          _SNAP_HDR).reshape(rows, dim).copy()
    windows, _ = _unpack_windows(payload, _SNAP_HDR + n * 4)
    return epoch, gen, table, windows, bool(seeded)


def _pack_delta(gen: int, body: bytes) -> bytes:
    """Pack one delta-log record (schema ``ckpt_delta``): a verbatim
    ``replica_apply_body`` under a crc-guarded length header."""
    body = bytes(body)
    return struct.pack("<iqqi", wire.CKPT_DELTA_MAGIC, gen,
                       zlib.crc32(body), len(body)) + body


def _unpack_delta(payload, offset: int = 0):
    """Parse one delta record at ``offset``; returns
    ``(gen, body, end_offset)``.  A torn tail (record cut mid-write)
    raises cleanly — the crc only covers the body, so a flipped ``gen``
    is instead caught by the restore chain check (the record falls out
    of the ``base+1, +2, ...`` sequence and replay stops there)."""
    magic, gen, crc, blen = wire.read("<iqqi", payload, offset,
                                      "ckpt_delta.hdr")
    if magic != wire.CKPT_DELTA_MAGIC:
        raise wire.WireError("ckpt_delta: bad magic 0x%x"
                             % (magic & 0xffffffff))
    off = offset + _DELTA_HDR
    blen = wire.check_count(blen, max(0, len(payload) - off),
                            "ckpt_delta.blen")
    wire.need(payload, off, blen, "ckpt_delta.body")
    body = bytes(payload[off:off + blen])
    if zlib.crc32(body) != crc:
        raise wire.WireError("ckpt_delta: checksum mismatch")
    return gen, body, off + blen


def _pack_marker(base_gen: int) -> bytes:
    """Pack the compaction marker file (schema ``ckpt_marker``)."""
    return struct.pack("<iiq", wire.CKPT_MARKER_MAGIC, CKPT_VERSION,
                       base_gen)


def _unpack_marker(payload) -> int:
    """Parse the compaction marker; returns the advertised base gen."""
    magic, version, base_gen = wire.read("<iiq", payload, 0,
                                         "ckpt_marker")
    if magic != wire.CKPT_MARKER_MAGIC:
        raise wire.WireError("ckpt_marker: bad magic 0x%x"
                             % (magic & 0xffffffff))
    if version != CKPT_VERSION:
        raise wire.WireError("ckpt_marker: unsupported version %d"
                             % version)
    return base_gen


# ---------------------------------------------------------------------------
# the per-shard store
# ---------------------------------------------------------------------------

@dataclass
class RestorePoint:
    """What :meth:`CheckpointStore.restore` recovered: the base image
    plus the chained delta tail, ending at the exact last durable
    generation.  ``deltas`` are verbatim ``replica_apply_body`` bytes —
    the server replays them through its live apply arithmetic."""
    epoch: int
    base_gen: int
    gen: int                       # base_gen + len(deltas)
    table: np.ndarray
    windows: Dict[str, int]
    deltas: List[Tuple[int, bytes]] = field(default_factory=list)
    seeded: bool = False           # base written by a chain-seeded server


class CheckpointStore:
    """One shard's durable checkpoint: base snapshot + delta log.

    Thread-safe; ``append_delta`` is designed to be called under the
    shard's table write lock (that is what makes log order == apply
    order), everything else from anywhere.  The store is deliberately
    arithmetic-free: it moves bytes, the server owns the math.

    ``fsync=False`` (the default) rides the OS page cache — that is
    durable across process death, which is the failure mode the bench
    kills with; power-loss durability costs ``fsync=True`` per record.
    """

    def __init__(self, root: str, *, fsync: bool = False,
                 compact_bytes: int = 16 << 20, keep_bases: int = 2):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.fsync = bool(fsync)
        self.compact_bytes = int(compact_bytes)
        self.keep_bases = max(1, int(keep_bases))
        self._mu = checked_lock("ps.ckpt")
        self._base_gen = -1          # no base yet: appends refused
        self._epoch = 0
        self._last_gen = -1
        self._seg_f = None           # open segment, None until a base lands
        self._tail: List[Tuple[int, bytes]] = []
        self._delta_bytes = 0

    # -- paths --------------------------------------------------------------

    def _base_paths(self):
        """``(gen, path)`` for every base file, newest first."""
        out = []
        for name in os.listdir(self.root):
            if name.startswith("base-") and name.endswith(".snap"):
                try:
                    g = int(name[5:-5])
                except ValueError:
                    continue
                out.append((g, os.path.join(self.root, name)))
        out.sort(reverse=True)
        return out

    def _seg_paths(self):
        """``(base_gen, path)`` for every delta segment, ascending —
        segment N holds gens ``N+1 .. next_base``, so an ascending scan
        chains contiguously from WHICHEVER base restore lands on."""
        out = []
        for name in os.listdir(self.root):
            if name.startswith("delta-") and name.endswith(".log"):
                try:
                    g = int(name[6:-4])
                except ValueError:
                    continue
                out.append((g, os.path.join(self.root, name)))
        out.sort()
        return out

    def _write_atomic(self, path: str, payload: bytes) -> None:
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            if self.fsync:
                os.fsync(f.fileno())
        os.replace(tmp, path)

    # -- write path ---------------------------------------------------------

    def save_snapshot(self, epoch: int, gen: int, table: np.ndarray,
                      windows: Dict[str, int], *,
                      seeded: bool = False) -> None:
        """Write a new base at ``gen``, open a fresh segment for its
        tail, and retire everything older than the ``keep_bases``
        newest bases (compaction: the previous tail is now folded into
        this base)."""
        payload = _pack_snapshot(epoch, gen, table, windows or {}, seeded)
        with self._mu:
            compacting = self._base_gen >= 0
            self._write_atomic(
                os.path.join(self.root, "base-%016d.snap" % gen), payload)
            if self._seg_f is not None:
                self._seg_f.close()
            self._seg_f = open(
                os.path.join(self.root, "delta-%016d.log" % gen), "wb")
            self._write_atomic(os.path.join(self.root, "compact.marker"),
                               _pack_marker(gen))
            bases = self._base_paths()
            kept = [g for g, _ in bases[:self.keep_bases]]
            oldest_kept = min(kept) if kept else gen
            for _, path in bases[self.keep_bases:]:
                try:
                    os.unlink(path)
                except OSError:
                    pass
            for g, path in self._seg_paths():
                if g < oldest_kept:
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
            self._base_gen = gen
            self._epoch = epoch
            self._last_gen = gen
            self._tail = []
            self._delta_bytes = 0
        if obs.enabled():
            obs.counter("ps_ckpt_snapshots").add(1)
            obs.counter("ps_ckpt_snapshot_bytes").add(len(payload))
            if compacting:
                obs.counter("ps_ckpt_compactions").add(1)

    def append_delta(self, gen: int, body: bytes,
                     epoch: Optional[int] = None) -> bool:
        """Tee one applied generation to the open segment.  Returns
        False when the record cannot extend the log — no base yet,
        ``gen`` is not the next link in the chain (a wholesale install
        jumped the generation), or ``epoch`` (when given) differs from
        the epoch the open base was written under (a promotion bumped
        the epoch WITHOUT an install: the generation chain continued,
        but a restore of the old base would resurrect the stale epoch
        and un-fence retired writers) — in each case the caller
        re-bases via :meth:`save_snapshot` instead."""
        body = bytes(body)
        with self._mu:
            if self._seg_f is None or self._base_gen < 0:
                return False
            if gen != self._last_gen + 1:
                return False
            if epoch is not None and epoch != self._epoch:
                return False
            rec = _pack_delta(gen, body)
            self._seg_f.write(rec)
            self._seg_f.flush()
            if self.fsync:
                os.fsync(self._seg_f.fileno())
            self._tail.append((gen, body))
            self._delta_bytes += len(rec)
            self._last_gen = gen
        if obs.enabled():
            obs.counter("ps_ckpt_deltas").add(1)
            obs.counter("ps_ckpt_delta_bytes").add(len(rec))
        return True

    def should_compact(self) -> bool:
        """True once the open tail outweighs ``compact_bytes`` — the
        caller folds it into a fresh base via :meth:`save_snapshot`."""
        with self._mu:
            return (self._base_gen >= 0
                    and self._delta_bytes >= self.compact_bytes)

    # -- read path ----------------------------------------------------------

    def tail_since(self, after_gen: int):
        """Delta bodies for gens ``> after_gen``, or None when
        ``after_gen`` predates the current base (the caller must fall
        back to a wholesale transfer)."""
        with self._mu:
            if self._base_gen < 0 or after_gen < self._base_gen:
                return None
            return [(g, b) for g, b in self._tail if g > after_gen]

    def load_base(self):
        """Newest VALID base as ``(epoch, gen, table, windows,
        seeded)``, or None.  Lock-free: base files are immutable once
        renamed into place, so provisioning reads race nothing."""
        for g, path in self._base_paths():
            try:
                with open(path, "rb") as f:
                    parsed = _unpack_snapshot(f.read())
            except (OSError, wire.WireError):
                continue
            if parsed[1] != g:
                continue            # filename lies about the content
            return parsed
        return None

    def restore(self) -> Optional[RestorePoint]:
        """Recover the exact durable state: newest valid base, then the
        delta chain replayed in ``base+1, +2, ...`` order across the
        retained segments, stopping at the first torn / corrupt /
        out-of-chain record.  Returns None when no usable base exists.

        Also resets the in-memory write state: the next
        :meth:`append_delta` returns False until a fresh
        :meth:`save_snapshot` re-anchors the log (a recovered tail is
        never appended to in place — it may be torn)."""
        with self._mu:
            if self._seg_f is not None:
                self._seg_f.close()
                self._seg_f = None
            self._base_gen = -1
            self._last_gen = -1
            self._tail = []
            self._delta_bytes = 0
            chosen = None
            for g, path in self._base_paths():
                try:
                    with open(path, "rb") as f:
                        chosen = _unpack_snapshot(f.read())
                except (OSError, wire.WireError):
                    continue
                if chosen[1] != g:
                    chosen = None
                    continue
                break
            if chosen is None:
                return None
            epoch, base_gen, table, windows, seeded = chosen
            records: List[Tuple[int, bytes]] = []
            for _, path in self._seg_paths():
                try:
                    with open(path, "rb") as f:
                        data = f.read()
                except OSError:
                    continue
                off = 0
                while off < len(data):
                    try:
                        gen, body, off = _unpack_delta(data, off)
                    except wire.WireError:
                        break       # torn tail: last complete record wins
                    records.append((gen, body))
            deltas: List[Tuple[int, bytes]] = []
            expect = base_gen + 1
            for gen, body in records:
                if gen < expect:
                    continue        # already folded into the base
                if gen > expect:
                    break           # chain gap: nothing past it is safe
                deltas.append((gen, body))
                expect += 1
            self._base_gen = base_gen
            self._epoch = epoch
            self._last_gen = base_gen + len(deltas)
            self._tail = list(deltas)
        if obs.enabled():
            obs.counter("ps_ckpt_restores").add(1)
            obs.counter("ps_ckpt_restore_deltas").add(len(deltas))
        return RestorePoint(epoch=epoch, base_gen=base_gen,
                            gen=base_gen + len(deltas), table=table,
                            windows=windows, deltas=deltas, seeded=seeded)

    # -- introspection ------------------------------------------------------

    @property
    def base_gen(self) -> int:
        with self._mu:
            return self._base_gen

    @property
    def last_gen(self) -> int:
        with self._mu:
            return self._last_gen

    def delta_bytes(self) -> int:
        with self._mu:
            return self._delta_bytes

    def close(self) -> None:
        with self._mu:
            if self._seg_f is not None:
                self._seg_f.close()
                self._seg_f = None


# ---------------------------------------------------------------------------
# snapshot-hydrated provisioning
# ---------------------------------------------------------------------------

def hydrate_replica(store: CheckpointStore, addr: str, *,
                    timeout_ms: int = 5000) -> int:
    """Seed a NEW backup replica from the checkpoint store instead of
    the live primary: ship the on-disk base over the normal Sync
    control frame.  The destination must already have replication
    configured (so it answers Sync as a backup); when the primary's
    replicator later connects, its hydrate-first mode finds the
    backup's generation inside the delta window and ships only the
    tail.  Returns the generation the replica was seeded at."""
    base = store.load_base()
    if base is None:
        raise ValueError("durable: no usable base snapshot to hydrate from")
    epoch, gen, table, windows, _seeded = base
    payload = (struct.pack("<qqq", epoch, gen, table.size)
               + np.ascontiguousarray(table, np.float32).tobytes()
               + _pack_windows(windows))
    ch = rpc.Channel(addr, timeout_ms=timeout_ms)
    try:
        ch.call("Ps", "Sync", payload, timeout_ms=timeout_ms)
    finally:
        ch.close()
    if obs.enabled():
        obs.counter("ps_replica_hydrate_seeds").add(1)
    return gen


def hydrate_destination(store: CheckpointStore, addr: str, scheme: int,
                        src_addr: str, src_base: int, row0: int,
                        rows: int, *, timeout_ms: int = 5000) -> int:
    """Seed a split/migration DESTINATION (an ``importing`` server)
    with its row range from the checkpoint store, over the normal
    MigrateSync control frame.  ``row0`` is GLOBAL; ``src_base`` is the
    source shard's first global row (the store itself is
    position-blind).  The destination records the source watermark, so
    the live source's MigrationShipper hydrate-first mode then ships
    only the delta tail.  Returns the seeded generation."""
    base = store.load_base()
    if base is None:
        raise ValueError("durable: no usable base snapshot to hydrate from")
    epoch, gen, table, windows, _seeded = base
    lo = row0 - src_base
    if lo < 0 or lo + rows > table.shape[0]:
        raise ValueError("durable: rows [%d, %d) outside snapshot range"
                         % (row0, row0 + rows))
    src = src_addr.encode()
    payload = (struct.pack("<qqqq", scheme, gen, row0, rows)
               + struct.pack("<i", len(src)) + src
               + np.ascontiguousarray(table[lo:lo + rows],
                                      np.float32).tobytes()
               + _pack_windows(windows))
    ch = rpc.Channel(addr, timeout_ms=timeout_ms)
    try:
        ch.call("Ps", "MigrateSync", payload, timeout_ms=timeout_ms)
    finally:
        ch.close()
    if obs.enabled():
        obs.counter("ps_migrate_hydrate_seeds").add(1)
    return gen
