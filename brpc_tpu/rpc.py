"""ctypes bindings over the native RPC core (cpp/ → libbrpc_tpu_c.so).

Gives Python the reference's user surface — Server/Channel/Controller
(src/brpc/server.h:347, channel.h:151) — backed by the C++ fiber scheduler,
wait-free socket transport and cluster layer. Payloads are bytes; structure
(JSON, msgpack, numpy buffers) is the caller's choice.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import threading
import time
import weakref
from typing import Callable, Optional

from brpc_tpu import fault, obs, resilience
from brpc_tpu.analysis import handles as _handles
from brpc_tpu.analysis import race as _race

_INT64_MIN = -(2 ** 63)  # "inherit the channel option" timeout sentinel

_HANDLER = ctypes.CFUNCTYPE(
    None, ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
    ctypes.c_size_t, ctypes.c_void_p
)

# brt_stream_handler: (user, stream_id, data, len, closed) — data frames
# arrive with closed=0, the final callback is (NULL, 0, 1).
_STREAM_HANDLER = ctypes.CFUNCTYPE(
    None, ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p,
    ctypes.c_size_t, ctypes.c_int
)

# brt_drop_hook: (user, service, method, port) -> nonzero to drop.
_DROP_HOOK = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
    ctypes.c_int
)

# brt_iobuf_release: (data, arg) — fired when the last native reference
# to a borrowed (append_pinned) block drops; arg is the pin-registry
# token.  ctypes auto-acquires the GIL, so the callback may fire from
# any fiber/socket thread.
_IOBUF_RELEASE = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_void_p)

_lib = None
_load_error: Optional[str] = None
# Serializes the first-touch cmake/ninja build + dlopen: two threads racing
# into _load() would otherwise both run the build.
_load_mu = _race.checked_lock("rpc.load")


class NativeCoreUnavailable(RuntimeError):
    """The native core (cpp/ → libbrpc_tpu_c.so) could not be built or
    loaded — usually a missing cmake/ninja toolchain, a failed build, or
    an unloadable .so.  Callers that can degrade (tests, pure-Python
    tiers) catch this; ``native_core_available()`` probes without
    raising."""


def _build_dir() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "cpp", "build")


def native_core_available() -> bool:
    """True when the native core is loadable (building it on first use
    if a toolchain is present). Never raises."""
    try:
        _load()
        return True
    except NativeCoreUnavailable:
        return False


def _load_inner():
    so = os.path.join(_build_dir(), "libbrpc_tpu_c.so")
    if not os.path.exists(so):
        build = _build_dir()
        os.makedirs(build, exist_ok=True)
        subprocess.run(["cmake", "-G", "Ninja",
                        "-DCMAKE_BUILD_TYPE=Release", ".."],
                       cwd=build, check=True, capture_output=True)
        subprocess.run(["ninja", "brpc_tpu_c"], cwd=build, check=True,
                       capture_output=True)
    return ctypes.CDLL(so)


def _load():
    global _lib
    if _lib is not None:
        return _lib
    with _load_mu:
        if _lib is None:
            _lib = _load_locked()
        return _lib


def _load_locked():
    global _load_error
    if _load_error is not None:
        # Don't retry a cmake/ninja run per call — the toolchain won't
        # appear mid-process.
        raise NativeCoreUnavailable(_load_error)
    try:
        lib = _load_inner()
    except FileNotFoundError as e:
        _load_error = (f"native build toolchain missing ({e}); install "
                       f"cmake+ninja or use a prebuilt "
                       f"{_build_dir()}/libbrpc_tpu_c.so")
        raise NativeCoreUnavailable(_load_error) from e
    except subprocess.CalledProcessError as e:
        tail = (e.stderr or b"").decode(errors="replace")[-2000:]
        _load_error = f"native build failed ({e.cmd}):\n{tail}"
        raise NativeCoreUnavailable(_load_error) from e
    except OSError as e:
        _load_error = f"native core failed to load: {e}"
        raise NativeCoreUnavailable(_load_error) from e
    # Every brt_* symbol declares BOTH argtypes and restype (matching
    # cpp/capi/c_api.h) — ctypes defaults an undeclared restype to c_int,
    # which truncates 64-bit pointers/handles; the `ctypes-contract` check
    # in brpc_tpu.analysis enforces this table stays complete.
    lib.brt_server_new.argtypes = []
    lib.brt_server_new.restype = ctypes.c_void_p
    lib.brt_server_add_service.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, _HANDLER, ctypes.c_void_p]
    lib.brt_server_add_service.restype = ctypes.c_int
    lib.brt_server_start.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.brt_server_start.restype = ctypes.c_int
    lib.brt_server_add_naming_registry.argtypes = [ctypes.c_void_p]
    lib.brt_server_add_naming_registry.restype = ctypes.c_int
    lib.brt_server_port.argtypes = [ctypes.c_void_p]
    lib.brt_server_port.restype = ctypes.c_int
    lib.brt_server_stop.argtypes = [ctypes.c_void_p]
    lib.brt_server_stop.restype = None
    lib.brt_server_set_concurrency_limiter.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.brt_server_set_concurrency_limiter.restype = ctypes.c_int
    lib.brt_server_max_concurrency.argtypes = [ctypes.c_void_p]
    lib.brt_server_max_concurrency.restype = ctypes.c_int
    lib.brt_server_destroy.argtypes = [ctypes.c_void_p]
    lib.brt_server_destroy.restype = None
    lib.brt_session_respond.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t, ctypes.c_int,
        ctypes.c_char_p]
    lib.brt_session_respond.restype = None
    lib.brt_channel_new.restype = ctypes.c_void_p
    lib.brt_channel_new.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int]
    lib.brt_channel_call.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_void_p,
        ctypes.c_size_t, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_size_t), ctypes.c_char_p, ctypes.c_size_t]
    lib.brt_channel_call.restype = ctypes.c_int
    lib.brt_channel_call_start.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_void_p,
        ctypes.c_size_t]
    lib.brt_channel_call_start.restype = ctypes.c_void_p
    lib.brt_channel_call_start_opts.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_void_p,
        ctypes.c_size_t, ctypes.c_int64]
    lib.brt_channel_call_start_opts.restype = ctypes.c_void_p
    lib.brt_call_join.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_size_t), ctypes.c_char_p, ctypes.c_size_t]
    lib.brt_call_join.restype = ctypes.c_int
    lib.brt_call_wait.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.brt_call_wait.restype = ctypes.c_int
    lib.brt_call_group_new.argtypes = []
    lib.brt_call_group_new.restype = ctypes.c_void_p
    lib.brt_call_group_add.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.brt_call_group_add.restype = ctypes.c_int
    lib.brt_call_group_wait.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.brt_call_group_wait.restype = ctypes.c_int
    lib.brt_call_group_wait_any.argtypes = [ctypes.c_void_p,
                                            ctypes.c_int64]
    lib.brt_call_group_wait_any.restype = ctypes.c_int
    lib.brt_call_group_completed.argtypes = [ctypes.c_void_p]
    lib.brt_call_group_completed.restype = ctypes.c_int
    lib.brt_call_group_destroy.argtypes = [ctypes.c_void_p]
    lib.brt_call_group_destroy.restype = None
    lib.brt_ps_shard_new.argtypes = [
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int, ctypes.c_int]
    lib.brt_ps_shard_new.restype = ctypes.c_void_p
    lib.brt_ps_shard_install.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint64]
    lib.brt_ps_shard_install.restype = ctypes.c_int
    lib.brt_ps_shard_generation.argtypes = [ctypes.c_void_p]
    lib.brt_ps_shard_generation.restype = ctypes.c_uint64
    lib.brt_ps_shard_native_lookups.argtypes = [ctypes.c_void_p]
    lib.brt_ps_shard_native_lookups.restype = ctypes.c_uint64
    lib.brt_ps_shard_lookup_stats.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64)]
    lib.brt_ps_shard_lookup_stats.restype = None
    lib.brt_server_add_ps_service.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p, _HANDLER,
        ctypes.c_void_p]
    lib.brt_server_add_ps_service.restype = ctypes.c_int
    lib.brt_ps_shard_destroy.argtypes = [ctypes.c_void_p]
    lib.brt_ps_shard_destroy.restype = None
    lib.brt_stream_create.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_void_p,
        ctypes.c_size_t, ctypes.c_int64, ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_size_t),
        ctypes.c_char_p, ctypes.c_size_t]
    lib.brt_stream_create.restype = ctypes.c_int
    lib.brt_stream_create_rx.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_void_p,
        ctypes.c_size_t, ctypes.c_int64, _STREAM_HANDLER, ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_size_t), ctypes.c_char_p, ctypes.c_size_t]
    lib.brt_stream_create_rx.restype = ctypes.c_int
    lib.brt_stream_accept.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, _STREAM_HANDLER, ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_uint64)]
    lib.brt_stream_accept.restype = ctypes.c_int
    lib.brt_stream_write.argtypes = [
        ctypes.c_uint64, ctypes.c_void_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_int64)]
    lib.brt_stream_write.restype = ctypes.c_int
    lib.brt_stream_close.argtypes = [ctypes.c_uint64]
    lib.brt_stream_close.restype = ctypes.c_int
    lib.brt_stream_join.argtypes = [ctypes.c_uint64, ctypes.c_int64]
    lib.brt_stream_join.restype = ctypes.c_int
    lib.brt_stream_abort.argtypes = [ctypes.c_uint64]
    lib.brt_stream_abort.restype = ctypes.c_int
    # zero-copy buffer currency (capi/iobuf_capi.cc + c_api.cc variants)
    lib.brt_iobuf_new.argtypes = []
    lib.brt_iobuf_new.restype = ctypes.c_void_p
    lib.brt_iobuf_destroy.argtypes = [ctypes.c_void_p]
    lib.brt_iobuf_destroy.restype = None
    lib.brt_iobuf_append.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]
    lib.brt_iobuf_append.restype = ctypes.c_int
    lib.brt_iobuf_appendv.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_size_t), ctypes.c_int]
    lib.brt_iobuf_appendv.restype = ctypes.c_int
    lib.brt_iobuf_append_user_data.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t, _IOBUF_RELEASE,
        ctypes.c_void_p]
    lib.brt_iobuf_append_user_data.restype = ctypes.c_int
    lib.brt_iobuf_append_iobuf.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.brt_iobuf_append_iobuf.restype = ctypes.c_int
    lib.brt_iobuf_size.argtypes = [ctypes.c_void_p]
    lib.brt_iobuf_size.restype = ctypes.c_int64
    lib.brt_iobuf_copy_out.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t, ctypes.c_size_t]
    lib.brt_iobuf_copy_out.restype = ctypes.c_int64
    lib.brt_iobuf_block_count.argtypes = [ctypes.c_void_p]
    lib.brt_iobuf_block_count.restype = ctypes.c_int
    lib.brt_iobuf_block_data.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.brt_iobuf_block_data.restype = ctypes.c_void_p
    lib.brt_iobuf_block_len.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.brt_iobuf_block_len.restype = ctypes.c_int64
    lib.brt_channel_call_iobuf.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int), ctypes.c_char_p, ctypes.c_size_t]
    lib.brt_channel_call_iobuf.restype = ctypes.c_void_p
    lib.brt_channel_call_start_iobuf.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_void_p,
        ctypes.c_int64]
    lib.brt_channel_call_start_iobuf.restype = ctypes.c_void_p
    lib.brt_call_join_iobuf.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int), ctypes.c_char_p,
        ctypes.c_size_t]
    lib.brt_call_join_iobuf.restype = ctypes.c_void_p
    lib.brt_session_respond_iobuf.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p]
    lib.brt_session_respond_iobuf.restype = None
    lib.brt_stream_writev.argtypes = [
        ctypes.c_uint64, ctypes.POINTER(ctypes.c_void_p), ctypes.c_int,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int64)]
    lib.brt_stream_writev.restype = ctypes.c_int
    lib.brt_set_drop_hook.argtypes = [_DROP_HOOK, ctypes.c_void_p]
    lib.brt_set_drop_hook.restype = None
    lib.brt_call_cancel.argtypes = [ctypes.c_void_p]
    lib.brt_call_cancel.restype = None
    lib.brt_call_destroy.argtypes = [ctypes.c_void_p]
    lib.brt_call_destroy.restype = None
    lib.brt_channel_destroy.argtypes = [ctypes.c_void_p]
    lib.brt_channel_destroy.restype = None
    lib.brt_free.argtypes = [ctypes.c_void_p]
    lib.brt_free.restype = None
    lib.brt_init.argtypes = [ctypes.c_int]
    lib.brt_init.restype = None
    lib.brt_event_new.argtypes = []
    lib.brt_event_new.restype = ctypes.c_void_p
    lib.brt_event_set.argtypes = [ctypes.c_void_p]
    lib.brt_event_set.restype = None
    lib.brt_event_wait.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.brt_event_wait.restype = ctypes.c_int
    lib.brt_event_destroy.argtypes = [ctypes.c_void_p]
    lib.brt_event_destroy.restype = None
    # device fabric (native PJRT staging + compiled execution)
    lib.brt_device_client_new.restype = ctypes.c_void_p
    lib.brt_device_client_new.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t]
    lib.brt_device_count.argtypes = [ctypes.c_void_p]
    lib.brt_device_count.restype = ctypes.c_int
    lib.brt_device_stage.restype = ctypes.c_uint64
    lib.brt_device_stage.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t, ctypes.c_int,
        ctypes.c_char_p, ctypes.c_size_t]
    lib.brt_device_stage_shaped.restype = ctypes.c_uint64
    lib.brt_device_stage_shaped.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t, ctypes.c_int,
        ctypes.c_int, ctypes.POINTER(ctypes.c_int64), ctypes.c_size_t,
        ctypes.c_char_p, ctypes.c_size_t]
    lib.brt_device_fetch.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_size_t), ctypes.c_char_p, ctypes.c_size_t]
    lib.brt_device_fetch.restype = ctypes.c_int
    lib.brt_device_release.argtypes = [ctypes.c_uint64]
    lib.brt_device_release.restype = ctypes.c_int
    lib.brt_device_client_destroy.argtypes = [ctypes.c_void_p]
    lib.brt_device_client_destroy.restype = None
    lib.brt_mlir_module.restype = ctypes.c_void_p
    lib.brt_mlir_module.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64]
    lib.brt_device_compile.restype = ctypes.c_void_p
    lib.brt_device_compile.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
        ctypes.c_size_t]
    lib.brt_device_executable_num_outputs.argtypes = [ctypes.c_void_p]
    lib.brt_device_executable_num_outputs.restype = ctypes.c_int
    lib.brt_device_execute.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64), ctypes.c_size_t,
        ctypes.c_size_t, ctypes.POINTER(ctypes.c_uint64), ctypes.c_size_t,
        ctypes.c_char_p, ctypes.c_size_t]
    lib.brt_device_execute.restype = ctypes.c_int
    lib.brt_device_executable_destroy.argtypes = [ctypes.c_void_p]
    lib.brt_device_executable_destroy.restype = None
    lib.brt_debug_handle_counts.argtypes = []
    lib.brt_debug_handle_counts.restype = ctypes.c_void_p
    lib.brt_debug_handle_count.argtypes = [ctypes.c_char_p]
    lib.brt_debug_handle_count.restype = ctypes.c_long
    lib.brt_debug_fail_connections.argtypes = [ctypes.c_char_p]
    lib.brt_debug_fail_connections.restype = ctypes.c_int
    lib.brt_init(0)
    if _handles.enabled():
        _install_handle_ledger(lib)
    return lib


# ---------------------------------------------------------------------------
# dynamic handle ledger (BRPC_TPU_HANDLECHECK=1)
# ---------------------------------------------------------------------------

# The owning brt_* constructor/destructor pairs, keyed the same way as
# the native ground-truth counters (cpp/capi/handle_ledger.cc) so
# debug_handle_counts() and the Python ledger compare directly.  Streams
# are tracked at the Python object layer instead (Channel.stream /
# the receiver registry): their ABI uses out-param ids, not returns.
_HANDLE_NEW = {
    "brt_server_new": "server",
    "brt_channel_new": "channel",
    "brt_channel_call_start": "call",
    "brt_channel_call_start_opts": "call",
    "brt_call_group_new": "call_group",
    "brt_ps_shard_new": "ps_shard",
    "brt_event_new": "event",
    "brt_device_client_new": "device_client",
    "brt_device_compile": "device_executable",
    "brt_iobuf_new": "iobuf",
    "brt_channel_call_iobuf": "iobuf",
    "brt_call_join_iobuf": "iobuf",
    "brt_channel_call_start_iobuf": "call",
}
_HANDLE_DESTROY = {
    "brt_server_destroy": "server",
    "brt_channel_destroy": "channel",
    "brt_call_destroy": "call",
    "brt_call_group_destroy": "call_group",
    "brt_ps_shard_destroy": "ps_shard",
    "brt_event_destroy": "event",
    "brt_device_client_destroy": "device_client",
    "brt_device_executable_destroy": "device_executable",
    "brt_iobuf_destroy": "iobuf",
}


class _LedgerFn:
    """Transparent wrapper over one bound ctypes function that feeds the
    handle ledger: constructors record their returned handle (with
    creation stack), destructors release the first argument.  The
    ``argtypes``/``restype`` surface delegates to the wrapped function so
    the C-ABI contract tests (and any later re-declaration) see through
    the wrapper."""

    __slots__ = ("_fn", "_kind", "_is_new")

    def __init__(self, fn, kind: str, is_new: bool):
        self._fn = fn
        self._kind = kind
        self._is_new = is_new

    def __call__(self, *args):
        if self._is_new:
            out = self._fn(*args)
            _handles.note_create(self._kind, out)
            return out
        _handles.note_destroy(self._kind, args[0])
        return self._fn(*args)

    @property
    def argtypes(self):
        return self._fn.argtypes

    @argtypes.setter
    def argtypes(self, value):
        self._fn.argtypes = value

    @property
    def restype(self):
        return self._fn.restype

    @restype.setter
    def restype(self, value):
        self._fn.restype = value


def _install_handle_ledger(lib) -> None:
    """Wraps every owning ``brt_*_new``/``_destroy`` pair so the dynamic
    ledger sees each native handle's birth and death.  Installed once, at
    load time, only under ``BRPC_TPU_HANDLECHECK`` — the unwrapped ABI
    carries zero overhead."""
    for name, kind in _HANDLE_NEW.items():
        setattr(lib, name, _LedgerFn(getattr(lib, name), kind, True))
    for name, kind in _HANDLE_DESTROY.items():
        setattr(lib, name, _LedgerFn(getattr(lib, name), kind, False))


def debug_handle_counts() -> dict:
    """Ground-truth live native-object counts per handle type, reported
    by the C++ side itself (``brt_debug_handle_counts``): the native
    cross-check for :mod:`brpc_tpu.analysis.handles` — the Python ledger
    knows creation stacks, this table knows the truth."""
    lib = _load()
    p = lib.brt_debug_handle_counts()
    if not p:
        return {}
    try:
        text = ctypes.string_at(p).decode()
    finally:
        lib.brt_free(p)
    out = {}
    for line in text.splitlines():
        name, _, count = line.partition(" ")
        if name:
            out[name] = int(count)
    return out


def debug_handle_count(kind: str) -> int:
    """Live native-object count for ONE handle kind (e.g. ``ps_shard``,
    ``server``) straight from the C++ atomics — the cheap point probe
    behind retirement proofs: after a resharding drain, the retired
    scheme's shards must return the ``ps_shard``/``server`` counts to
    their pre-scale-out baseline."""
    return int(_load().brt_debug_handle_count(kind.encode()))


def debug_fail_connections(addr: str) -> int:
    """Fails every live client connection to ``addr`` ("ip:port") —
    exactly what the peer observes when the process holding those
    sockets dies.  The abrupt-death lever for leak/teardown tests (the
    stream registry's socket-failure teardown fires, receivers see
    ``on_closed``).  Returns the number of sockets failed."""
    return _load().brt_debug_fail_connections(addr.encode())


class RpcError(RuntimeError):
    def __init__(self, code: int, text: str):
        super().__init__(f"rpc failed ({code}): {text}")
        self.code = code


def _req_ptr(request):
    """Request bytes for a native call: ``bytes`` pass straight through;
    writable buffers (``bytearray``/``memoryview``) are wrapped zero-copy
    — legal because every native call path copies the request before
    returning, so the caller may reuse the buffer immediately.  This is
    what lets the PS client frame each request into ONE pre-sized
    ``bytearray`` instead of concatenating intermediates."""
    if isinstance(request, bytes) or request is None:
        return request
    return (ctypes.c_char * len(request)).from_buffer(request)


# ---------------------------------------------------------------------------
# zero-copy buffer currency (brt_iobuf_* — capi/iobuf_capi.cc)
# ---------------------------------------------------------------------------

# Pin registry for borrowed blocks: append_pinned hands the native core a
# raw pointer into a Python buffer and parks the owning object here; the
# native release callback (last-ref drop — possibly on a socket thread,
# GIL auto-acquired) pops it.  The ledger of live pins is exact: a pinned
# buffer outlives every wire write that borrowed it, never longer.
_iobuf_pin_mu = threading.Lock()
_iobuf_pins: dict = {}
_iobuf_pin_seq = [0]


@_IOBUF_RELEASE
def _iobuf_release_cb(data, arg):
    with _iobuf_pin_mu:
        _iobuf_pins.pop(arg, None)


def debug_iobuf_pins() -> int:
    """Live borrowed-block pins (buffers the native core still holds a
    reference into).  Drops to zero once every in-flight write drained."""
    with _iobuf_pin_mu:
        return len(_iobuf_pins)


def _pin_buffer(data):
    """(address, nbytes, keepalive) of ``data``'s memory WITHOUT copying.
    Accepts bytes, writable buffers (bytearray/memoryview/numpy) and
    read-only numpy arrays; the keepalive object must stay referenced
    until the native side releases the block."""
    if isinstance(data, bytes):
        addr = ctypes.cast(ctypes.c_char_p(data), ctypes.c_void_p).value
        return addr, len(data), data
    if hasattr(data, "__array_interface__"):       # numpy, any writability
        ai = data.__array_interface__
        if ai.get("strides") is not None:
            raise ValueError("append_pinned needs a contiguous array")
        return ai["data"][0], data.nbytes, data
    mv = memoryview(data)
    if not mv.contiguous:
        raise ValueError("append_pinned needs a contiguous buffer")
    if mv.readonly:
        # ctypes can't from_buffer a read-only view; numpy can still
        # surface the address (the pin keeps the chain alive).
        import numpy as np
        arr = np.frombuffer(mv, np.uint8)
        return (arr.__array_interface__["data"][0], mv.nbytes,
                (data, mv, arr))
    c = (ctypes.c_char * mv.nbytes).from_buffer(mv)
    return ctypes.addressof(c), mv.nbytes, (data, mv, c)


class _IobufToken:
    """Keepalive anchor: every exported view holds a reference, and the
    native handle is destroyed by the token's finalizer once the LAST
    holder (wrapper or view) is gone — a borrowed view can therefore
    never dangle."""

    __slots__ = ("__weakref__",)


#: Crossover below which the zero-copy machinery COSTS more than the
#: copy it saves (native handle + pin-registry lifecycle vs a sub-page
#: memcpy): requests/responses carried as an :class:`IOBuf` under this
#: size are routed through the plain bytes twin automatically — the
#: wire bytes are identical — unless the handle was built with
#: ``force_iobuf=True``.  The PS tier keys its engagement floor off
#: this same constant (ps_remote._ZC_MIN_BYTES).
IOBUF_MIN_BYTES = 4096


class IOBuf:
    """A native refcounted buffer chain (``brt::IOBuf``) addressed from
    Python — the zero-copy currency of the RPC tier.

    Build requests as [small owned header ++ borrowed payload]:
    ``append()`` copies (use it for the few-byte framing headers),
    ``append_pinned()`` borrows the caller's buffer with NO copy — the
    buffer is pinned in a registry until the native core drops its last
    reference (i.e. after the socket write drained), so mutating it
    before then is a data race the caller owns.  Responses come back as
    an :class:`IOBuf` from ``Channel.call``/``PendingCall.join`` when the
    request went in as one; read them with ``as_memoryview()`` (zero-copy
    for single-block bodies) or ``tobytes()``.

    Lifetime: ``close()`` releases the handle — unless live views exist,
    in which case destruction defers to the last view's death (the
    borrow-not-dangle contract).  Abandoned handles are reclaimed by GC
    via the same finalizer, but the ledger check expects explicit
    ``close()``.
    """

    __slots__ = ("_lib", "_ptr", "_token", "_fin", "force_iobuf")

    def __init__(self, data=None, *, force_iobuf: bool = False):
        lib = _load()
        ptr = lib.brt_iobuf_new()
        if not ptr:
            raise MemoryError("brt_iobuf_new failed")
        self._lib = lib
        self._ptr = ptr
        self._token = _IobufToken()
        self._fin = weakref.finalize(self._token, lib.brt_iobuf_destroy,
                                     ptr)
        #: escape hatch for the sub-IOBUF_MIN_BYTES bytes-twin routing:
        #: True keeps this handle on the native iobuf path end to end
        #: no matter how small the payload is
        self.force_iobuf = bool(force_iobuf)
        if data:
            self.append(data)

    @classmethod
    def _adopt(cls, lib, ptr) -> "IOBuf":
        """Wraps a native handle we already own (response swaps)."""
        io = cls.__new__(cls)
        io._lib = lib
        io._ptr = ptr
        io._token = _IobufToken()
        io._fin = weakref.finalize(io._token, lib.brt_iobuf_destroy,
                                   ptr)
        io.force_iobuf = False
        return io

    def __len__(self) -> int:
        if self._ptr is None:
            return 0
        return int(self._lib.brt_iobuf_size(self._ptr))

    @property
    def size(self) -> int:
        return len(self)

    @property
    def block_count(self) -> int:
        if self._ptr is None:
            return 0
        return self._lib.brt_iobuf_block_count(self._ptr)

    def _require(self):
        if self._ptr is None:
            raise RuntimeError("IOBuf is closed")
        return self._ptr

    def append(self, data) -> None:
        """Copying append (the native side owns a copy) — right for the
        few-byte framing headers in front of a borrowed payload."""
        ptr = self._require()
        if not isinstance(data, (bytes, bytearray, memoryview)):
            data = bytes(data)
        n = len(data)
        if n == 0:
            return
        rc = self._lib.brt_iobuf_append(ptr, _req_ptr(data), n)
        if rc != 0:
            raise RpcError(rc, "iobuf append failed")

    def append_pinned(self, data) -> None:
        """Zero-copy append: the native chain BORROWS ``data``'s memory.
        ``data`` is pinned (kept alive and counted in
        :func:`debug_iobuf_pins`) until the core's last reference drops;
        the caller must not mutate it before then."""
        ptr = self._require()
        addr, n, keep = _pin_buffer(data)
        if n == 0:
            return
        with _iobuf_pin_mu:
            _iobuf_pin_seq[0] += 1
            token = _iobuf_pin_seq[0]
            _iobuf_pins[token] = keep
        rc = self._lib.brt_iobuf_append_user_data(
            ptr, addr, n, _iobuf_release_cb, token)
        if rc != 0:
            with _iobuf_pin_mu:
                _iobuf_pins.pop(token, None)
            raise RpcError(rc, "iobuf append_pinned failed")

    def append_iobuf(self, other: "IOBuf") -> None:
        """Shares ``other``'s blocks (refcount bump, no payload copy)."""
        ptr = self._require()
        rc = self._lib.brt_iobuf_append_iobuf(ptr, other._require())
        if rc != 0:
            raise RpcError(rc, "iobuf append_iobuf failed")

    def as_memoryview(self) -> memoryview:
        """The contents as a buffer suitable for ``np.frombuffer``.

        Single-block chains (bodies under the native 8KB block size, and
        swapped-in responses whose payload was one borrowed block) export
        a ZERO-COPY view over native memory: the view holds the handle's
        keepalive token, so it stays valid after ``close()`` — the
        handle's destruction defers to the view's death.  Multi-block
        chains gather once into fresh memory (still one copy fewer than
        the bytes path)."""
        ptr = self._require()
        nblocks = self._lib.brt_iobuf_block_count(ptr)
        if nblocks == 1:
            n = int(self._lib.brt_iobuf_block_len(ptr, 0))
            base = self._lib.brt_iobuf_block_data(ptr, 0)
            arr = (ctypes.c_char * n).from_address(base)
            # The view must pin the native handle: ctypes instances keep
            # arbitrary attributes, and memoryview(arr) keeps arr.
            arr._brt_keepalive = self._token
            return memoryview(arr)
        total = int(self._lib.brt_iobuf_size(ptr))
        out = bytearray(total)
        if total:
            got = self._lib.brt_iobuf_copy_out(
                ptr, (ctypes.c_char * total).from_buffer(out), total, 0)
            if got != total:
                raise RpcError(-1, f"iobuf gather {got} != {total}")
            if obs.enabled():
                obs.counter("rpc_bytes_copied").add(total)
        return memoryview(out)

    def tobytes(self) -> bytes:
        """Copy out the full contents (the compatibility exit)."""
        ptr = self._require()
        total = int(self._lib.brt_iobuf_size(ptr))
        out = bytearray(total)
        if total:
            self._lib.brt_iobuf_copy_out(
                ptr, (ctypes.c_char * total).from_buffer(out), total, 0)
            if obs.enabled():
                obs.counter("rpc_bytes_copied").add(total)
        return bytes(out)

    def close(self) -> None:
        """Release the handle.  With live ``as_memoryview()`` views the
        native buffer stays pinned and destruction happens when the last
        view dies; without views it is destroyed here, now."""
        if self._ptr is None:
            return
        ptr, self._ptr = self._ptr, None
        token, self._token = self._token, None
        # 2 = the local `token` + getrefcount's argument ref: no view
        # holds the anchor, so the handle can die synchronously.
        # Otherwise the finalizer owns destruction — it fires when the
        # last view drops the token.
        if sys.getrefcount(token) <= 2:
            self._fin.detach()
            self._lib.brt_iobuf_destroy(ptr)
        del token

    def __enter__(self) -> "IOBuf":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ---------------------------------------------------------------------------
# server-side stream receivers (one process-global dispatch trampoline)
# ---------------------------------------------------------------------------

# stream_id -> receiver (an object with on_data(bytes) / on_closed()).
# Server side: registered by Server.add_stream_handler's accept() before
# the response leaves (so no frame can beat the registration).  Client
# side (``Channel.stream(receiver=...)``): the native create returns the
# stream id only AFTER the setup RPC — a fast server can write frames
# that arrive BEFORE the Python registration, so unknown-sid frames are
# buffered (bounded) and drained through a two-phase handoff when the
# registration lands; ordering is preserved because the native exec
# fiber only appends while the handoff placeholder is present.  Entries
# are removed when the peer's CLOSE is delivered.
_stream_mu = _race.checked_lock("rpc.stream.receivers")
_stream_receivers: dict = {}
# sid -> [queued_bytes, frames]; a frame of None = the close sentinel
_stream_orphans: dict = {}
_STREAM_ORPHAN_SIDS = 64     # dropped-oldest bound on unclaimed sids
_STREAM_ORPHAN_BYTES = 1 << 20   # per-sid queued-bytes bound


class _PreRegistration:
    """Handoff placeholder: while present, the dispatch fiber APPENDS
    frames instead of delivering, and the registering thread drains in
    order before flipping the entry to the real receiver."""

    __slots__ = ("queued",)

    def __init__(self, queued):
        self.queued = queued   # list of frames; None element = close


def _deliver(receiver, item, stream_id: int) -> None:
    if item is None:
        _handles.note_destroy("stream_receiver", stream_id)
        try:
            receiver.on_closed()
        finally:
            # Complete the close handshake: the peer already closed,
            # closing our side fully retires the native stream (and
            # wakes the peer's join).
            _load().brt_stream_close(stream_id)
    else:
        receiver.on_data(item)


def _register_stream_receiver(stream_id: int, receiver) -> None:
    _handles.note_create("stream_receiver", stream_id)
    pre = None
    with _stream_mu:
        orphans = _stream_orphans.pop(stream_id, None)
        if orphans and orphans[1]:
            pre = _PreRegistration(orphans[1])
            _stream_receivers[stream_id] = pre
        else:
            _stream_receivers[stream_id] = receiver
    if pre is None:
        return
    # Drain-then-flip: pop one queued frame at a time (the exec fiber may
    # still be appending), deliver it on THIS thread, and atomically swap
    # in the receiver once the queue is empty.
    while True:
        with _stream_mu:
            if pre.queued:
                item = pre.queued.pop(0)
            else:
                if _stream_receivers.get(stream_id) is pre:
                    _stream_receivers[stream_id] = receiver
                return
        _deliver(receiver, item, stream_id)
        if item is None:
            with _stream_mu:
                _stream_receivers.pop(stream_id, None)
            return


@_STREAM_HANDLER
def _stream_dispatch(user, stream_id, data, length, closed):
    """Runs serialized per stream on the native ExecutionQueue consumer
    (same fiber→Python shape as the service trampoline).  A slow receiver
    back-pressures the writer through the consumed-bytes feedback — that
    is the design, not a bug.  Exceptions cannot reach a response (frames
    have none), so they are counted and swallowed."""
    try:
        payload = None
        if not closed:
            payload = ctypes.string_at(data, length) if length else b""
        evicted: list = []
        with _stream_mu:
            receiver = _stream_receivers.get(stream_id)
            if isinstance(receiver, _PreRegistration):
                receiver.queued.append(payload)
                return
            if receiver is None:
                # Not (yet) registered: buffer for a racing client-side
                # registration (Channel.stream(receiver=...)).  Unclaimed
                # sids are bounded two ways — count (drop the oldest sid)
                # and per-sid queued bytes (a firehose nobody claims is
                # garbage, not a registration race: the race window is
                # one Python call).  An evicted sid gets its native close
                # completed below so the peer's join isn't stranded.
                entry = _stream_orphans.setdefault(stream_id, [0, []])
                entry[0] += length if payload is not None else 0
                entry[1].append(payload)
                if entry[0] > _STREAM_ORPHAN_BYTES:
                    _stream_orphans.pop(stream_id, None)
                    evicted.append(stream_id)
                while len(_stream_orphans) > _STREAM_ORPHAN_SIDS:
                    sid = next(iter(_stream_orphans))
                    _stream_orphans.pop(sid)
                    evicted.append(sid)
            elif closed:
                _stream_receivers.pop(stream_id, None)
        if evicted:
            lib = _load()
            for sid in evicted:
                # Complete/abort the native half regardless of whether
                # the dropped queue held the close sentinel — this is
                # what retires the native stream for a sid no receiver
                # will ever claim.
                lib.brt_stream_close(sid)
                if obs.enabled():
                    obs.counter("stream_orphans_evicted").add(1)
            return
        if receiver is None:
            return
        if closed:
            _handles.note_destroy("stream_receiver", stream_id)
            try:
                receiver.on_closed()
            finally:
                _load().brt_stream_close(stream_id)
        else:
            receiver.on_data(payload)
    except Exception:  # noqa: BLE001 — no response channel for frames
        if obs.enabled():
            obs.counter("stream_handler_errors").add(1)


def _make_stream_accept(lib, session):
    """The ``accept`` callable handed to a stream-capable handler: binds
    the stream riding the in-flight request to ``receiver`` and registers
    it for dispatch.  Must run inside the handler, before the response
    leaves — which is guaranteed, because the trampoline responds only
    after the handler returns.  Returns the server half as a writable
    :class:`Stream` — the native stream layer is symmetric, so the
    handler (or its receiver) may WRITE frames back to the client
    (server→client direction: acks, progress, catch-up data); the client
    reads them by passing ``receiver=`` to :meth:`Channel.stream`."""

    def accept(receiver, max_buf_size: int = 0) -> "Stream":
        sid = ctypes.c_uint64()
        rc = lib.brt_stream_accept(session, max_buf_size, _stream_dispatch,
                                   None, ctypes.byref(sid))
        if rc != 0:
            raise RpcError(rc, "stream accept failed "
                               "(request carries no stream?)")
        # Register before the response can reach the client: no data
        # frame can arrive until the client learns the peer stream id
        # from the response meta.
        _register_stream_receiver(sid.value, receiver)
        if obs.enabled():
            obs.counter("stream_accepts").add(1)
        # track=False: the server half's lifecycle belongs to the close
        # handshake in _stream_dispatch (receiver registry is the ledger
        # entry); this wrapper is a write surface, not an owner.
        return Stream(lib, sid.value, b"", "", "", "peer", track=False)

    return accept


# ---------------------------------------------------------------------------
# native pre-dispatch drop hook (fault-injection tier)
# ---------------------------------------------------------------------------

# listen port -> "ip:port" of live servers, so the drop hook can hand the
# fault plan the same endpoint string its per-endpoint rules match on.
_servers_by_port: dict = {}
_drop_hook_ref = None  # pinned CFUNCTYPE while installed


def install_drop_hook() -> None:
    """Installs the native pre-dispatch drop hook (idempotent): every
    parsed request consults :func:`brpc_tpu.fault.server_drop_intercept`
    before dispatch, and a firing ``drop`` rule discards it silently —
    no response, so the CLIENT's real timeout path runs.  Called by
    ``fault.install`` when a plan carries server-side drop rules; raises
    :class:`NativeCoreUnavailable` without the native core."""
    global _drop_hook_ref
    if _drop_hook_ref is not None:
        return
    lib = _load()

    @_DROP_HOOK
    def hook(user, service, method, port):
        try:
            if not fault.active():
                return 0
            dropped = fault.server_drop_intercept(
                service.decode(errors="replace"),
                method.decode(errors="replace"),
                _servers_by_port.get(port))
            return 1 if dropped else 0
        except Exception:  # noqa: BLE001 — never fail the request path
            return 0

    _drop_hook_ref = hook  # pin before install: the native side keeps it
    lib.brt_set_drop_hook(hook, None)


def uninstall_drop_hook() -> None:
    """Removes the native drop hook (test isolation)."""
    global _drop_hook_ref
    if _drop_hook_ref is None:
        return
    _load().brt_set_drop_hook(ctypes.cast(None, _DROP_HOOK), None)
    _drop_hook_ref = None


#: overload-shed error codes -> the rpcz annotation that keeps shed
#: requests visible in traces instead of vanishing as generic errors
_SHED_TAGS = {2004: "shed=limiter", 2014: "shed=deadline"}


def _record_server_call(service: str, method: str, t0: int, wall: float,
                        req_len: int, rsp_len: int,
                        error: Optional[str],
                        error_code: int = 2001) -> None:
    end = time.monotonic_ns()
    obs.recorder(f"rpc_server_{service}_{method}").record((end - t0) / 1e9)
    obs.counter("rpc_server_in_bytes").add(req_len)
    obs.counter("rpc_server_out_bytes").add(rsp_len)
    if error is not None:
        obs.counter("rpc_server_errors").add(1)
    tag = _SHED_TAGS.get(error_code) if error is not None else None
    obs.record_span(obs.Span(
        service=service, method=method, side="server",
        request_bytes=req_len, response_bytes=rsp_len, start_ns=t0,
        end_ns=end, wall_time=wall,
        error_code=error_code if error else 0,
        error_text=error or "",
        annotations=[tag] if tag else []))


def _error_code_of(e: BaseException) -> int:
    """Server-side failure code: a handler raising :class:`RpcError`
    (fault injection, an overload rejection) keeps its code on the wire;
    anything else is EINTERNAL (2001)."""
    code = getattr(e, "code", None)
    return code if isinstance(code, int) and code != 0 else 2001


def _record_client_call(service: str, method: str, peer: str, t0: int,
                        wall: float, req_len: int, rsp_len: int,
                        error_code: int, error_text: str,
                        tag: Optional[str] = None) -> None:
    end = time.monotonic_ns()
    obs.recorder(f"rpc_client_{service}_{method}").record((end - t0) / 1e9)
    obs.counter("rpc_client_out_bytes").add(req_len)
    obs.counter("rpc_client_in_bytes").add(rsp_len)
    if error_code:
        obs.counter("rpc_client_errors").add(1)
    obs.record_span(obs.Span(
        service=service, method=method, side="client", peer=peer,
        request_bytes=req_len, response_bytes=rsp_len, start_ns=t0,
        end_ns=end, wall_time=wall, error_code=error_code,
        error_text=error_text,
        annotations=[tag] if tag else []))


class Server:
    """Native RPC server. Handlers: fn(method: str, request: bytes) -> bytes
    (raise to fail the call)."""

    def __init__(self):
        self._lib = _load()
        self._ptr = self._lib.brt_server_new()
        self._handlers = []  # keep CFUNCTYPE refs alive
        self._listen: Optional[str] = None  # set by start()
        # per-method overload control (brpc_tpu.limiter.ServerLimiter);
        # consulted by both trampolines on every dispatch
        self._limiter = None

    def set_concurrency_limiter(self, limiter) -> None:
        """Installs per-method overload control on the PYTHON
        trampolines: ``limiter`` is a
        :class:`brpc_tpu.limiter.ServerLimiter` (None clears).  A
        request its method gate refuses answers ``ELIMIT`` (2004)
        without touching the handler; admitted requests feed the
        gate's limiter with their outcome and handler latency.
        Live-switchable — gates are consulted per dispatch."""
        self._limiter = limiter

    def set_native_concurrency_limiter(self, name: str,
                                       max_concurrency: int = 0) -> None:
        """Installs the NATIVE server-wide concurrency limiter
        (``"auto"``, ``"constant"`` + ``max_concurrency``,
        ``"timeout[:us]"``, ``""`` = off — cpp/rpc/concurrency_limiter):
        enforced in the C++ dispatch path before ANY Python runs, so the
        zero-Python native Lookup path (``add_ps_service``) sheds too.
        Must be called before :meth:`start`."""
        rc = self._lib.brt_server_set_concurrency_limiter(
            self._ptr, name.encode(), max_concurrency)
        if rc != 0:
            raise RuntimeError(
                f"set_native_concurrency_limiter failed: {rc} "
                f"(server already started?)")

    @property
    def native_max_concurrency(self) -> int:
        """The native limiter's current ceiling (0 = off/unlimited) —
        the adaptive gauge for the native dispatch path."""
        return self._lib.brt_server_max_concurrency(self._ptr)

    def _sync_trampoline(self, name: str,
                         handler: Callable[[str, bytes], bytes], *,
                         pass_accept: bool = False):
        """Builds the fiber->Python trampoline shared by
        :meth:`add_service`, :meth:`add_ps_service` and
        :meth:`add_stream_handler` (the caller must pin the returned
        CFUNCTYPE on ``self._handlers``).  With ``pass_accept`` the
        handler is called as ``handler(method, request, accept)`` and may
        invoke ``accept(receiver, max_buf_size=0)`` once, BEFORE
        returning, to bind the stream riding this request."""
        lib = self._lib

        @_HANDLER
        def trampoline(user, method, req, req_len, session):
            rec = obs.enabled()
            # t0 is unconditional: the method gate's limiter needs the
            # handler latency whether or not obs is recording
            t0 = time.monotonic_ns()
            wall = time.time() if rec else 0.0
            m = b""
            mstr = ""
            out_len = 0
            err = None
            err_code = 0
            gate = None
            try:
                m = method
                mstr = m.decode()
                lim = self._limiter
                if lim is not None:
                    g = lim.gate(mstr)
                    if g is not None:
                        if not g.admit():
                            # per-method overload shed: answered before
                            # the handler (or even the request bytes)
                            # are touched — the MethodStatus::OnRequested
                            # contract
                            raise RpcError(
                                resilience.ELIMIT,
                                f"{name}.{mstr} shed: concurrency limit "
                                f"{g.max_concurrency} reached")
                        gate = g
                data = ctypes.string_at(req, req_len) if req_len else b""
                if rec and req_len:
                    obs.counter("rpc_bytes_copied").add(req_len)
                if fault.active():
                    fault.server_intercept(name, mstr, self._listen)
                if pass_accept:
                    out = handler(mstr, data,
                                  _make_stream_accept(lib, session))
                else:
                    out = handler(mstr, data)
                if out is None:
                    out = b""
                out_len = len(out)
            except Exception as e:  # noqa: BLE001
                err = str(e)
                err_code = _error_code_of(e)
            # Accounting BEFORE the response leaves: the moment the
            # client sees the reply it may read this server's vars —
            # a record landing after the respond races that read.
            try:
                if gate is not None:
                    gate.on_responded(
                        err_code, (time.monotonic_ns() - t0) // 1000)
                if rec:
                    _record_server_call(
                        name, mstr or m.decode(errors="replace"), t0,
                        wall, req_len, out_len, err,
                        err_code if err else 2001)
            finally:
                if err is None:
                    if isinstance(out, IOBuf) and not out.force_iobuf \
                            and out_len < IOBUF_MIN_BYTES:
                        # Sub-crossover response: the bytes twin is
                        # cheaper than the respond_iobuf handle dance
                        # (identical wire bytes).
                        data = out.tobytes()
                        out.close()
                        lib.brt_session_respond(session, data, out_len,
                                                0, None)
                    elif isinstance(out, IOBuf):
                        # The response SHARES the handler's blocks (no
                        # copy); the handle is not consumed — close it
                        # here, which defers actual destruction past the
                        # socket write via the block refcounts.
                        lib.brt_session_respond_iobuf(
                            session, out._require(), 0, None)
                        out.close()
                    else:
                        lib.brt_session_respond(session, out, out_len, 0,
                                                None)
                else:
                    lib.brt_session_respond(session, None, 0, err_code,
                                            err.encode())

        return trampoline

    def add_service(self, name: str,
                    handler: Callable[[str, bytes], bytes]) -> None:
        trampoline = self._sync_trampoline(name, handler)
        rc = self._lib.brt_server_add_service(self._ptr, name.encode(),
                                              trampoline, None)
        if rc != 0:
            raise RuntimeError(f"add_service failed: {rc}")
        self._handlers.append(trampoline)

    def add_stream_handler(self, name: str, handler) -> None:
        """Registers a service whose handler may ACCEPT streams:
        ``handler(method, request, accept) -> bytes``.  A method that
        wants the client's stream calls ``accept(receiver,
        max_buf_size=0)`` (at most once, before returning); ``receiver``
        then gets ``on_data(bytes)`` per frame and ``on_closed()`` once,
        serialized, after the client's graceful close — a slow receiver
        back-pressures the writer through the stream's consumed-bytes
        window.  Methods that ignore ``accept`` behave exactly like
        :meth:`add_service` handlers.  The server auto-closes its half of
        a stream after ``on_closed`` (completing the handshake the
        client's ``Stream.join`` waits on); a client that dies WITHOUT
        closing gets the same teardown — the socket-failure hook in the
        native stream registry delivers a synthetic close (ordered after
        queued data), so ``on_closed`` still fires and the receiver is
        freed, not leaked."""
        trampoline = self._sync_trampoline(name, handler, pass_accept=True)
        rc = self._lib.brt_server_add_service(self._ptr, name.encode(),
                                              trampoline, None)
        if rc != 0:
            raise RuntimeError(f"add_stream_handler failed: {rc}")
        self._handlers.append(trampoline)

    def add_ps_service(self, name: str, shard: "PsShard",
                       fallback: Callable[[str, bytes], bytes], *,
                       stream: bool = False) -> None:
        """Registers a PS service whose ``Lookup`` is served NATIVELY from
        ``shard`` — zero Python (no GIL, no ctypes trampoline, no request
        framing) in the read loop.  Every other method (``ApplyGrad``,
        lifecycle, fault injection) dispatches to ``fallback`` on the
        standard trampoline, so the Python tier keeps the write path.
        With ``stream=True`` the fallback is stream-capable and called as
        ``fallback(method, request, accept)`` (see
        :meth:`add_stream_handler`) — the streaming gradient push rides
        the same service as the native read path.  The shard must outlive
        this server (close the server first)."""
        trampoline = self._sync_trampoline(name, fallback,
                                           pass_accept=stream)
        rc = self._lib.brt_server_add_ps_service(
            self._ptr, name.encode(), shard._ptr, trampoline, None)
        if rc != 0:
            raise RuntimeError(f"add_ps_service failed: {rc}")
        self._handlers.append(trampoline)

    def add_async_service(self, name: str, handler) -> None:
        """handler(method: str, request: bytes, respond) — call
        ``respond(data: bytes)`` or ``respond(error=str)`` EXACTLY once,
        from any thread, any time (the fiber worker is released
        immediately — the "enqueue JAX work without blocking workers"
        shape: dispatch, return, respond from the completion callback)."""
        lib = self._lib

        @_HANDLER
        def trampoline(user, method, req, req_len, session):
            data = ctypes.string_at(req, req_len) if req_len else b""
            sess = ctypes.c_void_p(session)
            m = method.decode()
            rec = obs.enabled()
            t0 = time.monotonic_ns()  # gate latency needs it without obs
            if rec:
                wall = time.time()
                nreq = req_len
            gate = None

            def respond(payload: bytes = b"", error: Optional[str] = None,
                        error_code: int = 2001):
                # Latency spans dispatch -> respond, wherever respond runs
                # (the async contract: any thread, after the fiber worker
                # is long gone).  Accounting lands BEFORE the response
                # leaves — a client reading this server's vars right
                # after its reply must see this call counted.
                if gate is not None:
                    gate.on_responded(
                        error_code if error is not None else 0,
                        (time.monotonic_ns() - t0) // 1000)
                if error is not None:
                    if rec:
                        _record_server_call(name, m, t0, wall, nreq, 0,
                                            error, error_code)
                    lib.brt_session_respond(sess, None, 0, error_code,
                                            error.encode())
                else:
                    if rec:
                        _record_server_call(name, m, t0, wall, nreq,
                                            len(payload), None)
                    lib.brt_session_respond(sess, payload, len(payload), 0,
                                            None)

            lim = self._limiter
            if lim is not None:
                g = lim.gate(m)
                if g is not None and not g.admit():
                    # refused: respond ELIMIT with gate still None, so
                    # nothing is released on a request never admitted
                    respond(error=f"{name}.{m} shed: concurrency limit "
                                  f"{g.max_concurrency} reached",
                            error_code=resilience.ELIMIT)
                    return
                gate = g
            try:
                if fault.active():
                    fault.server_intercept(name, m, self._listen)
                handler(m, data, respond)
            except Exception as e:  # noqa: BLE001
                respond(error=str(e), error_code=_error_code_of(e))

        rc = lib.brt_server_add_service(self._ptr, name.encode(),
                                        trampoline, None)
        if rc != 0:
            raise RuntimeError(f"add_async_service failed: {rc}")
        self._handlers.append(trampoline)

    def add_status_service(self) -> None:
        """Hosts the ``_status`` builtin service (vars + rpcz dumps over
        the RPC fabric — the reference's builtin pages, src/brpc/builtin/)
        so a remote ``Channel`` can scrape this node's metrics:
        ``obs.status_service.scrape_vars(channel)``."""
        from brpc_tpu.obs.status_service import (SERVICE_NAME,
                                                 make_status_handler)
        self.add_service(SERVICE_NAME, make_status_handler())

    def add_naming_registry(self) -> None:
        """Hosts the native service registry on this server ("Naming",
        JSON-mapped — see brpc_tpu.naming for the client side)."""
        rc = self._lib.brt_server_add_naming_registry(self._ptr)
        if rc != 0:
            raise RuntimeError(f"add_naming_registry failed: {rc}")

    def start(self, addr: str = "127.0.0.1:0") -> int:
        rc = self._lib.brt_server_start(self._ptr, addr.encode())
        if rc != 0:
            raise RuntimeError(f"server start failed: {rc}")
        port = self._lib.brt_server_port(self._ptr)
        # the resolved listen address identifies this server to the
        # fault plan (per-endpoint server-side rules); the port map lets
        # the NATIVE drop hook translate its port back to this string
        self._listen = f"{addr.rsplit(':', 1)[0]}:{port}"
        _servers_by_port[port] = self._listen
        return port

    @property
    def port(self) -> int:
        return self._lib.brt_server_port(self._ptr)

    def stop(self) -> None:
        if self._ptr:
            self._lib.brt_server_stop(self._ptr)

    def close(self) -> None:
        if self._ptr:
            self._lib.brt_server_destroy(self._ptr)
            self._ptr = None


class PendingCall:
    """One in-flight async RPC (from :meth:`Channel.call_async`).

    ``join()`` parks until the reply lands and returns the response bytes
    (or raises :class:`RpcError` with the server/transport failure — same
    contract as the synchronous ``call``).  ``wait(timeout_s)`` peeks at
    completion without consuming it; ``cancel()`` requests native
    cancellation (reference ``StartCancel``) — the call still completes
    exactly once, with ECANCELEDRPC (2005) if the cancel won, so
    ``join``/``close`` stay mandatory.  The native handle is freed
    exactly once, by ``join()`` or ``close()``; ``close()`` on an
    un-joined call waits for completion first (the native core may still
    be filling the response), so abandoning a fan-out mid-error is safe —
    and cheap after ``cancel()``, which is how the PS tier abandons
    straggler shards.
    """

    __slots__ = ("_lib", "_ptr", "_service", "_method", "_peer",
                 "_req_len", "_t0", "_wall", "_tag", "_iobuf")

    def __init__(self, lib, ptr, service, method, peer, req_len, t0, wall,
                 tag=None, iobuf=False):
        self._lib = lib
        self._ptr = ptr
        self._service = service
        self._method = method
        self._peer = peer
        self._req_len = req_len
        self._t0 = t0      # None when obs was disabled at start
        self._wall = wall
        self._tag = tag
        # Calls started with an IOBuf request join to an IOBuf response
        # (brt_call_join_iobuf swaps the blocks out — no copy).
        self._iobuf = iobuf

    def wait(self, timeout_s: Optional[float] = None) -> bool:
        """True once the call has completed (``join`` will not block).
        ``timeout_s=None`` waits indefinitely; ``0`` polls.  Callable
        any number of times — nothing is consumed."""
        if self._ptr is None:
            return True
        if timeout_s is None:
            if _race.enabled():
                _race.note_blocking("brt_call_wait")
            return self._lib.brt_call_wait(self._ptr, -1) == 0
        us = max(0, int(timeout_s * 1e6))
        return self._lib.brt_call_wait(self._ptr, us) == 0

    def cancel(self) -> None:
        """Request cancellation (safe from any thread, idempotent, no-op
        after completion).  The losing half of a backup-request hedge and
        abandoned PS stragglers go through here."""
        if self._ptr is not None:
            self._lib.brt_call_cancel(self._ptr)
            if obs.enabled():
                obs.counter("rpc_cancels").add(1)

    def join(self) -> bytes:
        if self._ptr is None:
            raise RuntimeError("async call already joined/closed")
        if _race.enabled():
            _race.note_blocking("brt_call_join")
        if self._iobuf:
            return self._join_iobuf()
        ptr, self._ptr = self._ptr, None
        rsp = ctypes.c_void_p()
        rsp_len = ctypes.c_size_t()
        errbuf = ctypes.create_string_buffer(256)
        try:
            rc = self._lib.brt_call_join(ptr, ctypes.byref(rsp),
                                         ctypes.byref(rsp_len), errbuf, 256)
            if rc != 0:
                text = errbuf.value.decode(errors="replace")
                if self._t0 is not None:
                    _record_client_call(self._service, self._method,
                                        self._peer, self._t0, self._wall,
                                        self._req_len, 0, rc, text,
                                        self._tag)
                raise RpcError(rc, text)
            try:
                out = ctypes.string_at(rsp, rsp_len.value)
            finally:
                self._lib.brt_free(rsp)
        finally:
            self._lib.brt_call_destroy(ptr)
        if self._t0 is not None:
            # start -> join latency: the caller-visible async window
            _record_client_call(self._service, self._method, self._peer,
                                self._t0, self._wall, self._req_len,
                                len(out), 0, "", self._tag)
            obs.counter("rpc_bytes_copied").add(len(out))
        return out

    def _join_iobuf(self) -> "IOBuf":
        """Collects the reply as an :class:`IOBuf` — the response blocks
        are swapped out of the call, not copied."""
        ptr, self._ptr = self._ptr, None
        err = ctypes.c_int()
        errbuf = ctypes.create_string_buffer(256)
        try:
            h = self._lib.brt_call_join_iobuf(ptr, ctypes.byref(err),
                                              errbuf, 256)
            if not h:
                text = errbuf.value.decode(errors="replace")
                if self._t0 is not None:
                    _record_client_call(self._service, self._method,
                                        self._peer, self._t0, self._wall,
                                        self._req_len, 0, err.value, text,
                                        self._tag)
                raise RpcError(err.value or -1, text)
        finally:
            self._lib.brt_call_destroy(ptr)
        out = IOBuf._adopt(self._lib, h)
        if self._t0 is not None:
            _record_client_call(self._service, self._method, self._peer,
                                self._t0, self._wall, self._req_len,
                                len(out), 0, "", self._tag)
        return out

    def close(self) -> None:
        """Abandon without collecting the result (no-op after join)."""
        if self._ptr is not None:
            ptr, self._ptr = self._ptr, None
            self._lib.brt_call_destroy(ptr)


class CallGroup:
    """Exact multi-call fan-in: one native CountdownEvent signaled by the
    done-closure of every registered call (the ParallelChannel shape,
    cpp/cluster/parallel_channel.*).

    ``add()`` registers an un-consumed :class:`PendingCall` (a call that
    already completed counts immediately).  ``wait()`` parks until EVERY
    registered call has completed; ``wait_any()`` parks until a completion
    that no previous ``wait_any`` consumed exists, consumes it, and
    returns — N calls yield exactly N successful ``wait_any`` returns, so
    hedge/fan-out loops wake exactly instead of polling ``wait`` in time
    slices.  The group observes completion only: ``join()``/``close()``
    each call as usual.  ``close()`` is safe with members still in flight
    (registration is refcounted natively)."""

    __slots__ = ("_lib", "_ptr")

    def __init__(self):
        self._lib = _load()
        self._ptr = self._lib.brt_call_group_new()

    def add(self, call: PendingCall) -> None:
        if self._ptr is None or call._ptr is None:
            raise RuntimeError("cannot add a joined/closed call to a group")
        self._lib.brt_call_group_add(self._ptr, call._ptr)

    def wait(self, timeout_s: Optional[float] = None) -> bool:
        """True once every registered call has completed (all joins are
        then non-blocking).  Level-triggered; callable repeatedly."""
        if obs.enabled():
            obs.counter("rpc_group_waits").add(1)
        if timeout_s is None:
            if _race.enabled():
                _race.note_blocking("brt_call_group_wait")
            return self._lib.brt_call_group_wait(self._ptr, -1) == 0
        us = max(0, int(timeout_s * 1e6))
        return self._lib.brt_call_group_wait(self._ptr, us) == 0

    def wait_any(self, timeout_s: Optional[float] = None) -> bool:
        """True once an unconsumed completion exists (consuming it): each
        successful return corresponds to exactly one call completing."""
        if obs.enabled():
            obs.counter("rpc_group_waits").add(1)
        if timeout_s is None:
            if _race.enabled():
                _race.note_blocking("brt_call_group_wait")
            return self._lib.brt_call_group_wait_any(self._ptr, -1) == 0
        us = max(0, int(timeout_s * 1e6))
        return self._lib.brt_call_group_wait_any(self._ptr, us) == 0

    @property
    def completed(self) -> int:
        """Completions observed so far (diagnostics)."""
        return self._lib.brt_call_group_completed(self._ptr)

    def close(self) -> None:
        if self._ptr is not None:
            ptr, self._ptr = self._ptr, None
            self._lib.brt_call_group_destroy(ptr)


class Stream:
    """Client write side of a streaming RPC (from :meth:`Channel.stream`).

    An ordered, flow-controlled frame pipe bound to the channel's
    connection (the reference's StreamCreate/StreamWrite,
    cpp/rpc/stream.*): ``write()`` ships one framed message at wire rate
    and PARKS when the peer's unconsumed window (``max_buf_size``) is
    full — backpressure is real, not advisory; the stalled time feeds the
    ``stream_stall_ms`` counter.  ``close()`` is graceful: in-flight
    frames drain to the receiver IN ORDER before its ``on_closed`` runs,
    and ``join()`` returns once the peer has consumed everything and
    closed its half — the "every pushed delta is applied" barrier the PS
    tier builds on.  ``abort()`` is the error-path teardown (failed
    setup, dead connection): immediate, nothing reaches the peer.

    Writes on one stream must come from one thread at a time (frame
    order is the caller's once two writers interleave).
    """

    # Stalls below this are the wait-free socket write itself, not
    # backpressure; counting them would drown the signal in noise.
    _STALL_FLOOR_US = 1000

    __slots__ = ("_lib", "_id", "response", "service", "method", "peer",
                 "_closed", "_track")

    def __init__(self, lib, stream_id: int, response: bytes, service: str,
                 method: str, peer: str, track: bool = True):
        self._lib = lib
        self._id = stream_id
        #: the setup RPC's response bytes (the server's accept-time answer)
        self.response = response
        self.service = service
        self.method = method
        self.peer = peer
        self._closed = False
        # Client streams own their ledger entry; the server-half write
        # surface returned by accept() does not (the receiver registry
        # entry is that stream's ledger record).
        self._track = track

    def write(self, data) -> None:
        """Ordered framed write (bytes/bytearray/memoryview — the native
        side copies before returning).  Parks while the flow-control
        window is full; raises :class:`RpcError` on a closed/broken
        stream (EPIPE: peer closed; EINVAL: locally closed/unknown)."""
        if self._closed:
            raise RpcError(22, f"stream to {self.peer} is closed")
        if _race.enabled():
            _race.note_blocking("brt_stream_write")
        stall = ctypes.c_int64()
        rc = self._lib.brt_stream_write(self._id, _req_ptr(data),
                                        len(data), ctypes.byref(stall))
        if obs.enabled():
            obs.counter("stream_writes").add(1)
            obs.counter("stream_bytes_out").add(len(data))
            if stall.value > self._STALL_FLOOR_US:
                obs.counter("stream_stall_ms").add(stall.value / 1000.0)
        if rc != 0:
            raise RpcError(rc, f"stream write to {self.peer} failed")

    def writev(self, frames) -> int:
        """Batched ordered write: N framed messages in ONE native
        crossing, each frame's payload borrowed, not copied — bytes
        frames are pinned until the socket write drains them, and
        :class:`IOBuf` frames ride their own block refcounts.  Returns
        the number of frames written.  On failure raises
        :class:`RpcError` with ``e.frames_written`` set — frames before
        it are on the wire, frames from it on are NOT (the caller's
        retry queue still holds them)."""
        if self._closed:
            raise RpcError(22, f"stream to {self.peer} is closed")
        frames = list(frames)
        if not frames:
            return 0
        if _race.enabled():
            _race.note_blocking("brt_stream_writev")
        temps = []
        handles = []
        total = 0
        try:
            for f in frames:
                if isinstance(f, IOBuf):
                    handles.append(f._require())
                    total += len(f)
                else:
                    io = IOBuf()
                    io.append_pinned(f)
                    temps.append(io)
                    handles.append(io._require())
                    total += len(f)
            arr = (ctypes.c_void_p * len(handles))(*handles)
            nw = ctypes.c_int()
            stall = ctypes.c_int64()
            rc = self._lib.brt_stream_writev(
                self._id, arr, len(handles), ctypes.byref(nw),
                ctypes.byref(stall))
        finally:
            for io in temps:
                io.close()
        if obs.enabled():
            obs.counter("stream_writes").add(nw.value)
            obs.counter("stream_bytes_out").add(total)
            if stall.value > self._STALL_FLOOR_US:
                obs.counter("stream_stall_ms").add(stall.value / 1000.0)
        if rc != 0:
            e = RpcError(rc, f"stream writev to {self.peer} failed at "
                             f"frame {nw.value}/{len(handles)}")
            e.frames_written = nw.value
            raise e
        return nw.value

    def close(self) -> None:
        """Graceful close: flushes in-flight frames, then tells the peer.
        Idempotent; pair with :meth:`join` to wait for full application."""
        if not self._closed:
            self._closed = True
            if self._track:
                _handles.note_destroy("stream", self._id)
            self._lib.brt_stream_close(self._id)

    def join(self, timeout_s: Optional[float] = None) -> bool:
        """True once BOTH sides closed — every written frame was
        delivered, consumed, and the peer answered CLOSE.  Call after
        :meth:`close`; ``timeout_s=None`` waits forever."""
        if _race.enabled():
            _race.note_blocking("brt_stream_join")
        us = -1 if timeout_s is None else max(0, int(timeout_s * 1e6))
        return self._lib.brt_stream_join(self._id, us) == 0

    def abort(self) -> None:
        """Abrupt local teardown (reconnect/error paths): wakes any
        writer/joiner, frees native state, sends nothing.  Idempotent."""
        if not self._closed:
            self._closed = True
            if self._track:
                _handles.note_destroy("stream", self._id)
        self._lib.brt_stream_abort(self._id)


class PsShard:
    """Native generation-versioned PS shard (cpp/capi/ps_shard.cc): serves
    ``Lookup`` entirely inside the C++ fiber handler once attached to a
    server via :meth:`Server.add_ps_service`.

    The caller owns the WRITE path: it keeps the mutable table (numpy),
    applies gradients, then publishes an immutable snapshot with
    :meth:`install` — readers pin a generation, gather outside any lock,
    and the last reader frees a retired snapshot (the handle-generation
    scheme of the device shard, moved into the native core)."""

    __slots__ = ("_lib", "_ptr", "rows_per", "dim")

    def __init__(self, vocab: int, dim: int, shard_index: int,
                 num_shards: int):
        self._lib = _load()
        self._ptr = self._lib.brt_ps_shard_new(vocab, dim, shard_index,
                                               num_shards)
        if not self._ptr:
            raise ValueError(
                f"bad shard geometry: vocab={vocab} dim={dim} "
                f"shard={shard_index}/{num_shards}")
        self.rows_per = vocab // num_shards
        self.dim = dim

    def install(self, table, gen: int) -> None:
        """Publishes ``table`` ([rows_per, dim] float32) as generation
        ``gen``.  The native side snapshots the buffer before returning,
        so the caller may keep mutating its array."""
        import numpy as np
        arr = np.ascontiguousarray(table, dtype=np.float32)
        if arr.shape != (self.rows_per, self.dim):
            raise ValueError(f"table shape {arr.shape} != "
                             f"({self.rows_per}, {self.dim})")
        rc = self._lib.brt_ps_shard_install(self._ptr, arr.ctypes.data,
                                            self.rows_per, gen)
        if rc != 0:
            raise RpcError(rc, "ps shard install failed")

    @property
    def generation(self) -> int:
        return self._lib.brt_ps_shard_generation(self._ptr)

    @property
    def native_lookups(self) -> int:
        """Lookups served with zero Python in the loop."""
        return self._lib.brt_ps_shard_native_lookups(self._ptr)

    def lookup_stats(self) -> "tuple[int, int]":
        """``(sum_us, count)`` of native Lookup service times — the
        zero-Python read path never touches the server's Python latency
        recorder, so its tail stats are reconstructed from this pair."""
        sum_us = ctypes.c_int64(0)
        count = ctypes.c_int64(0)
        self._lib.brt_ps_shard_lookup_stats(
            self._ptr, ctypes.byref(sum_us), ctypes.byref(count))
        return sum_us.value, count.value

    def close(self) -> None:
        """Destroy the shard.  Servers it is attached to MUST already be
        closed (their handlers gather from this shard's snapshots)."""
        if self._ptr is not None:
            ptr, self._ptr = self._ptr, None
            self._lib.brt_ps_shard_destroy(ptr)


class Channel:
    """Client channel. addr: "ip:port" single-server, or a cluster url
    ("list://h1,h2", "file://path", "dns://host:port") + lb name."""

    def __init__(self, addr: str, lb: Optional[str] = None,
                 timeout_ms: int = 1000, max_retry: int = 3):
        self._lib = _load()
        self._addr = addr
        self._ptr = self._lib.brt_channel_new(
            addr.encode(), lb.encode() if lb else None, timeout_ms,
            max_retry)
        if not self._ptr:
            raise RuntimeError(f"channel init failed for {addr}")

    def call(self, service: str, method: str, request: bytes = b"", *,
             timeout_ms: Optional[int] = None,
             retry: "Optional[resilience.RetryPolicy]" = None,
             deadline_ms: Optional[float] = None,
             backup_ms: Optional[float] = None,
             breaker: "Optional[resilience.CircuitBreaker]" = None
             ) -> bytes:
        """Synchronous call.  The keyword-only resilience options layer
        policy over the bare native call (brpc_tpu.resilience):

        - ``timeout_ms`` — per-call deadline override (reference
          ``Controller::set_timeout_ms``).
        - ``retry`` / ``deadline_ms`` — RetryPolicy attempts under a
          total deadline budget; each attempt's native timeout is the
          budget still remaining.
        - ``backup_ms`` — hedge: a second attempt fires if no reply in
          N ms, first completion wins, loser is cancelled natively.
        - ``breaker`` — per-endpoint CircuitBreaker: fail fast while
          open, feed every outcome.
        """
        if retry is not None or deadline_ms is not None \
                or backup_ms is not None or breaker is not None:
            return resilience.resilient_call(
                self, service, method, request, retry=retry,
                deadline_ms=deadline_ms, backup_ms=backup_ms,
                breaker=breaker, timeout_ms=timeout_ms)
        if timeout_ms is not None:
            return self.call_async(service, method, request,
                                   timeout_ms=timeout_ms).join()
        rec = obs.enabled()
        if rec:
            t0 = time.monotonic_ns()
            wall = time.time()
        if fault.active():
            fault.client_intercept(service, method, self._addr)
        if _race.enabled():
            _race.note_blocking("brt_channel_call")
        if isinstance(request, IOBuf) and not request.force_iobuf \
                and len(request) < IOBUF_MIN_BYTES:
            # Below the crossover the handle-lifecycle tax outweighs
            # the saved copy: route through the bytes twin (identical
            # wire bytes; the caller still closes its handle, and the
            # response comes back as plain bytes).
            request = request.tobytes()
        if isinstance(request, IOBuf):
            # Zero-copy currency: the request's blocks are shared into
            # the native call (no payload copy; the caller's handle keeps
            # its contents for retries) and the reply comes back as an
            # IOBuf whose blocks were swapped out of the response.
            err = ctypes.c_int()
            errbuf = ctypes.create_string_buffer(256)
            h = self._lib.brt_channel_call_iobuf(
                self._ptr, service.encode(), method.encode(),
                request._require(), ctypes.byref(err), errbuf, 256)
            if not h:
                text = errbuf.value.decode(errors="replace")
                if rec:
                    _record_client_call(service, method, self._addr, t0,
                                        wall, len(request), 0, err.value,
                                        text)
                raise RpcError(err.value or -1, text)
            out = IOBuf._adopt(self._lib, h)
            if rec:
                _record_client_call(service, method, self._addr, t0, wall,
                                    len(request), len(out), 0, "")
            return out
        rsp = ctypes.c_void_p()
        rsp_len = ctypes.c_size_t()
        errbuf = ctypes.create_string_buffer(256)
        rc = self._lib.brt_channel_call(
            self._ptr, service.encode(), method.encode(),
            _req_ptr(request), len(request), ctypes.byref(rsp),
            ctypes.byref(rsp_len), errbuf, 256)
        if rc != 0:
            text = errbuf.value.decode(errors="replace")
            if rec:
                _record_client_call(service, method, self._addr, t0, wall,
                                    len(request), 0, rc, text)
            raise RpcError(rc, text)
        try:
            out = ctypes.string_at(rsp, rsp_len.value)
        finally:
            self._lib.brt_free(rsp)
        if rec:
            _record_client_call(service, method, self._addr, t0, wall,
                                len(request), len(out), 0, "")
            obs.counter("rpc_bytes_copied").add(len(out))
        return out

    def call_async(self, service: str, method: str, request: bytes = b"",
                   *, timeout_ms: Optional[int] = None,
                   tag: Optional[str] = None) -> PendingCall:
        """Starts the call and returns immediately with a
        :class:`PendingCall`; the RPC proceeds on the fiber scheduler and
        ``join()`` collects the reply.  Starting N calls before joining
        any fans out over N servers concurrently — whole-batch latency is
        max(server) instead of sum(server) (the ParallelChannel shape,
        cpp/cluster/parallel_channel.*).  The request bytes are copied by
        the native core before this returns.  ``timeout_ms`` overrides
        the channel deadline for this one call (the retry loop's
        shrinking budget rides this); ``tag`` annotates the client rpcz
        span (attempt/hedge labels)."""
        rec = obs.enabled()
        t0 = time.monotonic_ns() if rec else None
        wall = time.time() if rec else 0.0
        if fault.active():
            fault.client_intercept(service, method, self._addr, timeout_ms)
        if isinstance(request, IOBuf) and not request.force_iobuf \
                and len(request) < IOBUF_MIN_BYTES:
            # Same bytes-twin routing as the sync call: sub-crossover
            # payloads skip the handle tax (join() then returns bytes).
            request = request.tobytes()
        if isinstance(request, IOBuf):
            ptr = self._lib.brt_channel_call_start_iobuf(
                self._ptr, service.encode(), method.encode(),
                request._require(),
                _INT64_MIN if timeout_ms is None else int(timeout_ms))
            if not ptr:
                raise RpcError(-1, f"call_start failed for {self._addr}")
            return PendingCall(self._lib, ptr, service, method, self._addr,
                               len(request), t0, wall, tag, iobuf=True)
        ptr = self._lib.brt_channel_call_start_opts(
            self._ptr, service.encode(), method.encode(),
            _req_ptr(request), len(request),
            _INT64_MIN if timeout_ms is None else int(timeout_ms))
        if not ptr:
            raise RpcError(-1, f"call_start failed for {self._addr}")
        return PendingCall(self._lib, ptr, service, method, self._addr,
                           len(request), t0, wall, tag)

    def stream(self, service: str, method: str, request: bytes = b"", *,
               max_buf_size: int = 0, receiver=None) -> Stream:
        """Creates an ordered flow-controlled byte-frame stream bound to
        this channel's connection by running ``service``.``method``
        synchronously — the server's handler must ``accept`` the stream
        (see :meth:`Server.add_stream_handler`); its response comes back
        on ``Stream.response``.  ``max_buf_size`` bounds the unconsumed
        bytes in flight (0 = the native 2MB default): writers park beyond
        it until the receiver's consumed-bytes feedback returns credit.
        Raises :class:`RpcError` when the setup RPC fails or the server
        never accepted — nothing is left behind either way.

        ``receiver`` (an object with ``on_data(bytes)``/``on_closed()``)
        attaches a READ side: frames the server writes on its accepted
        half deliver to it, serialized, with a final ``on_closed`` after
        the server closes — the server→client direction (replica acks,
        catch-up data).  Frames the server wrote before this call
        returned are buffered and delivered first, possibly on the
        calling thread.  ``close()`` is a FULL close, not a half-close:
        peer frames arriving after it are discarded, so collect what you
        expect before closing.  An rx stream must be torn down with
        ``close()`` (``abort()`` would strand the native relay — the
        closed callback is what frees it)."""
        rec = obs.enabled()
        if rec:
            t0 = time.monotonic_ns()
            wall = time.time()
        if fault.active():
            fault.client_intercept(service, method, self._addr)
        if _race.enabled():
            _race.note_blocking("brt_stream_create")
        sid = ctypes.c_uint64()
        rsp = ctypes.c_void_p()
        rsp_len = ctypes.c_size_t()
        errbuf = ctypes.create_string_buffer(256)
        if receiver is not None:
            rc = self._lib.brt_stream_create_rx(
                self._ptr, service.encode(), method.encode(),
                _req_ptr(request), len(request), max_buf_size,
                _stream_dispatch, None, ctypes.byref(sid),
                ctypes.byref(rsp), ctypes.byref(rsp_len), errbuf, 256)
        else:
            rc = self._lib.brt_stream_create(
                self._ptr, service.encode(), method.encode(),
                _req_ptr(request), len(request), max_buf_size,
                ctypes.byref(sid), ctypes.byref(rsp), ctypes.byref(rsp_len),
                errbuf, 256)
        if rc != 0:
            text = errbuf.value.decode(errors="replace")
            if rec:
                _record_client_call(service, method, self._addr, t0, wall,
                                    len(request), 0, rc, text,
                                    tag="stream")
            raise RpcError(rc, text)
        try:
            out = ctypes.string_at(rsp, rsp_len.value)
        finally:
            self._lib.brt_free(rsp)
        if rec:
            obs.counter("stream_creates").add(1)
            _record_client_call(service, method, self._addr, t0, wall,
                                len(request), len(out), 0, "",
                                tag="stream")
        _handles.note_create("stream", sid.value)
        if receiver is not None:
            # Registration drains any frames the server raced ahead of
            # this return (ordered handoff — see _register_stream_receiver).
            _register_stream_receiver(sid.value, receiver)
        return Stream(self._lib, sid.value, out, service, method,
                      self._addr)

    def close(self) -> None:
        if self._ptr:
            self._lib.brt_channel_destroy(self._ptr)
            self._ptr = None


class DeviceExecutable:
    """A compiled StableHLO program launched via the native executable tier
    (cpp/device/pjrt_executable.cc) — no JAX in the launch path."""

    def __init__(self, lib, ptr):
        self._lib = lib
        self._ptr = ptr
        self.num_outputs = lib.brt_device_executable_num_outputs(ptr)

    def execute(self, args, nreplicas: int = 1):
        """args: flat list of buffer handles, row-major [replica][arg].
        Returns [replica][output] handles (release each when done)."""
        if _race.enabled():
            _race.note_blocking("brt_device_execute")
        nargs = len(args) // nreplicas
        a = (ctypes.c_uint64 * len(args))(*args)
        outs = (ctypes.c_uint64 * (nreplicas * self.num_outputs))()
        errbuf = ctypes.create_string_buffer(512)
        rc = self._lib.brt_device_execute(
            self._ptr, a, nargs, nreplicas, outs, len(outs), errbuf, 512)
        if rc != 0:
            raise RpcError(rc, errbuf.value.decode(errors="replace"))
        flat = list(outs)
        return [flat[d * self.num_outputs:(d + 1) * self.num_outputs]
                for d in range(nreplicas)]

    def close(self) -> None:
        if self._ptr:
            self._lib.brt_device_executable_destroy(self._ptr)
            self._ptr = None


class DeviceClient:
    """Native PJRT device fabric: staging + compiled execution, addressed by
    64-bit buffer handles (the RDMA-lkey analog). This is the binding the PS
    tier uses to keep embedding tables resident in HBM
    (brpc_tpu/ps_remote.py) — bytes move host<->device by DMA through the
    native layer, not through JAX."""

    DTYPE = {"u8": 0, "f32": 1, "i32": 2}

    def __init__(self, plugin_path: Optional[str] = None):
        self._lib = _load()
        errbuf = ctypes.create_string_buffer(512)
        self._ptr = self._lib.brt_device_client_new(
            plugin_path.encode() if plugin_path else None, errbuf, 512)
        if not self._ptr:
            raise RuntimeError(
                f"device client: {errbuf.value.decode(errors='replace')}")

    @property
    def device_count(self) -> int:
        return self._lib.brt_device_count(self._ptr)

    def stage(self, data, device_index: int = 0, dtype: str = "u8",
              dims=None) -> int:
        """DMAs bytes (or a numpy array) into device memory; returns a
        buffer handle."""
        import numpy as np
        if isinstance(data, np.ndarray):
            if dims is None:
                dims = list(data.shape)
            if dtype == "u8" and data.dtype != np.uint8:
                dtype = {"float32": "f32", "int32": "i32"}.get(
                    data.dtype.name, dtype)
            data = np.ascontiguousarray(data).tobytes()
        if dims is None:
            dims = [len(data)]
        errbuf = ctypes.create_string_buffer(512)
        d = (ctypes.c_int64 * len(dims))(*dims)
        h = self._lib.brt_device_stage_shaped(
            self._ptr, data, len(data), device_index, self.DTYPE[dtype], d,
            len(dims), errbuf, 512)
        if h == 0:
            raise RpcError(5002, errbuf.value.decode(errors="replace"))
        return h

    def fetch(self, handle: int) -> bytes:
        """DMAs the buffer behind handle back to host (fiber parks during
        the DMA); the buffer stays resident until released."""
        if _race.enabled():
            _race.note_blocking("brt_device_fetch")
        out = ctypes.c_void_p()
        out_len = ctypes.c_size_t()
        errbuf = ctypes.create_string_buffer(512)
        rc = self._lib.brt_device_fetch(
            self._ptr, handle, ctypes.byref(out), ctypes.byref(out_len),
            errbuf, 512)
        if rc != 0:
            raise RpcError(rc, errbuf.value.decode(errors="replace"))
        try:
            return ctypes.string_at(out, out_len.value)
        finally:
            self._lib.brt_free(out)

    def release(self, handle: int) -> None:
        self._lib.brt_device_release(handle)

    def mlir(self, kind: str, p0: int, p1: int = 0, p2: int = 0) -> str:
        p = self._lib.brt_mlir_module(kind.encode(), p0, p1, p2)
        if not p:
            raise ValueError(f"unknown mlir builder kind {kind!r}")
        try:
            return ctypes.string_at(p).decode()
        finally:
            self._lib.brt_free(p)

    def compile(self, mlir_text: str,
                num_replicas: int = 1) -> DeviceExecutable:
        errbuf = ctypes.create_string_buffer(1024)
        ptr = self._lib.brt_device_compile(
            self._ptr, mlir_text.encode(), num_replicas, errbuf, 1024)
        if not ptr:
            raise RpcError(5003, errbuf.value.decode(errors="replace"))
        return DeviceExecutable(self._lib, ptr)

    def close(self) -> None:
        if self._ptr:
            self._lib.brt_device_client_destroy(self._ptr)
            self._ptr = None
