"""ctypes bindings over the native RPC core (cpp/ → libbrpc_tpu_c.so).

Gives Python the reference's user surface — Server/Channel/Controller
(src/brpc/server.h:347, channel.h:151) — backed by the C++ fiber scheduler,
wait-free socket transport and cluster layer. Payloads are bytes; structure
(JSON, msgpack, numpy buffers) is the caller's choice.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Callable, Optional

_HANDLER = ctypes.CFUNCTYPE(
    None, ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
    ctypes.c_size_t, ctypes.c_void_p
)

_lib = None


def _build_dir() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "cpp", "build")


def _load():
    global _lib
    if _lib is not None:
        return _lib
    so = os.path.join(_build_dir(), "libbrpc_tpu_c.so")
    if not os.path.exists(so):
        build = _build_dir()
        os.makedirs(build, exist_ok=True)
        subprocess.run(["cmake", "-G", "Ninja",
                        "-DCMAKE_BUILD_TYPE=Release", ".."],
                       cwd=build, check=True, capture_output=True)
        subprocess.run(["ninja", "brpc_tpu_c"], cwd=build, check=True,
                       capture_output=True)
    lib = ctypes.CDLL(so)
    lib.brt_server_new.restype = ctypes.c_void_p
    lib.brt_server_add_service.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, _HANDLER, ctypes.c_void_p]
    lib.brt_server_start.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.brt_server_port.argtypes = [ctypes.c_void_p]
    lib.brt_server_stop.argtypes = [ctypes.c_void_p]
    lib.brt_server_destroy.argtypes = [ctypes.c_void_p]
    lib.brt_session_respond.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t, ctypes.c_int,
        ctypes.c_char_p]
    lib.brt_channel_new.restype = ctypes.c_void_p
    lib.brt_channel_new.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int]
    lib.brt_channel_call.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_void_p,
        ctypes.c_size_t, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_size_t), ctypes.c_char_p, ctypes.c_size_t]
    lib.brt_channel_destroy.argtypes = [ctypes.c_void_p]
    lib.brt_free.argtypes = [ctypes.c_void_p]
    lib.brt_init.argtypes = [ctypes.c_int]
    lib.brt_event_new.restype = ctypes.c_void_p
    lib.brt_event_set.argtypes = [ctypes.c_void_p]
    lib.brt_event_wait.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.brt_event_destroy.argtypes = [ctypes.c_void_p]
    lib.brt_init(0)
    _lib = lib
    return lib


class RpcError(RuntimeError):
    def __init__(self, code: int, text: str):
        super().__init__(f"rpc failed ({code}): {text}")
        self.code = code


class Server:
    """Native RPC server. Handlers: fn(method: str, request: bytes) -> bytes
    (raise to fail the call)."""

    def __init__(self):
        self._lib = _load()
        self._ptr = self._lib.brt_server_new()
        self._handlers = []  # keep CFUNCTYPE refs alive

    def add_service(self, name: str,
                    handler: Callable[[str, bytes], bytes]) -> None:
        lib = self._lib

        @_HANDLER
        def trampoline(user, method, req, req_len, session):
            try:
                data = ctypes.string_at(req, req_len) if req_len else b""
                out = handler(method.decode(), data)
                if out is None:
                    out = b""
                lib.brt_session_respond(session, out, len(out), 0, None)
            except Exception as e:  # noqa: BLE001
                lib.brt_session_respond(session, None, 0, 2001,
                                        str(e).encode())

        rc = lib.brt_server_add_service(self._ptr, name.encode(),
                                        trampoline, None)
        if rc != 0:
            raise RuntimeError(f"add_service failed: {rc}")
        self._handlers.append(trampoline)

    def add_async_service(self, name: str, handler) -> None:
        """handler(method: str, request: bytes, respond) — call
        ``respond(data: bytes)`` or ``respond(error=str)`` EXACTLY once,
        from any thread, any time (the fiber worker is released
        immediately — the "enqueue JAX work without blocking workers"
        shape: dispatch, return, respond from the completion callback)."""
        lib = self._lib

        @_HANDLER
        def trampoline(user, method, req, req_len, session):
            data = ctypes.string_at(req, req_len) if req_len else b""
            sess = ctypes.c_void_p(session)

            def respond(payload: bytes = b"", error: Optional[str] = None):
                if error is not None:
                    lib.brt_session_respond(sess, None, 0, 2001,
                                            error.encode())
                else:
                    lib.brt_session_respond(sess, payload, len(payload), 0,
                                            None)

            try:
                handler(method.decode(), data, respond)
            except Exception as e:  # noqa: BLE001
                respond(error=str(e))

        rc = lib.brt_server_add_service(self._ptr, name.encode(),
                                        trampoline, None)
        if rc != 0:
            raise RuntimeError(f"add_async_service failed: {rc}")
        self._handlers.append(trampoline)

    def start(self, addr: str = "127.0.0.1:0") -> int:
        rc = self._lib.brt_server_start(self._ptr, addr.encode())
        if rc != 0:
            raise RuntimeError(f"server start failed: {rc}")
        return self._lib.brt_server_port(self._ptr)

    @property
    def port(self) -> int:
        return self._lib.brt_server_port(self._ptr)

    def stop(self) -> None:
        if self._ptr:
            self._lib.brt_server_stop(self._ptr)

    def close(self) -> None:
        if self._ptr:
            self._lib.brt_server_destroy(self._ptr)
            self._ptr = None


class Channel:
    """Client channel. addr: "ip:port" single-server, or a cluster url
    ("list://h1,h2", "file://path", "dns://host:port") + lb name."""

    def __init__(self, addr: str, lb: Optional[str] = None,
                 timeout_ms: int = 1000, max_retry: int = 3):
        self._lib = _load()
        self._ptr = self._lib.brt_channel_new(
            addr.encode(), lb.encode() if lb else None, timeout_ms,
            max_retry)
        if not self._ptr:
            raise RuntimeError(f"channel init failed for {addr}")

    def call(self, service: str, method: str, request: bytes = b"") -> bytes:
        rsp = ctypes.c_void_p()
        rsp_len = ctypes.c_size_t()
        errbuf = ctypes.create_string_buffer(256)
        rc = self._lib.brt_channel_call(
            self._ptr, service.encode(), method.encode(), request,
            len(request), ctypes.byref(rsp), ctypes.byref(rsp_len), errbuf,
            256)
        if rc != 0:
            raise RpcError(rc, errbuf.value.decode(errors="replace"))
        try:
            return ctypes.string_at(rsp, rsp_len.value)
        finally:
            self._lib.brt_free(rsp)

    def close(self) -> None:
        if self._ptr:
            self._lib.brt_channel_destroy(self._ptr)
            self._ptr = None
