"""Scenario traffic harness — the ``rpc_press`` / ``rpc_replay`` analog
(SURVEY §2.9/§2.11) for the PS fabric.

The reference ships load tooling as part of the framework: ``rpc_press``
replays synthetic traffic at a target qps against any service,
``rpc_replay`` re-fires traffic captured by the rpc_dump sampler.  This
module is that pairing for the embedding fabric, and the acceptance
workload of the overload-control tier (:mod:`brpc_tpu.limiter`):

- :func:`build_ops` generates a DETERMINISTIC op stream from a
  :class:`Scenario`: seeded Poisson (open-loop) arrivals at a
  piecewise-constant rate (steady + periodic bursts), zipf-skewed key
  draws (the hot-row reality of embedding traffic), and a
  read/write mix.
- record/replay: :func:`save_trace` / :func:`load_trace` persist an op
  stream as a binary trace file — schema-declared framing
  (``press_header`` / ``press_record`` in :mod:`brpc_tpu.wire`, fuzzed
  like every other parser), gradients re-derived from the header seed
  so a trace is compact and a replay is exact.
- :func:`run_press` drives the stream OPEN-LOOP against a live shard
  server (one pacer thread issuing ``call_async`` at the scheduled
  instants — arrivals do not slow down when the server does, which is
  the point — plus a collector pool joining completions), measuring
  per-op sojourn (completion minus SCHEDULED arrival: coordinated
  omission is not allowed to hide queueing) and reporting the SLO
  numbers the scenario matrix is judged on: availability, p50/p99 of
  successes, and GOODPUT — in-deadline successes per second, the only
  number that survives an overload collapse.

CLI::

    python -m brpc_tpu.press --target ip:port --qps 500 --duration 3
        [--record FILE | --replay FILE] [--deadline-ms 50 --stamp]
"""

from __future__ import annotations

import argparse
import collections
import dataclasses
import json
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from brpc_tpu import obs, wire
from brpc_tpu.analysis.race import checked_lock

__all__ = [
    "OP_LOOKUP", "OP_APPLY", "PressOp", "Scenario", "zipf_weights",
    "build_ops", "trace_bytes", "parse_trace", "save_trace",
    "load_trace", "run_press", "GRAD_VALUE", "main",
]

#: trace file format version (press_header.version)
PRESS_VERSION = 1

OP_LOOKUP = 0
OP_APPLY = 1

#: the synthesized gradient value: exactly representable (2^-6), so a
#: recorded run and its replay mutate tables byte-identically
GRAD_VALUE = 2.0 ** -6


@dataclasses.dataclass(frozen=True)
class PressOp:
    """One scheduled op: arrival offset (us from run start), kind
    (``OP_LOOKUP``/``OP_APPLY``), and the key ids it touches."""

    t_us: int
    op: int
    ids: np.ndarray


@dataclasses.dataclass(frozen=True)
class Scenario:
    """One traffic shape, fully determined by its fields + ``seed``.

    ``qps`` is the steady open-loop arrival rate; when
    ``burst_every_s`` > 0, windows of ``burst_len_s`` starting every
    ``burst_every_s`` arrive at ``burst_qps`` instead (the
    past-capacity spike overload control exists for).  ``zipf_s`` > 0
    draws keys zipf(s)-skewed over the vocab (rank-1 hottest);
    ``read_fraction`` splits lookups vs gradient applies."""

    name: str = "steady"
    duration_s: float = 2.0
    qps: float = 200.0
    batch: int = 16
    read_fraction: float = 1.0
    zipf_s: float = 0.0
    burst_qps: float = 0.0
    burst_every_s: float = 0.0
    burst_len_s: float = 0.0
    seed: int = 0


def zipf_weights(vocab: int, s: float) -> np.ndarray:
    """Normalized zipf(s) pmf over ``vocab`` ranks (rank 1 hottest)."""
    ranks = np.arange(1, vocab + 1, dtype=np.float64)
    w = ranks ** -float(s)
    return w / w.sum()


def _rate_at(sc: Scenario, t: float) -> float:
    if sc.burst_every_s > 0 and sc.burst_qps > 0 and \
            (t % sc.burst_every_s) < sc.burst_len_s:
        return sc.burst_qps
    return sc.qps


def build_ops(sc: Scenario, vocab: int) -> List[PressOp]:
    """The scenario's deterministic op stream: seeded Poisson arrivals
    whose rate follows the steady/burst schedule, zipf or uniform key
    draws, seeded read/write coin flips.  Same scenario → same stream,
    always (the record/replay determinism contract)."""
    rng = np.random.default_rng(sc.seed)
    weights = zipf_weights(vocab, sc.zipf_s) if sc.zipf_s > 0 else None
    ops: List[PressOp] = []
    t = 0.0
    while True:
        rate = max(_rate_at(sc, t), 1e-6)
        t += float(rng.exponential(1.0 / rate))
        if t >= sc.duration_s:
            break
        if weights is not None:
            ids = rng.choice(vocab, size=sc.batch, p=weights)
        else:
            ids = rng.integers(0, vocab, size=sc.batch)
        kind = OP_LOOKUP if rng.random() < sc.read_fraction else OP_APPLY
        ops.append(PressOp(int(t * 1e6), kind,
                           np.sort(ids).astype(np.int32)))
    return ops


# ---------------------------------------------------------------------------
# record / replay (wire schemas press_header / press_record)
# ---------------------------------------------------------------------------

def _pack_press_header(seed: int, vocab: int, dim: int,
                       count: int) -> bytes:
    return struct.pack("<iiqqii", wire.PRESS_MAGIC, PRESS_VERSION,
                       seed, vocab, dim, count)


def _unpack_press_header(payload, offset: int = 0
                         ) -> Tuple[Tuple[int, int, int, int], int]:
    """Returns ``((seed, vocab, dim, count), end_offset)``; rejects a
    wrong magic/version or hostile geometry with :class:`wire.WireError`
    before anything is allocated."""
    magic, version, seed, vocab, dim, count = wire.read(
        "<iiqqii", payload, offset, "press.header")
    if magic != wire.PRESS_MAGIC:
        raise wire.WireError(f"press trace magic {magic:#x} != "
                             f"{wire.PRESS_MAGIC:#x}")
    if version != PRESS_VERSION:
        raise wire.WireError(f"press trace version {version} "
                             f"(supported: {PRESS_VERSION})")
    if vocab < 0 or dim < 0 or count < 0:
        raise wire.WireError(
            f"press trace header with negative geometry "
            f"(vocab={vocab}, dim={dim}, count={count})")
    return (seed, vocab, dim, count), offset + 32


def _pack_press_record(op: PressOp) -> bytes:
    ids = np.ascontiguousarray(op.ids, dtype=np.int32)
    return struct.pack("<qii", op.t_us, op.op, ids.size) + ids.tobytes()


def _unpack_press_record(payload, offset: int = 0
                         ) -> Tuple[PressOp, int]:
    """Guarded record parse: the id count is bounded by the bytes
    actually present before it drives the array read."""
    t_us, kind, nids = wire.read("<qii", payload, offset, "press.record")
    offset += 16
    wire.check_count(nids, (len(payload) - offset) // 4, "press.nids")
    ids = np.frombuffer(payload, np.int32, nids, offset)
    return PressOp(t_us, kind, ids), offset + 4 * nids


def trace_bytes(ops: List[PressOp], *, seed: int = 0, vocab: int = 0,
                dim: int = 0) -> bytes:
    """Serialize one op stream (header ++ records back to back)."""
    parts = [_pack_press_header(seed, vocab, dim, len(ops))]
    for op in ops:
        parts.append(_pack_press_record(op))
    return b"".join(parts)


def parse_trace(buf) -> Tuple[Dict[str, int], List[PressOp]]:
    """Strict inverse of :func:`trace_bytes`: every declared record
    must parse, kinds must be known, and nothing may trail the last
    record — a torn or corrupted trace rejects cleanly
    (:class:`wire.WireError`), it never replays garbage traffic."""
    (seed, vocab, dim, count), off = _unpack_press_header(buf)
    wire.check_count(count, (len(buf) - off) // 16, "press.count")
    ops: List[PressOp] = []
    for _ in range(count):
        op, off = _unpack_press_record(buf, off)
        if op.op not in (OP_LOOKUP, OP_APPLY):
            raise wire.WireError(f"press record with unknown op kind "
                                 f"{op.op}")
        if op.t_us < 0:
            raise wire.WireError("press record with negative arrival")
        ops.append(op)
    if off != len(buf):
        raise wire.WireError(
            f"press trace carries {len(buf) - off} trailing byte(s) "
            f"after its {count} declared record(s)")
    return {"seed": seed, "vocab": vocab, "dim": dim}, ops


def save_trace(path: str, ops: List[PressOp], *, seed: int = 0,
               vocab: int = 0, dim: int = 0) -> None:
    with open(path, "wb") as f:
        f.write(trace_bytes(ops, seed=seed, vocab=vocab, dim=dim))


def load_trace(path: str) -> Tuple[Dict[str, int], List[PressOp]]:
    with open(path, "rb") as f:
        return parse_trace(f.read())


# ---------------------------------------------------------------------------
# the open-loop driver
# ---------------------------------------------------------------------------

def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1)))
    return sorted_vals[idx]


class _ChannelPool:
    """Round-robin native-channel registry for the multi-connection
    pacer: one connection serializes its socket writes, so ``n > 1``
    raises the open-loop client ceiling on multi-core hosts.  Owns its
    channels — :meth:`close` releases every one (the dynamic handle
    ledger cross-checks)."""

    def __init__(self, addr: str, n: int, timeout_ms: int):
        from brpc_tpu import rpc  # lazy: press imports without the core
        self._chs: Dict[int, object] = {}
        for i in range(max(1, n)):
            ch = rpc.Channel(addr, timeout_ms=timeout_ms)
            self._chs[i] = ch

    def __len__(self) -> int:
        return len(self._chs)

    def pick(self, i: int):
        return self._chs[i % len(self._chs)]

    def close(self) -> None:
        for ch in self._chs.values():
            ch.close()
        self._chs.clear()


def run_press(addr: str, ops: List[PressOp], dim: int, *,
              deadline_ms: Optional[float] = None,
              stamp_deadline: bool = False,
              stamp_mode: str = "absolute",
              collectors: int = 4,
              channels: int = 1,
              timeout_ms: Optional[int] = None,
              retry_on_limit: int = 0,
              limit_backoff_ms: float = 5.0,
              service: str = "Ps") -> Dict[str, object]:
    """Drive ``ops`` open-loop against the shard server at ``addr``.

    One pacer thread issues every op at its SCHEDULED instant via
    ``call_async`` (a slow server does not slow arrivals — that is what
    makes overload real); ``collectors`` threads join completions.
    With ``deadline_ms`` each call carries that native timeout, and
    ``stamp_deadline=True`` additionally prefixes the deadline header
    (wire schema ``deadline_hdr``) so the SERVER sheds queued work that
    can no longer answer in time.

    Latency is reported two ways: ``service`` (join minus issue) and
    ``sojourn`` (join minus scheduled arrival — the open-loop number
    that includes client-side catch-up lag and refuses coordinated
    omission).  Goodput counts successes whose sojourn beat the
    deadline; availability counts all successes.

    ``retry_on_limit`` applies the production client policy to
    ``ELIMIT`` sheds: up to N re-issues, each after the MANDATORY
    ``limit_backoff_ms`` pause (never straight back into the overload)
    and only while the op's own deadline budget still has room — a
    transient admission spike is absorbed, a sustained overload stays
    a shed.

    ``channels=N`` paces over N native connections round-robin: one
    channel serializes its socket's writes, so on a multi-core host a
    single connection caps the open-loop driver below what the server
    could absorb — the multi-connection pacer raises the client
    ceiling (the reference rpc_press's connection fan-out).
    ``stamp_mode="relative"`` stamps the v2 relative-budget header
    instead of the absolute wall-clock form."""
    from brpc_tpu import rpc  # lazy: press imports without the native core
    from brpc_tpu.ps_remote import (_pack_apply_req, _pack_deadline,
                                    _pack_deadline_rel, _pack_lookup_req)

    # channel registry keyed by pacer index (every entry is closed
    # before run_press returns; the dynamic handle ledger checks it)
    chs = _ChannelPool(addr, channels,
                       timeout_ms or int(deadline_ms * 4
                                         if deadline_ms else 2000))
    results: List[Tuple[bool, int, float, float]] = []
    res_mu = checked_lock("press.results")
    inflight: collections.deque = collections.deque()
    pacing_done = threading.Event()
    call_timeout = int(deadline_ms) if deadline_ms is not None else None

    def _record(ok: bool, code: int, sojourn_s: float,
                service_s: float) -> None:
        with res_mu:
            results.append((ok, code, sojourn_s, service_s))

    start = time.monotonic()

    def pacer() -> None:
        wall0 = time.time()
        for i, op in enumerate(ops):
            due = start + op.t_us / 1e6
            now = time.monotonic()
            if due > now:
                time.sleep(due - now)
            if op.op == OP_LOOKUP:
                method, req = "Lookup", _pack_lookup_req(op.ids)
            else:
                grads = np.full((op.ids.size, dim), GRAD_VALUE,
                                np.float32)
                method, req = "ApplyGrad", _pack_apply_req(op.ids, grads)
            if stamp_deadline and deadline_ms is not None:
                if stamp_mode == "relative":
                    # v2: remaining budget at ISSUE; the server
                    # arrival-stamps with its own clock (no wall-clock
                    # agreement assumed).  Client-side catch-up lag
                    # already burned part of the budget.
                    req = _pack_deadline_rel(
                        int((due + deadline_ms / 1000.0
                             - time.monotonic()) * 1e6), req)
                else:
                    # absolute wall-clock deadline: scheduled arrival +
                    # budget (NOT issue + budget — an op the pacer
                    # issued late has already burned part of its
                    # budget queueing client-side)
                    req = _pack_deadline(
                        int((wall0 + op.t_us / 1e6
                             + deadline_ms / 1000.0) * 1e6), req)
            op_ch = chs.pick(i)
            t_issue = time.monotonic()
            try:
                pc = op_ch.call_async(service, method, req,
                                      timeout_ms=call_timeout)
            except rpc.RpcError as e:
                _record(False, e.code, t_issue - due, 0.0)
                continue
            # collector-pool registry: every queued PendingCall is
            # joined by exactly one collector before the run returns
            inflight.append((due, t_issue, method, req, 0, op_ch, pc))  # lint: allow-handle-escape
        pacing_done.set()

    def collector() -> None:
        while True:
            try:
                due, t_issue, method, req, tries, op_ch, pc = \
                    inflight.popleft()
            except IndexError:
                if pacing_done.is_set() and not inflight:
                    return
                time.sleep(0.001)
                continue
            try:
                pc.join()
                ok, code = True, 0
            except rpc.RpcError as e:
                ok, code = False, e.code
            end = time.monotonic()
            if not ok and code == 2004 and tries < retry_on_limit and \
                    deadline_ms is not None and \
                    (due + deadline_ms / 1000.0) - end \
                    > 2 * limit_backoff_ms / 1000.0:
                # ELIMIT with budget left: MANDATORY backoff, then one
                # more leg (the resilience-tier retry contract) —
                # sojourn keeps accruing from the original arrival
                time.sleep(limit_backoff_ms / 1000.0)
                try:
                    pc2 = op_ch.call_async(service, method, req,
                                           timeout_ms=call_timeout)
                except rpc.RpcError as e:
                    _record(False, e.code, time.monotonic() - due, 0.0)
                    continue
                inflight.append((due, t_issue, method, req,  # lint: allow-handle-escape
                                 tries + 1, op_ch, pc2))
                continue
            _record(ok, code, end - due, end - t_issue)

    threads = [threading.Thread(target=pacer, name="press-pacer")]
    threads += [threading.Thread(target=collector,
                                 name=f"press-collect{i}")
                for i in range(max(1, collectors))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.monotonic() - start

    with res_mu:
        done = list(results)
    n = len(done)
    ok_sojourns = sorted(s for ok, _, s, _ in done if ok)
    ok_services = sorted(sv for ok, _, _, sv in done if ok)
    errors: Dict[str, int] = {}
    for ok, code, _, _ in done:
        if not ok:
            errors[str(code)] = errors.get(str(code), 0) + 1
    n_ok = len(ok_sojourns)
    in_deadline = n_ok if deadline_ms is None else sum(
        1 for s in ok_sojourns if s * 1000.0 <= deadline_ms)
    offered_qps = len(ops) / max(wall_s, 1e-9)
    report = {
        "n": n,
        "ok": n_ok,
        "errors": errors,
        "availability": round(n_ok / n, 4) if n else 0.0,
        "goodput_qps": round(in_deadline / max(wall_s, 1e-9), 1),
        "offered_qps": round(offered_qps, 1),
        "p50_ms": round(_percentile(ok_sojourns, 0.50) * 1e3, 3),
        "p99_ms": round(_percentile(ok_sojourns, 0.99) * 1e3, 3),
        "p50_service_ms": round(_percentile(ok_services, 0.50) * 1e3, 3),
        "p99_service_ms": round(_percentile(ok_services, 0.99) * 1e3, 3),
        "duration_s": round(wall_s, 3),
        "deadline_ms": deadline_ms,
        "stamped": bool(stamp_deadline and deadline_ms is not None),
        "stamp_mode": stamp_mode,
        "channels": len(chs),
    }
    if obs.enabled():
        obs.counter("press_ops").add(n)
        obs.counter("press_errors").add(n - n_ok)
    chs.close()
    return report


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m brpc_tpu.press",
        description="Scenario load harness (rpc_press/rpc_replay "
                    "analog) for the PS fabric")
    parser.add_argument("--target", help="shard server ip:port (omit "
                                         "with --record to only write "
                                         "a trace)")
    parser.add_argument("--vocab", type=int, default=1024)
    parser.add_argument("--dim", type=int, default=32)
    parser.add_argument("--qps", type=float, default=200.0)
    parser.add_argument("--duration", type=float, default=2.0)
    parser.add_argument("--batch", type=int, default=16)
    parser.add_argument("--read-fraction", type=float, default=1.0)
    parser.add_argument("--zipf", type=float, default=0.0)
    parser.add_argument("--burst-qps", type=float, default=0.0)
    parser.add_argument("--burst-every", type=float, default=0.0)
    parser.add_argument("--burst-len", type=float, default=0.0)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--deadline-ms", type=float)
    parser.add_argument("--stamp", action="store_true",
                        help="propagate the deadline header so the "
                             "server sheds expired work")
    parser.add_argument("--stamp-mode", choices=("absolute",
                                                 "relative"),
                        default="absolute",
                        help="deadline header form: absolute "
                             "wall-clock us (v1) or relative budget "
                             "with server-side arrival stamp (v2)")
    parser.add_argument("--channels", type=int, default=1,
                        help="native connections to pace over "
                             "round-robin (raises the open-loop "
                             "client ceiling on multi-core hosts)")
    parser.add_argument("--record", metavar="FILE",
                        help="write the generated op stream to FILE")
    parser.add_argument("--replay", metavar="FILE",
                        help="replay a recorded trace instead of "
                             "generating")
    args = parser.parse_args(argv)

    if args.replay:
        meta, ops = load_trace(args.replay)
        vocab = meta["vocab"] or args.vocab
        dim = meta["dim"] or args.dim
    else:
        sc = Scenario(duration_s=args.duration, qps=args.qps,
                      batch=args.batch,
                      read_fraction=args.read_fraction,
                      zipf_s=args.zipf, burst_qps=args.burst_qps,
                      burst_every_s=args.burst_every,
                      burst_len_s=args.burst_len, seed=args.seed)
        ops = build_ops(sc, args.vocab)
        vocab, dim = args.vocab, args.dim
    if args.record:
        save_trace(args.record, ops, seed=args.seed, vocab=vocab,
                   dim=dim)
        print(f"recorded {len(ops)} op(s) to {args.record}")
        if not args.target:
            return 0
    if not args.target:
        parser.error("--target is required unless only --record is "
                     "given")
    report = run_press(args.target, ops, dim,
                       deadline_ms=args.deadline_ms,
                       stamp_deadline=args.stamp,
                       stamp_mode=args.stamp_mode,
                       channels=args.channels)
    print(json.dumps(report))
    return 0


if __name__ == "__main__":  # pragma: no cover
    import sys
    sys.exit(main())
