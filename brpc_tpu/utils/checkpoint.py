"""Checkpoint / resume for sharded training state.

The reference has no model checkpointing (SURVEY.md §5.5 — its nearest
analogs are rpc_dump's recordio capture and rpcz's LevelDB); this is the
new scope the TPU build adds: async, sharding-preserving checkpoints of the
(params, opt_state, step) pytree via orbax, restoring onto any mesh (orbax
re-shards on load).
"""

from __future__ import annotations

import os
from typing import Any

import jax
import orbax.checkpoint as ocp


def _manager(ckpt_dir: str, max_to_keep: int = 3) -> ocp.CheckpointManager:
    return ocp.CheckpointManager(
        os.path.abspath(ckpt_dir),
        options=ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep, create=True
        ),
    )


def save_checkpoint(ckpt_dir: str, step: int, state: Any,
                    max_to_keep: int = 3, blocking: bool = True) -> None:
    """Saves a pytree (arrays keep their shardings). ``state`` is any
    pytree: {'params': ..., 'opt_state': ..., ...}."""
    mgr = _manager(ckpt_dir, max_to_keep)
    mgr.save(step, args=ocp.args.StandardSave(state))
    if blocking:
        mgr.wait_until_finished()
    mgr.close()


def latest_step(ckpt_dir: str) -> int | None:
    if not os.path.isdir(ckpt_dir):
        return None
    mgr = _manager(ckpt_dir)
    step = mgr.latest_step()
    mgr.close()
    return step


def restore_checkpoint(ckpt_dir: str, step: int | None = None,
                       template: Any = None) -> Any:
    """Restores the pytree saved at ``step`` (default: latest). With
    ``template`` (a pytree of like-shaped, possibly-sharded arrays), the
    restore re-shards onto the template's layout."""
    mgr = _manager(ckpt_dir)
    if step is None:
        step = mgr.latest_step()
        if step is None:
            mgr.close()
            raise FileNotFoundError(f"no checkpoints in {ckpt_dir}")
    if template is not None:
        restored = mgr.restore(
            step,
            args=ocp.args.StandardRestore(template),
        )
    else:
        restored = mgr.restore(step)
    mgr.close()
    return restored
