"""Server-side overload control: per-method adaptive concurrency
limiting.

The reference treats overload as a first-class server concern: every
method carries a ``MethodStatus`` whose ``OnRequested`` consults a
pluggable ``ConcurrencyLimiter`` — ``constant`` (a fixed
max_concurrency) or ``auto`` (the gradient/Vegas adaptive policy,
policy/auto_concurrency_limiter.cpp + docs/cn/auto_concurrency_limiter.md)
— and a request refused there answers ``ELIMIT`` (2004) WITHOUT touching
the handler (SURVEY §2.6).  This module is the Python tier's port,
mirrored field-for-field from the native scaffold
(``cpp/rpc/concurrency_limiter.h``) so both tiers shed by the same
policy:

- :class:`ConstantLimiter` — admit while inflight <= max.
- :class:`AutoLimiter` — sampled response windows estimate a no-load
  latency floor (EMA downward) and a peak qps (jump up, decay slowly);
  Little's law (``floor_latency x peak_qps``) times an explore ratio
  that widens while latency hugs the floor and narrows under queueing
  sets the limit; a randomized remeasure interval periodically pulls
  load down and re-measures the floor; an all-failed window halves the
  limit.  The clock is injectable (``clock_us``) so the whole state
  machine is testable without wall time.
- :class:`MethodGate` — one method's inflight counter + limiter + shed
  accounting: the ``MethodStatus::OnRequested`` analog the server
  trampolines call around every dispatch.
- :class:`ServerLimiter` — the per-method gate map a server installs
  (``rpc.Server.set_concurrency_limiter``); gates are created lazily
  per method (or restricted to an explicit method list), and every
  shed feeds ``<prefix>_shed`` / ``<prefix>_shed_<Method>`` counters
  so rejected traffic shows up in ``_status`` instead of vanishing.

The client-side story (mandatory backoff on ``ELIMIT``, breaker feeding
so sustained shedding trips the redirect path, deadline stamping) lives
in :mod:`brpc_tpu.resilience` / :mod:`brpc_tpu.ps_remote`; the traffic
harness that proves the whole loop is :mod:`brpc_tpu.press`.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, Optional, Sequence

from brpc_tpu import obs
from brpc_tpu.analysis.race import checked_lock

__all__ = [
    "ELIMIT", "ConcurrencyLimiter", "ConstantLimiter", "AutoLimiter",
    "AutoOptions", "MethodGate", "ServerLimiter", "make_limiter",
]

#: concurrency limit reached (native errors.h) — the shed answer;
#: retriable WITH mandatory backoff (brpc_tpu.resilience.RetryPolicy)
ELIMIT = 2004
#: deadline budget exhausted before the handler ran (EDEADLINE) —
#: shed outcomes are not a load signal, the limiter ignores both
_EDEADLINE = 2014


def _monotonic_us() -> int:
    return time.monotonic_ns() // 1000


class ConcurrencyLimiter:
    """Admission policy: ``on_requested(current)`` is consulted with the
    would-be inflight count (the caller has already incremented);
    ``on_responded`` feeds one completed request back."""

    def on_requested(self, current: int) -> bool:
        raise NotImplementedError

    def on_responded(self, error_code: int, latency_us: int) -> None:
        pass

    @property
    def max_concurrency(self) -> int:
        raise NotImplementedError


class ConstantLimiter(ConcurrencyLimiter):
    """Fixed ceiling (reference ``constant`` policy): ``max <= 0`` means
    unlimited (the off mode kept constructible for config tables)."""

    def __init__(self, max_concurrency: int):
        self._max = int(max_concurrency)

    def on_requested(self, current: int) -> bool:
        return self._max <= 0 or current <= self._max

    @property
    def max_concurrency(self) -> int:
        return self._max


@dataclasses.dataclass(frozen=True)
class AutoOptions:
    """Mirrors ``AutoLimiter::Options`` in
    cpp/rpc/concurrency_limiter.h (reference defaults,
    policy/auto_concurrency_limiter.cpp)."""

    initial_limit: int = 40          # warm-up ceiling
    min_limit: int = 4
    window_us: int = 1_000_000       # sample window duration
    min_samples: int = 20            # discard smaller windows
    max_samples: int = 200           # close early past this
    sample_interval_us: int = 100    # <=1 sample per interval
    ema_alpha: float = 0.1           # latency-floor smoothing
    max_explore: float = 0.3
    min_explore: float = 0.06
    explore_step: float = 0.02
    fail_punish: float = 1.0         # failed-latency weight
    remeasure_interval_us: int = 50 * 1_000_000
    remeasure_reduce: float = 0.9


class AutoLimiter(ConcurrencyLimiter):
    """Gradient/Vegas adaptive limiter — the Python twin of the native
    ``AutoLimiter`` (cpp/rpc/concurrency_limiter.h), same estimator,
    same windows, with an injectable microsecond clock so tests drive
    the state machine deterministically.

    The loop: responses are SAMPLED (at most one per
    ``sample_interval_us``) into a window that closes after
    ``window_us`` or ``max_samples`` and is discarded below
    ``min_samples``.  Each closed window updates a no-load latency
    floor (EMA, downward only) and a peak-qps estimate (jump up, decay
    slowly), then sets ``limit = floor_latency x peak_qps x
    (1 + explore)`` — Little's law with an explore ratio that walks up
    while the window's latency stays near the floor (probe for more)
    and down under queueing.  Periodically (randomized in [T/2, T)) the
    limit is pulled to ``remeasure_reduce x`` the estimate and the
    floor is re-measured at the resulting low load.  An all-failed
    window halves the limit.  Shed outcomes (``ELIMIT``/``EDEADLINE``)
    are the limiter's OWN output and never enter the estimator."""

    def __init__(self, options: Optional[AutoOptions] = None,
                 clock_us: Callable[[], int] = _monotonic_us):
        self.opt = options or AutoOptions()
        self._clock_us = clock_us
        self._limit = int(self.opt.initial_limit)
        self._explore = self.opt.max_explore
        self._mu = checked_lock("limiter.auto")
        self._last_sample_us = 0
        self._total_succ = 0
        self._win_start_us = 0
        self._win_succ = 0
        self._win_fail = 0
        self._win_succ_lat_us = 0
        self._win_fail_lat_us = 0
        self._min_latency_us = -1
        self._ema_max_qps = -1.0
        self._reset_at_us = 0
        self._remeasure_at_us = self._next_remeasure(clock_us())

    # -- admission (lock-free read: a stale limit admits/refuses one
    # request late, same contract as the native atomics) ---------------

    def on_requested(self, current: int) -> bool:
        return current <= self._limit

    @property
    def max_concurrency(self) -> int:
        return self._limit

    # -- feedback ------------------------------------------------------

    def on_responded(self, error_code: int, latency_us: int) -> None:
        if error_code in (ELIMIT, _EDEADLINE):
            return  # our own sheds are not a load signal
        now = self._clock_us()
        with self._mu:
            if error_code == 0:
                self._total_succ += 1
            # sampling interval: at most one response per interval
            # enters the window (bounds estimator work at high qps)
            if self._last_sample_us != 0 and \
                    now - self._last_sample_us < \
                    self.opt.sample_interval_us:
                return
            self._last_sample_us = now
            self._add_sample_locked(error_code, latency_us, now)

    # -- estimator (all under the lock) --------------------------------

    def _next_remeasure(self, now: int) -> int:
        # randomized in [T/2, T): herds of servers must not re-probe in
        # sync (the reference uses the same now-derived jitter)
        half = self.opt.remeasure_interval_us // 2
        return now + half + (now % (half if half > 0 else 1))

    def _add_sample_locked(self, error_code: int, latency_us: int,
                           now: int) -> None:
        if self._reset_at_us != 0:
            if self._reset_at_us > now:
                return  # draining to low load: ignore
            # low load reached: re-measure the floor from scratch
            self._min_latency_us = -1
            self._reset_at_us = 0
            self._remeasure_at_us = self._next_remeasure(now)
            self._reset_window(now)
        if self._win_start_us == 0:
            self._win_start_us = now
        if error_code != 0:
            self._win_fail += 1
            self._win_fail_lat_us += latency_us
        else:
            self._win_succ += 1
            self._win_succ_lat_us += latency_us
        n = self._win_succ + self._win_fail
        if n < self.opt.min_samples:
            if now - self._win_start_us >= self.opt.window_us:
                self._reset_window(now)
            return  # window too small (yet)
        if now - self._win_start_us < self.opt.window_us and \
                n < self.opt.max_samples:
            return  # window still open
        if self._win_succ > 0:
            self._update(now)
        else:
            self._set_limit(self._limit // 2)  # all failed
        self._reset_window(now)

    def _reset_window(self, now: int) -> None:
        self._total_succ = 0
        self._win_start_us = now
        self._win_succ = self._win_fail = 0
        self._win_succ_lat_us = self._win_fail_lat_us = 0

    def _set_limit(self, v: int) -> None:
        self._limit = max(self.opt.min_limit, int(v))

    def _update(self, now: int) -> None:
        punished = (float(self._win_fail_lat_us) * self.opt.fail_punish
                    + float(self._win_succ_lat_us))
        avg_lat = int(punished / float(self._win_succ)) + 1
        elapsed = max(1, now - self._win_start_us)
        qps = 1e6 * float(self._total_succ) / float(elapsed)
        # latency floor: EMA downward only
        if self._min_latency_us <= 0:
            self._min_latency_us = avg_lat
        elif avg_lat < self._min_latency_us:
            self._min_latency_us = int(
                float(avg_lat) * self.opt.ema_alpha
                + float(self._min_latency_us) * (1 - self.opt.ema_alpha))
        # peak qps: jump up, decay slowly
        if qps >= self._ema_max_qps:
            self._ema_max_qps = qps
        else:
            a = self.opt.ema_alpha / 10
            self._ema_max_qps = qps * a + self._ema_max_qps * (1 - a)
        if self._remeasure_at_us <= now:
            # pull load down and re-measure the floor once drained
            self._reset_at_us = now + avg_lat * 2
            self._set_limit(int(self._ema_max_qps
                                * float(self._min_latency_us) / 1e6
                                * self.opt.remeasure_reduce) + 1)
            return
        # explore walk: widen while latency hugs the floor (or qps sits
        # below peak — not limit-bound), narrow under queueing
        if float(avg_lat) <= float(self._min_latency_us) \
                * (1.0 + self.opt.min_explore) or \
                qps <= self._ema_max_qps / (1.0 + self.opt.min_explore):
            self._explore = min(self.opt.max_explore,
                                self._explore + self.opt.explore_step)
        else:
            self._explore = max(self.opt.min_explore,
                                self._explore - self.opt.explore_step)
        self._set_limit(int(float(self._min_latency_us)
                            * self._ema_max_qps / 1e6
                            * (1 + self._explore)) + 1)


def make_limiter(spec: Optional[str], *,
                 options: Optional[AutoOptions] = None,
                 clock_us: Callable[[], int] = _monotonic_us
                 ) -> Optional[ConcurrencyLimiter]:
    """Limiter factory over the config vocabulary shared with the
    native tier (``CreateConcurrencyLimiter``): ``"auto"``,
    ``"constant:<n>"``, and ``""``/``"none"``/``None`` → no limiter
    (unlimited).  A bare ``"constant"`` with no bound is the off mode
    too — a constant limiter needs its constant."""
    if spec is None or spec in ("", "none", "off"):
        return None
    if spec == "auto":
        return AutoLimiter(options, clock_us=clock_us)
    if spec.startswith("constant"):
        _, _, arg = spec.partition(":")
        maxc = int(arg) if arg else 0
        return ConstantLimiter(maxc) if maxc > 0 else None
    raise ValueError(f"unknown concurrency limiter spec {spec!r} "
                     f"(want 'auto', 'constant:<n>', or 'none')")


class MethodGate:
    """One method's admission gate: inflight counter + limiter + shed
    accounting (the ``MethodStatus`` analog).  ``admit()`` increments
    inflight and consults the limiter — a refusal decrements back and
    counts one shed; every admitted request must pair with exactly one
    ``on_responded`` carrying the outcome and handler latency."""

    __slots__ = ("method", "limiter", "_mu", "_inflight", "_shed",
                 "_prefix")

    def __init__(self, method: str, limiter: ConcurrencyLimiter,
                 counter_prefix: str = "rpc_server"):
        self.method = method
        self.limiter = limiter
        self._mu = checked_lock("limiter.gate")
        self._inflight = 0
        self._shed = 0
        self._prefix = counter_prefix

    def admit(self) -> bool:
        with self._mu:
            self._inflight += 1
            c = self._inflight
        if self.limiter.on_requested(c):
            return True
        with self._mu:
            self._inflight -= 1
            self._shed += 1
        if obs.enabled():
            obs.counter(f"{self._prefix}_shed").add(1)
            obs.counter(f"{self._prefix}_shed_{self.method}").add(1)
        return False

    def on_responded(self, error_code: int, latency_us: int) -> None:
        with self._mu:
            self._inflight -= 1
        self.limiter.on_responded(error_code, latency_us)

    @property
    def inflight(self) -> int:
        return self._inflight

    @property
    def shed(self) -> int:
        return self._shed

    @property
    def max_concurrency(self) -> int:
        return self.limiter.max_concurrency


class ServerLimiter:
    """The per-method gate map a server enforces (installed via
    ``rpc.Server.set_concurrency_limiter``).

    ``spec`` names the policy (``"auto"`` / ``"constant:<n>"``); each
    method gets its OWN limiter instance (per-method limiting, the
    reference's ``MethodStatus`` shape) created lazily on first
    dispatch — or restricted to ``methods`` when given, leaving
    everything else ungated (the PS servers gate the data plane and
    leave failover/migration control traffic admissible under
    overload).  ``counter_prefix`` names the shed counters
    (``ps_shed[_<Method>]`` on the shard servers)."""

    def __init__(self, spec: str = "auto", *,
                 methods: Optional[Sequence[str]] = None,
                 options: Optional[AutoOptions] = None,
                 clock_us: Callable[[], int] = _monotonic_us,
                 counter_prefix: str = "rpc_server"):
        make_limiter(spec, options=options, clock_us=clock_us)  # validate
        self.spec = spec
        self._options = options
        self._clock_us = clock_us
        self._methods = frozenset(methods) if methods is not None else None
        self._prefix = counter_prefix
        self._mu = checked_lock("limiter.server")
        self._gates: Dict[str, MethodGate] = {}

    def gate(self, method: str) -> Optional[MethodGate]:
        """The gate for ``method`` (None = ungated).  Lazy creation is
        double-checked so the steady state is one dict hit."""
        g = self._gates.get(method)
        if g is not None:
            return g
        if self._methods is not None and method not in self._methods:
            return None
        with self._mu:
            g = self._gates.get(method)
            if g is None:
                lim = make_limiter(self.spec, options=self._options,
                                   clock_us=self._clock_us)
                if lim is None:
                    return None
                g = MethodGate(method, lim, self._prefix)
                self._gates[method] = g
        return g

    def total_inflight(self) -> int:
        """Live admitted requests across every gate (the
        ``ps_inflight`` PassiveStatus)."""
        return sum(g.inflight for g in list(self._gates.values()))

    def max_concurrency(self) -> Dict[str, int]:
        """Current per-method limit (the adaptive gauge)."""
        return {m: g.max_concurrency
                for m, g in sorted(self._gates.items())}

    def snapshot(self) -> Dict[str, Dict[str, int]]:
        return {m: {"inflight": g.inflight, "shed": g.shed,
                    "max_concurrency": g.max_concurrency}
                for m, g in sorted(self._gates.items())}
