"""tpu_ps — parameter-server fabric: sharded embedding serving + grad sync.

The BASELINE north-star app: "bRPC param-server serving Llama-3-8B embedding
shards, allreduce grads over v5e-16".  The reference reaches this shape with
PartitionChannel (shard-addressed calls, src/brpc/partition_channel.h:75)
plus ParallelChannel fan-out for reduction (SURVEY.md §2.7).  TPU-native,
the intra-pod tier compiles to collectives:

- the embedding table lives row-sharded over a 'ps' mesh axis (the
  PartitionChannel "i/N" tag == the mesh coordinate);
- ``lookup`` is the shard-addressed read: every shard gathers its local
  rows, a psum merges (exactly one shard owns each row);
- ``apply_gradients`` is the sharded write: scatter-add lands on the owning
  shard only — no cross-shard traffic beyond the ids broadcast;
- worker gradient sync is CollectiveChannel.all_reduce over 'dp'.

The cross-host / DCN tier (many pods) runs the same contract over the
native RPC PartitionChannel (cpp/cluster/partition_channel.*).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from brpc_tpu._compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class EmbeddingShards(NamedTuple):
    """A [vocab, dim] table row-sharded over ``axis``.

    Registered as a pytree with (vocab, dim, axis) static so instances pass
    straight through jit/grad.
    """

    table: jax.Array
    vocab: int
    dim: int
    axis: str


jax.tree_util.register_pytree_node(
    EmbeddingShards,
    lambda e: ((e.table,), (e.vocab, e.dim, e.axis)),
    lambda aux, children: EmbeddingShards(children[0], *aux),
)


def create_embedding(
    key: jax.Array,
    vocab: int,
    dim: int,
    mesh: Mesh,
    axis: str = "ps",
    scale: float = 0.02,
    dtype=jnp.float32,
) -> EmbeddingShards:
    if vocab % mesh.shape[axis] != 0:
        raise ValueError(
            f"vocab {vocab} not divisible by {axis}={mesh.shape[axis]}"
        )
    table = jax.random.normal(key, (vocab, dim), dtype) * scale
    table = jax.device_put(table, NamedSharding(mesh, P(axis, None)))
    return EmbeddingShards(table, vocab, dim, axis)


def lookup(emb: EmbeddingShards, ids: jax.Array, mesh: Mesh) -> jax.Array:
    """Shard-addressed read: ids [...] -> rows [..., dim].

    Every shard contributes its owned rows (zeros elsewhere); one psum
    merges — the PartitionChannel broadcast-read with additive merger.
    """
    axis = emb.axis
    n = mesh.shape[axis]
    rows_per = emb.vocab // n

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis, None), P()),
        out_specs=P(),
        check_vma=False,
    )
    def _lookup(shard, flat_ids):
        base = lax.axis_index(axis) * rows_per
        local = flat_ids - base
        mine = (local >= 0) & (local < rows_per)
        safe = jnp.clip(local, 0, rows_per - 1)
        got = shard[safe]  # [N, dim]
        got = jnp.where(mine[:, None], got, 0)
        return lax.psum(got, axis)

    flat = ids.reshape(-1)
    out = _lookup(emb.table, flat)
    return out.reshape(*ids.shape, emb.dim)


def apply_gradients(
    emb: EmbeddingShards,
    ids: jax.Array,
    grads: jax.Array,
    mesh: Mesh,
    lr: float = 1e-2,
) -> EmbeddingShards:
    """Sharded write: scatter-add -lr*grads onto owning shards only."""
    axis = emb.axis
    n = mesh.shape[axis]
    rows_per = emb.vocab // n

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis, None), P(), P()),
        out_specs=P(axis, None),
        check_vma=False,
    )
    def _apply(shard, flat_ids, flat_grads):
        base = lax.axis_index(axis) * rows_per
        local = flat_ids - base
        mine = (local >= 0) & (local < rows_per)
        safe = jnp.where(mine, local, 0)
        contrib = jnp.where(mine[:, None], flat_grads, 0)
        return shard.at[safe].add(-lr * contrib)

    flat_ids = ids.reshape(-1)
    flat_grads = grads.reshape(-1, emb.dim)
    new_table = _apply(emb.table, flat_ids, flat_grads)
    return emb._replace(table=new_table)


def make_ps_train_step(emb_axis: str, dp_axis: str, mesh: Mesh, lr: float):
    """The BASELINE #5 loop: embedding lookup → toy loss → grad allreduce
    over dp → sharded embedding update. Returns a jittable step:
    (EmbeddingShards, ids [B,T], targets [B,T,dim]) -> (EmbeddingShards, loss).

    ids/targets are replicated here (each dp worker's slice handled by the
    caller's batch sharding); the demonstrative loss is MSE to targets.
    """

    def step(emb: EmbeddingShards, ids, targets):
        def loss_fn(table):
            e = emb._replace(table=table)
            pred = lookup(e, ids, mesh)
            return jnp.mean((pred - targets) ** 2)

        loss, grad_rows = jax.value_and_grad(
            lambda table: loss_fn(table)
        )(emb.table)
        # grad wrt the full table; turn into per-id dense grads via lookup
        # of the gradient rows — cheaper path: direct sharded SGD on the
        # table gradient (already laid out like the table).
        new_table = emb.table - lr * grad_rows
        return emb._replace(table=new_table), loss

    return step
