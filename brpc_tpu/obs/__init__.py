"""brpc_tpu.obs — observability: bvar-style metrics + rpcz tracing.

Two layers, both pure Python/numpy (no native build required):

- :mod:`brpc_tpu.obs.vars` — the metrics core: ``Adder``/``Maxer``/
  ``Miner`` thread-local-agent reducers, ``PassiveStatus``, ``Window`` /
  ``PerSecond`` time-windowed views, ``LatencyRecorder`` (count/qps/avg +
  log-bucket percentiles), and a global ``Registry`` behind
  ``expose`` / ``dump_exposed`` (the /vars page).
- :mod:`brpc_tpu.obs.rpcz` — per-call ``Span`` records in a bounded ring
  (``dump_rpcz``, the /rpcz page) plus a ``span(...)`` context manager
  for user code.

The RPC/PS fabric (``brpc_tpu.rpc``, ``brpc_tpu.ps_remote``,
``brpc_tpu.parallel.collective_channel``) is instrumented through the
cached helpers here (:func:`recorder`, :func:`counter`); every hook
checks :func:`enabled` first and degrades to a no-op when observability
is switched off (``set_enabled(False)`` or env
``BRPC_TPU_OBS=0``).  ``Server.add_status_service()`` serves both dumps
over the RPC fabric itself so a remote ``Channel`` can scrape any node
(:mod:`brpc_tpu.obs.status_service`).
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Tuple

from brpc_tpu.analysis.race import checked_lock

from brpc_tpu.obs.vars import (  # noqa: F401
    Adder,
    LatencyRecorder,
    Maxer,
    Miner,
    PassiveStatus,
    PerSecond,
    Registry,
    Variable,
    Window,
    default_registry,
    dump_exposed,
    dump_exposed_dict,
    expose,
)
from brpc_tpu.obs.rpcz import (  # noqa: F401
    Span,
    SpanRing,
    default_ring,
    dump_rpcz,
    format_rpcz,
    record_span,
    span,
)

__all__ = [
    # vars
    "Adder", "Maxer", "Miner", "PassiveStatus", "Window", "PerSecond",
    "LatencyRecorder", "Registry", "Variable", "default_registry",
    "expose", "dump_exposed", "dump_exposed_dict",
    # rpcz
    "Span", "SpanRing", "default_ring", "dump_rpcz", "format_rpcz",
    "record_span", "span",
    # gate + cached fabric helpers
    "enabled", "set_enabled", "recorder", "counter", "maxer", "gauge",
    "drop_var", "reset_fabric_vars",
]

_enabled = os.environ.get("BRPC_TPU_OBS", "1") not in ("0", "false", "off")


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> None:
    """Global observability switch; instrumentation hooks become no-ops
    when off (they check this before touching any recorder)."""
    global _enabled
    _enabled = bool(on)


# Cached, auto-exposed fabric variables.  Instrumented call sites resolve
# their recorder by name on every call; the dict hit is the steady-state
# cost, and creation (+ expose) happens once per distinct name.
_fabric_mu = checked_lock("obs.fabric")
_recorders: Dict[str, LatencyRecorder] = {}
_counters: Dict[str, Adder] = {}
_maxers: Dict[str, Maxer] = {}
_gauges: Dict[str, PassiveStatus] = {}


def recorder(name: str, window_size: int = 10) -> LatencyRecorder:
    """The process-wide LatencyRecorder exposed under ``name``."""
    rec = _recorders.get(name)
    if rec is None:
        with _fabric_mu:
            rec = _recorders.get(name)
            if rec is None:
                rec = LatencyRecorder(window_size=window_size)
                rec.expose(name)
                _recorders[name] = rec
    return rec


def counter(name: str) -> Adder:
    """The process-wide Adder exposed under ``name``."""
    c = _counters.get(name)
    if c is None:
        with _fabric_mu:
            c = _counters.get(name)
            if c is None:
                c = Adder()
                c.expose(name)
                _counters[name] = c
    return c


def maxer(name: str) -> Maxer:
    """The process-wide Maxer exposed under ``name`` (high-water marks:
    combine-queue depth, window occupancy)."""
    m = _maxers.get(name)
    if m is None:
        with _fabric_mu:
            m = _maxers.get(name)
            if m is None:
                m = Maxer()
                m.expose(name)
                _maxers[name] = m
    return m


def gauge(name: str, fn: Callable[[], object]) -> PassiveStatus:
    """Exposes (or replaces) a :class:`PassiveStatus` under ``name`` —
    a value computed on read (live inflight, the adaptive limiter's
    current max_concurrency).  Components with a lifetime (a shard
    server's overload gauges) pair this with :func:`drop_var` at
    teardown."""
    g = PassiveStatus(fn)
    with _fabric_mu:
        g.expose(name)
        _gauges[name] = g
    return g


def drop_var(name: str) -> None:
    """Hide one fabric variable (any kind) and drop its cache entry —
    the teardown half of per-component gauges."""
    with _fabric_mu:
        default_registry().hide(name)
        _recorders.pop(name, None)
        _counters.pop(name, None)
        _maxers.pop(name, None)
        _gauges.pop(name, None)


def reset_fabric_vars() -> None:
    """Drop all cached fabric recorders/counters and their registry
    entries (test isolation)."""
    with _fabric_mu:
        for name in list(_recorders) + list(_counters) + list(_maxers) \
                + list(_gauges):
            default_registry().hide(name)
        _recorders.clear()
        _counters.clear()
        _maxers.clear()
        _gauges.clear()
