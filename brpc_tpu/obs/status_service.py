"""The ``_status`` builtin service: /vars and /rpcz served over the RPC
fabric itself (reference src/brpc/builtin/ — every bRPC server ships its
introspection pages on its own port; here they ride the same brt_std
framing as user services, so any ``Channel`` can scrape any node).

Wire mapping (payloads are UTF-8/JSON, like the naming bridge):

- ``vars``       req = optional filter string → rsp = ``/vars`` text dump
- ``vars_json``  req = optional filter string → rsp = JSON object
- ``rpcz``       req = optional JSON query {limit, service, method, side,
                 errors_only} → rsp = JSON list of span dicts (newest
                 first)
- ``rpcz_text``  same query → rsp = one-line-per-span text
- ``health``     empty req → ``ok`` (the plain liveness probe the
                 resilience tier's HealthProber and the reference's
                 health checker use); any non-empty req (convention:
                 ``full``) → JSON per-component health — circuit-breaker
                 states per endpoint, last probe results, racecheck/obs
                 gates (``brpc_tpu.resilience.health_components``)

Registered via ``rpc.Server.add_status_service()``; client side via
:func:`scrape_vars` / :func:`scrape_rpcz` over an existing ``Channel``.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from brpc_tpu.obs import rpcz, vars as obs_vars

SERVICE_NAME = "_status"


def _parse_query(payload: bytes) -> dict:
    if not payload:
        return {}
    q = json.loads(payload.decode())
    if not isinstance(q, dict):
        raise ValueError("rpcz query must be a JSON object")
    allowed = {"limit", "service", "method", "side", "errors_only"}
    unknown = set(q) - allowed
    if unknown:
        raise ValueError(f"unknown rpcz query keys: {sorted(unknown)}")
    return q


def make_status_handler(registry: "Optional[obs_vars.Registry]" = None,
                        ring: "Optional[rpcz.SpanRing]" = None):
    """Returns ``fn(method, request) -> bytes`` for ``Server.add_service``."""
    reg = registry or obs_vars.default_registry()
    # an empty SpanRing is falsy (__len__), so test identity, not truth
    rng = rpcz.default_ring() if ring is None else ring

    def handler(method: str, request: bytes) -> bytes:
        if method == "health":
            if not request:
                return b"ok"  # plain probes keep the bare contract
            # resilience imports obs; this hook runs lazily so the
            # dependency stays one-way at import time
            from brpc_tpu import resilience
            return json.dumps(resilience.health_components()).encode()
        if method == "vars":
            return reg.dump_exposed(request.decode() or None).encode()
        if method == "vars_json":
            return json.dumps(
                reg.dump_exposed_dict(request.decode() or None)).encode()
        if method in ("rpcz", "rpcz_text"):
            q = _parse_query(request)
            spans = rng.dump(limit=int(q.get("limit", 50)),
                             service=q.get("service"),
                             method=q.get("method"),
                             side=q.get("side"),
                             errors_only=bool(q.get("errors_only", False)))
            if method == "rpcz_text":
                return rpcz.format_rpcz(spans).encode()
            return json.dumps(spans).encode()
        raise ValueError(f"unknown _status method {method}")

    return handler


# ---- client side: scrape a remote node over an existing Channel ----

def scrape_health(channel, full: bool = False):
    """Remote health: the bare ``"ok"`` string, or the structured
    per-component dict with ``full=True``."""
    if not full:
        return channel.call(SERVICE_NAME, "health").decode()
    raw = channel.call(SERVICE_NAME, "health", b"full")
    return json.loads(raw.decode())

def scrape_vars(channel, filter: str = "", json_form: bool = False):
    """Remote ``dump_exposed``: text by default, dict with json_form."""
    if json_form:
        raw = channel.call(SERVICE_NAME, "vars_json", filter.encode())
        return json.loads(raw.decode())
    return channel.call(SERVICE_NAME, "vars", filter.encode()).decode()


def scrape_rpcz(channel, limit: int = 50, service: Optional[str] = None,
                method: Optional[str] = None, side: Optional[str] = None,
                errors_only: bool = False) -> List[Dict[str, object]]:
    """Remote ``dump_rpcz``: newest-first span dicts from the peer."""
    q = {"limit": limit, "errors_only": errors_only}
    if service is not None:
        q["service"] = service
    if method is not None:
        q["method"] = method
    if side is not None:
        q["side"] = side
    raw = channel.call(SERVICE_NAME, "rpcz", json.dumps(q).encode())
    return json.loads(raw.decode())
