"""rpcz-style per-call tracing (reference src/brpc/builtin/rpcz_service.cpp,
src/brpc/span.cpp).

Every instrumented call — client-side ``Channel.call``, server-side
handler dispatch, PS lookups, user code under ``span(...)`` — appends one
``Span`` to a bounded ring buffer.  ``dump_rpcz`` answers the /rpcz
queries: most-recent-first, filterable by service/method/side/errors.
The ring is deliberately small and lossy: under heavy traffic old spans
fall off the back, which is exactly the reference's behaviour (rpcz keeps
a time-bounded window, not a full log).
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from collections import deque
from typing import Dict, List, Optional

from brpc_tpu.analysis.race import checked_lock

__all__ = ["Span", "SpanRing", "default_ring", "record_span", "span",
           "dump_rpcz", "set_capacity", "clear"]

DEFAULT_CAPACITY = 1024


@dataclasses.dataclass
class Span:
    service: str
    method: str
    side: str = "client"            # "client" | "server" | "user"
    peer: str = ""                  # remote address when known
    request_bytes: int = 0
    response_bytes: int = 0
    start_ns: int = 0               # monotonic ns
    end_ns: int = 0
    wall_time: float = 0.0          # epoch seconds at start (display)
    error_code: int = 0
    error_text: str = ""
    annotations: List[str] = dataclasses.field(default_factory=list)

    @property
    def latency_us(self) -> float:
        return (self.end_ns - self.start_ns) / 1e3

    def annotate(self, text: str) -> None:
        self.annotations.append(text)

    def to_dict(self) -> Dict[str, object]:
        return {
            "service": self.service,
            "method": self.method,
            "side": self.side,
            "peer": self.peer,
            "request_bytes": self.request_bytes,
            "response_bytes": self.response_bytes,
            "start_ns": self.start_ns,
            "end_ns": self.end_ns,
            "wall_time": self.wall_time,
            "latency_us": round(self.latency_us, 3),
            "error_code": self.error_code,
            "error_text": self.error_text,
            "annotations": list(self.annotations),
        }


class SpanRing:
    """Bounded, thread-safe span store."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._mu = checked_lock("obs.rpcz_ring")
        self._ring: deque = deque(maxlen=capacity)

    @property
    def capacity(self) -> int:
        return self._ring.maxlen

    def set_capacity(self, capacity: int) -> None:
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        with self._mu:
            self._ring = deque(self._ring, maxlen=capacity)

    def append(self, s: Span) -> None:
        with self._mu:
            self._ring.append(s)

    def __len__(self) -> int:
        with self._mu:
            return len(self._ring)

    def clear(self) -> None:
        with self._mu:
            self._ring.clear()

    def dump(self, limit: int = 50, service: Optional[str] = None,
             method: Optional[str] = None, side: Optional[str] = None,
             errors_only: bool = False) -> List[Dict[str, object]]:
        """Most-recent-first span dicts matching the filters."""
        with self._mu:
            snapshot = list(self._ring)
        out: List[Dict[str, object]] = []
        for s in reversed(snapshot):
            if service is not None and s.service != service:
                continue
            if method is not None and s.method != method:
                continue
            if side is not None and s.side != side:
                continue
            if errors_only and s.error_code == 0:
                continue
            out.append(s.to_dict())
            if len(out) >= limit:
                break
        return out


_default_ring = SpanRing()


def default_ring() -> SpanRing:
    return _default_ring


def set_capacity(capacity: int) -> None:
    _default_ring.set_capacity(capacity)


def clear() -> None:
    _default_ring.clear()


def record_span(s: Span, ring: Optional[SpanRing] = None) -> None:
    # "ring or _default_ring" would misroute: an EMPTY SpanRing is falsy
    # through __len__.
    (_default_ring if ring is None else ring).append(s)


def dump_rpcz(limit: int = 50, service: Optional[str] = None,
              method: Optional[str] = None, side: Optional[str] = None,
              errors_only: bool = False) -> List[Dict[str, object]]:
    return _default_ring.dump(limit=limit, service=service, method=method,
                              side=side, errors_only=errors_only)


@contextlib.contextmanager
def span(service: str, method: str, side: str = "user", peer: str = "",
         request_bytes: int = 0, ring: Optional[SpanRing] = None):
    """Trace a block of user code:

        with obs.span("Trainer", "step") as sp:
            ...
            sp.annotate("compiled")

    An exception inside the block marks the span failed (code 2001) and
    re-raises; the span is recorded either way.
    """
    s = Span(service=service, method=method, side=side, peer=peer,
             request_bytes=request_bytes, wall_time=time.time(),
             start_ns=time.monotonic_ns())
    try:
        yield s
    except Exception as e:  # noqa: BLE001
        s.error_code = s.error_code or 2001
        s.error_text = s.error_text or str(e)
        raise
    finally:
        s.end_ns = time.monotonic_ns()
        record_span(s, ring)


def format_rpcz(spans: List[Dict[str, object]]) -> str:
    """Text rendering in the /rpcz style, one line per span."""
    lines = []
    for d in spans:
        err = (f" error={d['error_code']}({d['error_text']})"
               if d["error_code"] else "")
        lines.append(
            f"{d['side']:6s} {d['service']}.{d['method']} "
            f"peer={d['peer'] or '-'} req={d['request_bytes']}B "
            f"rsp={d['response_bytes']}B lat={d['latency_us']:.1f}us{err}")
    return "\n".join(lines)
