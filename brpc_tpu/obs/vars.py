"""bvar-semantics metrics core (reference src/bvar/, SURVEY §2.3).

The reference's bvar layer is write-mostly optimized: each writer thread
mutates a thread-local agent with no synchronization, and readers combine
agents on demand (``Reducer::get_value`` walks the agent list).  The same
shape here: ``Adder``/``Maxer``/``Miner`` write to a per-thread cell (a
one-element list — plain attribute stores under the GIL, no lock on the
hot path) and fold across cells on read.

Windowed views (``Window``, ``PerSecond``) mirror bvar's sampler: one
sample per second of the underlying reducer, kept in a bounded deque.
Instead of a sampler thread, samples are taken lazily on read against an
injectable ``clock`` (tests drive a fake clock; production uses
``time.monotonic``).  For invertible ops (Adder) the window value is
``newest - oldest``; for non-invertible ops (Maxer/Miner) each sample is
taken with get-and-reset and the window folds the per-second samples, the
reference's ReducerSampler behaviour for ops without an inverse.

``LatencyRecorder`` is the composite the reference ships for RPC paths:
count, qps, average, max, and p50/p90/p99/p999 from a fixed log-scale
bucket histogram — ``record()`` does one log10 and one slot increment, no
per-sample allocation.

Everything is pure Python + numpy: importable and testable with no native
build present.
"""

from __future__ import annotations

import fnmatch
import itertools
import math
import threading
import time
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from brpc_tpu.analysis.race import checked_lock

__all__ = [
    "Variable", "Adder", "Maxer", "Miner", "PassiveStatus", "Window",
    "PerSecond", "LatencyRecorder", "Registry", "default_registry",
    "expose", "dump_exposed", "dump_exposed_dict",
]


class Variable:
    """Anything dumpable by name (reference src/bvar/variable.h:83)."""

    def get_value(self):
        raise NotImplementedError

    def describe(self) -> str:
        v = self.get_value()
        if isinstance(v, float):
            return f"{v:.6g}"
        return str(v)

    def expose(self, name: str, registry: "Optional[Registry]" = None
               ) -> "Variable":
        (registry or default_registry()).expose(name, self)
        return self


class _TlsReducer(Variable):
    """Thread-local-agent reducer: writes touch only this thread's cell."""

    #: fold across agent cells (and across window samples)
    _OP: Callable = None
    #: value of a cell no thread has written yet
    _IDENTITY = 0
    #: True when _OP has an inverse (window value = newest - oldest)
    _INVERTIBLE = False

    def __init__(self):
        self._local = threading.local()
        self._mu = checked_lock("obs.reducer")
        self._cells: List[list] = []        # all threads' [value] cells
        self._retired = self._IDENTITY      # folded cells of reset() epochs

    def _cell(self) -> list:
        cell = getattr(self._local, "cell", None)
        if cell is None:
            cell = [self._IDENTITY]
            with self._mu:
                self._cells.append(cell)
            self._local.cell = cell
        return cell

    def get_value(self):
        with self._mu:
            acc = self._retired
            for cell in self._cells:
                acc = self._OP(acc, cell[0])
        return acc

    def reset(self):
        """Zero the reducer (best-effort under concurrent writers)."""
        with self._mu:
            self._retired = self._IDENTITY
            for cell in self._cells:
                cell[0] = self._IDENTITY

    def _take_window_sample(self):
        """One per-second sample for Window.

        Invertible ops return the running value (Window subtracts);
        non-invertible ops return value-and-reset (Window folds samples),
        matching the reference sampler split on ``Op::has_inverse``.
        """
        if self._INVERTIBLE:
            return self.get_value()
        with self._mu:
            acc = self._retired
            self._retired = self._IDENTITY
            for cell in self._cells:
                acc = self._OP(acc, cell[0])
                cell[0] = self._IDENTITY
        return acc


class Adder(_TlsReducer):
    """Cumulative sum (bvar::Adder). ``add``/``<<`` are the hot path."""

    _OP = staticmethod(lambda a, b: a + b)
    _IDENTITY = 0
    _INVERTIBLE = True

    def add(self, v=1):
        cell = getattr(self._local, "cell", None) or self._cell()
        cell[0] += v

    def __lshift__(self, v):
        self.add(v)
        return self


class Maxer(_TlsReducer):
    """Running maximum (bvar::Maxer)."""

    _OP = staticmethod(max)
    _IDENTITY = float("-inf")
    _INVERTIBLE = False

    def update(self, v):
        cell = getattr(self._local, "cell", None) or self._cell()
        if v > cell[0]:
            cell[0] = v

    __lshift__ = update

    def get_value(self):
        v = super().get_value()
        return 0 if v == float("-inf") else v


class Miner(_TlsReducer):
    """Running minimum (bvar::Miner)."""

    _OP = staticmethod(min)
    _IDENTITY = float("inf")
    _INVERTIBLE = False

    def update(self, v):
        cell = getattr(self._local, "cell", None) or self._cell()
        if v < cell[0]:
            cell[0] = v

    __lshift__ = update

    def get_value(self):
        v = super().get_value()
        return 0 if v == float("inf") else v


class PassiveStatus(Variable):
    """Value computed on read (bvar::PassiveStatus) — e.g. queue depth."""

    def __init__(self, fn: Callable[[], object]):
        self._fn = fn

    def get_value(self):
        return self._fn()


class Window(Variable):
    """Value of a reducer over the last ``window_size`` seconds.

    Samples lazily on read: every whole second elapsed on ``clock`` since
    the last read pushes one sample.  A read gap longer than the window
    attributes the gap's activity to its final second — the price of not
    running a sampler thread; heavy paths read at least once per dump.
    """

    def __init__(self, reducer: _TlsReducer, window_size: int = 10,
                 clock: Callable[[], float] = time.monotonic):
        if window_size <= 0:
            raise ValueError("window_size must be positive")
        self._reducer = reducer
        self.window_size = window_size
        self._clock = clock
        self._mu = checked_lock("obs.window")
        # invertible: cumulative samples, newest-oldest is the window value;
        # keep window_size+1 so the diff spans exactly window_size seconds.
        self._samples: deque = deque(maxlen=window_size + 1)
        self._last = clock()
        self._samples.append(reducer._take_window_sample())

    def _catch_up(self):
        now = self._clock()
        missed = int(now - self._last)
        if missed <= 0:
            return
        self._last += missed
        sample = self._reducer._take_window_sample()
        if self._reducer._INVERTIBLE:
            for _ in range(min(missed, self._samples.maxlen)):
                self._samples.append(sample)
        else:
            # Identity-pad the quiet seconds first so the real sample lands
            # in the newest slot and survives a gap longer than the window.
            for _ in range(min(missed, self._samples.maxlen) - 1):
                self._samples.append(self._reducer._IDENTITY)
            self._samples.append(sample)

    def get_value(self):
        with self._mu:
            self._catch_up()
            if self._reducer._INVERTIBLE:
                return self._samples[-1] - self._samples[0]
            acc = self._reducer._IDENTITY
            for s in itertools.islice(self._samples, 1, None):
                acc = self._reducer._OP(acc, s)
            if acc == self._reducer._IDENTITY and not isinstance(acc, int):
                return 0  # Maxer/Miner with no samples in window
            return acc

    def elapsed(self) -> float:
        """Seconds actually covered by the stored samples (≤ window_size)."""
        with self._mu:
            self._catch_up()
            return max(len(self._samples) - 1, 1)


class PerSecond(Window):
    """Windowed rate: window delta divided by seconds covered
    (bvar::PerSecond — qps when the reducer counts calls)."""

    def get_value(self):
        covered = self.elapsed()
        with self._mu:
            if self._reducer._INVERTIBLE:
                delta = self._samples[-1] - self._samples[0]
            else:
                raise TypeError("PerSecond requires an invertible reducer")
        return delta / covered


# ---------------------------------------------------------------------------
# Latency recorder: log-scale fixed-bucket histogram
# ---------------------------------------------------------------------------

_BUCKETS_PER_DECADE = 20
_DECADES = 9            # 0.1us .. 10^8 us (100 s)
_NBUCKETS = _BUCKETS_PER_DECADE * _DECADES
_LOG_MIN = -1.0         # log10(0.1us)
# Geometric midpoint of each bucket, in microseconds (for percentiles).
_BUCKET_MID_US = np.power(
    10.0, _LOG_MIN + (np.arange(_NBUCKETS) + 0.5) / _BUCKETS_PER_DECADE)


class LatencyRecorder(Variable):
    """count / qps / avg / max / p50 p90 p99 p999 for one timed path.

    ``record(seconds)`` is the hot path: one log10, one histogram slot
    increment, two adder writes — no allocation.  Latencies are reported
    in microseconds (the reference's unit).  Relative percentile error is
    bounded by the bucket width: 10^(1/20) ≈ ±12%.
    """

    def __init__(self, window_size: int = 10,
                 clock: Callable[[], float] = time.monotonic):
        self._count = Adder()
        self._sum_us = Adder()
        self._max = Maxer()
        self._qps = PerSecond(self._count, window_size, clock)
        # plain list, not numpy: a scalar ndarray increment is ~3x the cost
        # of a list slot increment, and this is the hot path
        self._hist = [0] * _NBUCKETS
        self._hmu = checked_lock("obs.latency_hist")

    def record(self, seconds: float):
        us = seconds * 1e6
        if us < 0.1:
            idx = 0
        else:
            idx = int((math.log10(us) - _LOG_MIN) * _BUCKETS_PER_DECADE)
            if idx >= _NBUCKETS:
                idx = _NBUCKETS - 1
        with self._hmu:
            self._hist[idx] += 1
        self._count.add(1)
        self._sum_us.add(us)
        self._max.update(us)

    def record_bulk(self, seconds: float, n: int):
        """Fold ``n`` samples of the same latency in one shot.  For
        draining counters maintained OUTSIDE Python (e.g. the native
        Lookup path's sum/count pair): the per-sample distribution is
        gone by then, so all ``n`` land in one bucket at their mean."""
        if n <= 0:
            return
        us = seconds * 1e6
        if us < 0.1:
            idx = 0
        else:
            idx = int((math.log10(us) - _LOG_MIN) * _BUCKETS_PER_DECADE)
            if idx >= _NBUCKETS:
                idx = _NBUCKETS - 1
        with self._hmu:
            self._hist[idx] += n
        self._count.add(n)
        self._sum_us.add(us * n)
        self._max.update(us)

    @property
    def count(self) -> int:
        return self._count.get_value()

    @property
    def qps(self) -> float:
        return self._qps.get_value()

    @property
    def avg_us(self) -> float:
        n = self._count.get_value()
        return self._sum_us.get_value() / n if n else 0.0

    @property
    def max_us(self) -> float:
        return self._max.get_value()

    def percentile(self, q: float) -> float:
        """q in (0, 1]; returns the bucket-midpoint latency in us."""
        with self._hmu:
            hist = np.asarray(self._hist)
        total = int(hist.sum())
        if total == 0:
            return 0.0
        rank = max(int(math.ceil(q * total)), 1)
        cdf = np.cumsum(hist)
        idx = int(np.searchsorted(cdf, rank))
        return float(_BUCKET_MID_US[idx])

    def get_value(self):
        return {
            "count": self.count,
            "qps": round(self.qps, 3),
            "avg_us": round(self.avg_us, 3),
            "max_us": round(self.max_us, 3),
            "p50_us": round(self.percentile(0.50), 3),
            "p90_us": round(self.percentile(0.90), 3),
            "p99_us": round(self.percentile(0.99), 3),
            "p999_us": round(self.percentile(0.999), 3),
        }

    def describe(self) -> str:
        v = self.get_value()
        return (f"count={v['count']} qps={v['qps']} avg_us={v['avg_us']} "
                f"max_us={v['max_us']} p50={v['p50_us']} p90={v['p90_us']} "
                f"p99={v['p99_us']} p999={v['p999_us']}")


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

class Registry:
    """Named, exposed variables — the ``/vars`` page's backing store
    (reference Variable::expose + dump_exposed, src/bvar/variable.cpp)."""

    def __init__(self):
        self._mu = checked_lock("obs.registry")
        self._vars: Dict[str, Variable] = {}

    def expose(self, name: str, var: Variable) -> Variable:
        with self._mu:
            self._vars[name] = var
        return var

    def hide(self, name: str) -> None:
        with self._mu:
            self._vars.pop(name, None)

    def clear(self) -> None:
        with self._mu:
            self._vars.clear()

    def _select(self, filter) -> List[Tuple[str, Variable]]:
        with self._mu:
            items = sorted(self._vars.items())
        if filter is None or filter == "":
            return items
        if callable(filter):
            return [(n, v) for n, v in items if filter(n)]
        if any(ch in filter for ch in "*?["):
            return [(n, v) for n, v in items if fnmatch.fnmatch(n, filter)]
        return [(n, v) for n, v in items if filter in n]

    def dump_exposed(self, filter=None) -> str:
        """bRPC /vars text: one ``name : value`` line per variable.
        ``filter``: None (all), substring, glob, or predicate."""
        return "\n".join(f"{n} : {v.describe()}"
                         for n, v in self._select(filter))

    def dump_exposed_dict(self, filter=None) -> Dict[str, object]:
        return {n: v.get_value() for n, v in self._select(filter)}

    def __contains__(self, name: str) -> bool:
        with self._mu:
            return name in self._vars

    def names(self) -> List[str]:
        with self._mu:
            return sorted(self._vars)


_default_registry = Registry()


def default_registry() -> Registry:
    return _default_registry


def expose(name: str, var: Variable) -> Variable:
    return _default_registry.expose(name, var)


def dump_exposed(filter=None) -> str:
    return _default_registry.dump_exposed(filter)


def dump_exposed_dict(filter=None) -> Dict[str, object]:
    return _default_registry.dump_exposed_dict(filter)
