"""Service discovery for the Python tier over the native naming registry.

The registry itself is native (cpp/cluster/remote_naming.h — the consul
analog: versioned clusters, blocking Watch, TTL registrations); any brt
server hosts it via ``rpc.Server.add_naming_registry()``. This module is
the Python-side client, speaking the registry's JSON mapping over plain
HTTP (the restful bridge, cpp/rpc/json.h) so no binary codec is needed:

    reg = NamingClient("127.0.0.1:7000")
    reg.register("ps", "127.0.0.1:7100", ttl_ms=10_000)   # + heartbeats
    nodes, version = reg.list("ps")
    nodes, version = reg.watch("ps", known_version=version, wait_ms=30_000)

`RemoteEmbedding.from_registry` (ps_remote.py) builds the PS shard list
from a cluster, ordered by registration tag "<shard>/<num_shards>".
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import threading
from typing import Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class ReplicaSet:
    """One shard range's replica group: every address serves the SAME
    row range; ``primary`` indexes the replica that owns writes at boot
    (runtime promotion is the client's/fabric's business — this is the
    declared topology, reference SelectiveChannel's "replica groups per
    partition" shape, SURVEY §2.6–2.7).

    Declared in the naming registry with tags ``"<shard>/<num>"``
    (replica 0 — the boot primary, also the legacy single-owner form)
    and ``"<shard>/<num>/<replica>"`` (backups); parsed back by
    :func:`parse_shard_tag` / consumed by
    ``RemoteEmbedding.from_registry``."""

    addresses: Tuple[str, ...]
    primary: int = 0

    def __post_init__(self):
        if not self.addresses:
            raise ValueError("ReplicaSet needs at least one address")
        if not 0 <= self.primary < len(self.addresses):
            raise ValueError(
                f"primary index {self.primary} outside "
                f"[0, {len(self.addresses)})")

    @classmethod
    def of(cls, addrs: "str | Sequence[str]") -> "ReplicaSet":
        """Normalize a bare address or an address sequence."""
        if isinstance(addrs, ReplicaSet):
            return addrs
        if isinstance(addrs, str):
            return cls((addrs,))
        return cls(tuple(str(a) for a in addrs))


def shard_tag(shard: int, num_shards: int, replica: int = 0) -> str:
    """Registration tag for shard ``shard`` of ``num_shards``: replica 0
    keeps the legacy two-field form so pre-replication registrants and
    resolvers interoperate."""
    if replica == 0:
        return f"{shard}/{num_shards}"
    return f"{shard}/{num_shards}/{replica}"


def parse_shard_tag(tag: str) -> Optional[Tuple[int, int, int]]:
    """``(shard, num_shards, replica)`` from a registration tag, or
    ``None`` for tags that are not shard tags."""
    parts = tag.split("/")
    if len(parts) not in (2, 3):
        return None
    try:
        shard, num = int(parts[0]), int(parts[1])
        replica = int(parts[2]) if len(parts) == 3 else 0
    except ValueError:
        return None
    if replica < 0:
        return None
    return shard, num, replica


class NamingClient:
    def __init__(self, registry_addr: str, timeout_s: float = 35.0):
        self.addr = registry_addr
        self.timeout_s = timeout_s
        self._heartbeats: list[threading.Thread] = []
        self._stop = threading.Event()
        # One persistent keep-alive connection PER THREAD (watch blocks
        # for seconds while heartbeat threads keep renewing — they must
        # not share a socket), reused across polls instead of paying a
        # TCP handshake per probe.  All live connections are tracked for
        # close(); a broken one is dropped and recreated once.
        self._tls = threading.local()
        self._conns_mu = threading.Lock()
        self._conns: list[http.client.HTTPConnection] = []

    def _thread_conn(self) -> http.client.HTTPConnection:
        conn = getattr(self._tls, "conn", None)
        if conn is None:
            host, port = self.addr.rsplit(":", 1)
            conn = http.client.HTTPConnection(host, int(port),
                                              timeout=self.timeout_s)
            self._tls.conn = conn
            with self._conns_mu:
                self._conns.append(conn)
        return conn

    def _drop_thread_conn(self) -> None:
        conn = getattr(self._tls, "conn", None)
        if conn is None:
            return
        self._tls.conn = None
        with self._conns_mu:
            if conn in self._conns:
                self._conns.remove(conn)
        conn.close()

    def _call(self, method: str, payload: dict,
              timeout_s: Optional[float] = None) -> dict:
        body = json.dumps(payload)
        t = timeout_s or self.timeout_s
        for attempt in (0, 1):
            conn = self._thread_conn()
            conn.timeout = t
            if conn.sock is not None:
                conn.sock.settimeout(t)
            try:
                conn.request("POST", f"/Naming/{method}", body,
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                data = resp.read()
            except Exception:  # noqa: BLE001 — stale keep-alive socket:
                self._drop_thread_conn()   # reconnect once, then raise
                if attempt:
                    raise
                continue
            if resp.status != 200:
                raise RuntimeError(
                    f"Naming/{method} -> {resp.status}: {data!r}")
            return json.loads(data)
        raise AssertionError("unreachable")  # pragma: no cover

    def register(self, cluster: str, addr: str, weight: int = 1,
                 tag: str = "", ttl_ms: int = 0,
                 heartbeat: bool = True) -> int:
        """Registers addr in cluster; with a TTL and heartbeat=True a
        daemon thread renews at ttl/3 until close()."""
        if self._stop.is_set():
            raise RuntimeError("NamingClient is closed")
        req = {"cluster": cluster, "addr": addr, "weight": weight}
        if tag:
            req["tag"] = tag
        if ttl_ms > 0:
            req["ttl_ms"] = ttl_ms
        version = int(self._call("Register", req).get("version", 0))
        if ttl_ms > 0 and heartbeat:
            t = threading.Thread(
                target=self._heartbeat_loop, args=(dict(req), ttl_ms / 3000.0),
                daemon=True)
            t.start()
            self._heartbeats.append(t)
        return version

    def _heartbeat_loop(self, req: dict, period_s: float) -> None:
        while not self._stop.wait(period_s):
            try:
                self._call("Register", req)
            except Exception:  # noqa: BLE001 — registry outage: keep trying
                pass

    def deregister(self, cluster: str, addr: str) -> None:
        self._call("Deregister", {"cluster": cluster, "addr": addr})

    @staticmethod
    def _nodes(resp: dict) -> list[dict]:
        return resp.get("nodes", [])

    def list(self, cluster: str) -> tuple[list[dict], int]:
        resp = self._call("List", {"cluster": cluster})
        return self._nodes(resp), int(resp.get("version", 0))

    def watch(self, cluster: str, known_version: int = 0,
              wait_ms: int = 30_000) -> tuple[list[dict], int]:
        """Blocking query: returns when the cluster version passes
        known_version (or wait_ms elapses)."""
        resp = self._call(
            "Watch",
            {"cluster": cluster, "known_version": known_version,
             "wait_ms": wait_ms},
            timeout_s=wait_ms / 1000.0 + 5.0)
        return self._nodes(resp), int(resp.get("version", 0))

    def close(self) -> None:
        self._stop.set()
        with self._conns_mu:
            conns, self._conns = list(self._conns), []
        for conn in conns:
            conn.close()
