"""Service discovery for the Python tier over the native naming registry.

The registry itself is native (cpp/cluster/remote_naming.h — the consul
analog: versioned clusters, blocking Watch, TTL registrations); any brt
server hosts it via ``rpc.Server.add_naming_registry()``. This module is
the Python-side client, speaking the registry's JSON mapping over plain
HTTP (the restful bridge, cpp/rpc/json.h) so no binary codec is needed:

    reg = NamingClient("127.0.0.1:7000")
    reg.register("ps", "127.0.0.1:7100", ttl_ms=10_000)   # + heartbeats
    nodes, version = reg.list("ps")
    nodes, version = reg.watch("ps", known_version=version, wait_ms=30_000)

`RemoteEmbedding.from_registry` (ps_remote.py) builds the PS shard list
from a cluster, ordered by registration tag "<shard>/<num_shards>".

Two higher-level records also live in the same registry namespace:

- :class:`PartitionScheme` — a VERSIONED partitioning of the table
  (shard count + row-range map + replica sets + capacity weight +
  lifecycle state), published as one registry node per scheme
  (``addr="scheme#<version>"``, JSON tag).  Multiple schemes coexist
  during a live reshard (the DynamicPartitionChannel contract, SURVEY
  §2.7): clients weight read traffic across them and the migration
  driver walks a scheme through active → draining → retired.
- primary/epoch CLAIMS — shard tags may carry an ``@e<epoch>P|B``
  suffix (scheme-scoped form ``@v<scheme>e<epoch>P|B``) refreshed per
  heartbeat (``register(tag_fn=...)``), so failover state converges
  from one shared view instead of every client re-sweeping replicas
  (see ``parse_claim_tag``).
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import math
import threading
from typing import Dict, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class ReplicaSet:
    """One shard range's replica group: every address serves the SAME
    row range; ``primary`` indexes the replica that owns writes at boot
    (runtime promotion is the client's/fabric's business — this is the
    declared topology, reference SelectiveChannel's "replica groups per
    partition" shape, SURVEY §2.6–2.7).

    Declared in the naming registry with tags ``"<shard>/<num>"``
    (replica 0 — the boot primary, also the legacy single-owner form)
    and ``"<shard>/<num>/<replica>"`` (backups); parsed back by
    :func:`parse_shard_tag` / consumed by
    ``RemoteEmbedding.from_registry``."""

    addresses: Tuple[str, ...]
    primary: int = 0

    def __post_init__(self):
        if not self.addresses:
            raise ValueError("ReplicaSet needs at least one address")
        if not 0 <= self.primary < len(self.addresses):
            raise ValueError(
                f"primary index {self.primary} outside "
                f"[0, {len(self.addresses)})")

    @classmethod
    def of(cls, addrs: "str | Sequence[str]") -> "ReplicaSet":
        """Normalize a bare address or an address sequence."""
        if isinstance(addrs, ReplicaSet):
            return addrs
        if isinstance(addrs, str):
            return cls((addrs,))
        return cls(tuple(str(a) for a in addrs))


def shard_tag(shard: int, num_shards: int, replica: int = 0, *,
              epoch: Optional[int] = None,
              primary: Optional[bool] = None,
              scheme: Optional[int] = None) -> str:
    """Registration tag for shard ``shard`` of ``num_shards``: replica 0
    keeps the legacy two-field form so pre-replication registrants and
    resolvers interoperate.  ``epoch``/``primary`` append a CLAIM suffix
    (``@e<epoch>P`` or ``@e<epoch>B``) — the server's current failover
    state, refreshed per heartbeat via ``register(tag_fn=...)`` so
    clients can adopt the claimed primary without sweeping replicas.
    ``scheme`` scopes the claim to one partition scheme VERSION
    (``@v<scheme>e<epoch>P``): two coexisting schemes with the same
    shard count (a bounds-only reshard, a merge back) must not mask
    each other's claims, mirroring the per-scheme writer keys."""
    base = f"{shard}/{num_shards}" if replica == 0 \
        else f"{shard}/{num_shards}/{replica}"
    if epoch is None:
        return base
    ver = "" if scheme is None else f"v{scheme}"
    return f"{base}@{ver}e{epoch}{'P' if primary else 'B'}"


def parse_shard_tag(tag: str) -> Optional[Tuple[int, int, int]]:
    """``(shard, num_shards, replica)`` from a registration tag, or
    ``None`` for tags that are not shard tags.  A claim suffix
    (``@e<epoch>P|B``) is tolerated and stripped — claim-carrying
    heartbeats stay visible to claim-unaware resolvers."""
    parts = tag.split("@", 1)[0].split("/")
    if len(parts) not in (2, 3):
        return None
    try:
        shard, num = int(parts[0]), int(parts[1])
        replica = int(parts[2]) if len(parts) == 3 else 0
    except ValueError:
        return None
    # Fuzz-hardened: int() happily parses "-1" and "+0007", but a shard
    # outside [0, num) or a non-positive shard count can only poison the
    # resolver's grouping arithmetic downstream — not a shard tag.
    if replica < 0 or shard < 0 or num <= 0 or shard >= num:
        return None
    return shard, num, replica


def parse_claim_tag(
        tag: str
) -> Optional[Tuple[int, int, int, int, bool, Optional[int]]]:
    """``(shard, num_shards, replica, epoch, is_primary, scheme)`` from
    a claim-suffixed shard tag, or ``None`` when the tag carries no
    claim (plain shard tags parse with :func:`parse_shard_tag`).
    ``scheme`` is ``None`` for legacy unscoped claims (``@e<epoch>P``);
    scheme-scoped claims carry ``@v<scheme>e<epoch>P``."""
    base = parse_shard_tag(tag)
    if base is None or "@" not in tag:
        return None
    suffix = tag.split("@", 1)[1]
    scheme: Optional[int] = None
    if suffix.startswith("v"):
        head, sep, rest = suffix[1:].partition("e")
        if not sep:
            return None
        try:
            scheme = int(head)
        except ValueError:
            return None
        suffix = "e" + rest
    if not suffix.startswith("e") or suffix[-1] not in ("P", "B"):
        return None
    try:
        epoch = int(suffix[1:-1])
    except ValueError:
        return None
    # Negative epochs/scheme versions never exist (fencing epochs only
    # grow from 0; scheme versions are registry-encodable naturals) — a
    # tag carrying one is hostile or corrupt, not a claim.
    if epoch < 0 or (scheme is not None and scheme < 0):
        return None
    return base[0], base[1], base[2], epoch, suffix[-1] == "P", scheme


#: lifecycle states a published scheme moves through: ``preparing``
#: (published at copy start — its shards still import; a fallback
#: route, never the weighted pick or the write owner), ``active``
#: (serves reads and — the newest active — owns writes), ``draining``
#: (reads only while its traffic weight decays), ``retired`` (must not
#: be routed to at all; its servers may already be gone).
SCHEME_STATES = ("preparing", "active", "draining", "retired")

#: scheme records are registry nodes too, but the native registry
#: validates ``addr`` as a real endpoint — so a scheme registers under
#: the reserved address ``0.0.0.0:<version>`` (never a routable server)
#: and is recognized by its TAG prefix; the JSON payload rides the tag.
SCHEME_TAG_PREFIX = "scheme!"


def scheme_record_addr(version: int) -> str:
    if not 0 <= version < 65536:
        raise ValueError(
            f"scheme version {version} outside the registry-encodable "
            f"range [0, 65536)")
    return f"0.0.0.0:{version}"


@dataclasses.dataclass(frozen=True)
class PartitionScheme:
    """One VERSIONED partitioning of a table: the row-range map, the
    replica group serving each range, and how much read traffic the
    scheme should carry (the reference DynamicPartitionChannel keeps
    multiple partitioning schemes alive simultaneously and weights
    traffic by capacity, partition_channel.h:136 /
    dynpart_load_balancer.cpp — this is that object made first-class
    and published through the naming registry).

    ``bounds`` is the explicit row-range map (``bounds[s] <= id <
    bounds[s+1]`` owns shard ``s``); ``None`` means uniform ranges over
    the consumer's vocab.  ``weight`` is the scheme's capacity share of
    READ traffic (writes always go to the newest active scheme).
    """

    version: int
    replica_sets: Tuple[ReplicaSet, ...]
    weight: float = 1.0
    state: str = "active"
    bounds: Optional[Tuple[int, ...]] = None

    def __post_init__(self):
        if self.version < 0:
            raise ValueError(f"scheme version {self.version} < 0")
        if not self.replica_sets:
            raise ValueError("a scheme needs at least one shard")
        object.__setattr__(self, "replica_sets", tuple(
            ReplicaSet.of(rs) for rs in self.replica_sets))
        if self.weight < 0:
            raise ValueError(f"scheme weight {self.weight} < 0")
        if self.state not in SCHEME_STATES:
            raise ValueError(f"unknown scheme state {self.state!r}; "
                             f"valid: {', '.join(SCHEME_STATES)}")
        if self.bounds is not None:
            b = tuple(int(x) for x in self.bounds)
            if len(b) != len(self.replica_sets) + 1 or b[0] != 0 or \
                    any(b[i] >= b[i + 1] for i in range(len(b) - 1)):
                raise ValueError(
                    f"bounds {b} must be strictly increasing, start at "
                    f"0, and have num_shards+1 entries")
            object.__setattr__(self, "bounds", b)

    @property
    def num_shards(self) -> int:
        return len(self.replica_sets)

    def shard_bounds(self, s: int, vocab: int) -> Tuple[int, int]:
        """``[lo, hi)`` row range of shard ``s`` under this scheme."""
        if self.bounds is not None:
            return self.bounds[s], self.bounds[s + 1]
        rows_per = vocab // self.num_shards
        return s * rows_per, (s + 1) * rows_per

    def with_(self, **changes) -> "PartitionScheme":
        """A copy with ``changes`` applied (weight/state transitions)."""
        return dataclasses.replace(self, **changes)

    def to_json(self) -> str:
        return json.dumps({
            "version": self.version,
            "replica_sets": [
                {"addresses": list(rs.addresses), "primary": rs.primary}
                for rs in self.replica_sets],
            "weight": self.weight,
            "state": self.state,
            "bounds": list(self.bounds) if self.bounds else None,
        })

    @classmethod
    def from_json(cls, text: str) -> "PartitionScheme":
        """Strict record parse — registry records are hostile input
        (anything can publish a ``scheme!`` tag).  Shape violations the
        dataclass validation cannot see raise ``ValueError`` here: a
        string where an address LIST belongs (``tuple("abc")`` silently
        becomes three one-char addresses), a non-finite weight (inf/nan
        poisons every capacity-weighting comparison downstream), or a
        non-list bounds."""
        d = json.loads(text)
        if not isinstance(d, dict):
            raise ValueError("scheme record must be a JSON object")
        rs_in = d["replica_sets"]
        if not isinstance(rs_in, (list, tuple)):
            raise ValueError("replica_sets must be a list")
        sets = []
        for rs in rs_in:
            if not isinstance(rs, dict):
                raise ValueError("replica set must be an object")
            addrs = rs["addresses"]
            if not isinstance(addrs, (list, tuple)) or not all(
                    isinstance(a, str) for a in addrs):
                raise ValueError("addresses must be a list of strings")
            sets.append(ReplicaSet(tuple(addrs),
                                   primary=int(rs.get("primary", 0))))
        weight = float(d.get("weight", 1.0))
        if not math.isfinite(weight):
            raise ValueError(f"scheme weight {weight} is not finite")
        bounds = d.get("bounds")
        if bounds is not None and not isinstance(bounds, (list, tuple)):
            raise ValueError("bounds must be a list")
        return cls(
            version=int(d["version"]),
            replica_sets=tuple(sets),
            weight=weight,
            state=d.get("state", "active"),
            bounds=tuple(bounds) if bounds else None)


def publish_scheme(client: "NamingClient", cluster: str,
                   scheme: PartitionScheme) -> int:
    """Publishes (or re-publishes — weight/state updates re-register the
    same node) ``scheme`` into ``cluster``.  Returns the new registry
    version; watchers holding the old version wake immediately."""
    return client.register(
        cluster, scheme_record_addr(scheme.version),
        tag=SCHEME_TAG_PREFIX + scheme.to_json(), heartbeat=False)


def parse_schemes(nodes: Sequence[dict]) -> Dict[int, PartitionScheme]:
    """Every scheme record in a cluster listing, by version (the LAST
    occurrence of a version wins — registration order is publication
    order, so re-published weight/state transitions supersede)."""
    out: Dict[int, PartitionScheme] = {}
    for n in nodes:
        tag = n.get("tag", "")
        if not isinstance(tag, str) or \
                not tag.startswith(SCHEME_TAG_PREFIX):
            continue
        try:
            scheme = PartitionScheme.from_json(
                tag[len(SCHEME_TAG_PREFIX):])
        except (ValueError, KeyError, TypeError, RecursionError):
            # RecursionError: json.loads on a deeply-nested hostile
            # payload ("[[[[…") overflows the decoder's stack — a
            # malformed record, not a parser crash.
            continue
        out[scheme.version] = scheme
    return out


def parse_claims(
        nodes: Sequence[dict]
) -> Dict[Tuple[Optional[int], int, int], Tuple[int, str]]:
    """Primary claims from claim-suffixed shard tags:
    ``{(scheme, num_shards, shard): (epoch, addr)}`` keeping the
    highest epoch per key.  Claims are SCOPED per scheme version so two
    coexisting schemes with the same shard count never mask each other
    (``scheme`` is ``None`` for legacy unscoped claims).  Only PRIMARY
    claims are returned — a backup's claim says who it is, not who owns
    the range."""
    out: Dict[Tuple[Optional[int], int, int], Tuple[int, str]] = {}
    for n in nodes:
        tag = n.get("tag", "")
        parsed = parse_claim_tag(tag) if isinstance(tag, str) else None
        if parsed is None:
            continue
        shard, num, _replica, epoch, is_primary, scheme = parsed
        if not is_primary:
            continue
        # a claim-tagged node without a routable addr is corrupt — a
        # KeyError here used to kill the whole listing's ingest
        addr = n.get("addr")
        if not isinstance(addr, str) or not addr:
            continue
        key = (scheme, num, shard)
        if key not in out or epoch >= out[key][0]:
            out[key] = (epoch, addr)
    return out


class NamingClient:
    def __init__(self, registry_addr: str, timeout_s: float = 35.0):
        self.addr = registry_addr
        self.timeout_s = timeout_s
        self._heartbeats: list[threading.Thread] = []
        self._stop = threading.Event()
        # One persistent keep-alive connection PER THREAD (watch blocks
        # for seconds while heartbeat threads keep renewing — they must
        # not share a socket), reused across polls instead of paying a
        # TCP handshake per probe.  All live connections are tracked for
        # close(); a broken one is dropped and recreated once.
        self._tls = threading.local()
        self._conns_mu = threading.Lock()
        self._conns: list[http.client.HTTPConnection] = []

    def _thread_conn(self) -> http.client.HTTPConnection:
        conn = getattr(self._tls, "conn", None)
        if conn is None:
            host, port = self.addr.rsplit(":", 1)
            conn = http.client.HTTPConnection(host, int(port),
                                              timeout=self.timeout_s)
            self._tls.conn = conn
            with self._conns_mu:
                self._conns.append(conn)
        return conn

    def _drop_thread_conn(self) -> None:
        conn = getattr(self._tls, "conn", None)
        if conn is None:
            return
        self._tls.conn = None
        with self._conns_mu:
            if conn in self._conns:
                self._conns.remove(conn)
        conn.close()

    def _call(self, method: str, payload: dict,
              timeout_s: Optional[float] = None) -> dict:
        body = json.dumps(payload)
        t = timeout_s or self.timeout_s
        for attempt in (0, 1):
            conn = self._thread_conn()
            conn.timeout = t
            if conn.sock is not None:
                conn.sock.settimeout(t)
            try:
                conn.request("POST", f"/Naming/{method}", body,
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                data = resp.read()
            except Exception:  # noqa: BLE001 — stale keep-alive socket:
                self._drop_thread_conn()   # reconnect once, then raise
                if attempt:
                    raise
                continue
            if resp.status != 200:
                raise RuntimeError(
                    f"Naming/{method} -> {resp.status}: {data!r}")
            return json.loads(data)
        raise AssertionError("unreachable")  # pragma: no cover

    def register(self, cluster: str, addr: str, weight: int = 1,
                 tag: str = "", ttl_ms: int = 0,
                 heartbeat: bool = True, tag_fn=None) -> int:
        """Registers addr in cluster; with a TTL and heartbeat=True a
        daemon thread renews at ttl/3 until close().  ``tag_fn`` (a
        callable returning the CURRENT tag) is re-evaluated on every
        heartbeat, so registrants can publish live state — a PS
        replica's primary/epoch claim rides its shard tag this way
        (see :func:`parse_claim_tag`)."""
        if self._stop.is_set():
            raise RuntimeError("NamingClient is closed")
        req = {"cluster": cluster, "addr": addr, "weight": weight}
        if tag_fn is not None:
            req["tag"] = str(tag_fn())
        elif tag:
            req["tag"] = tag
        if ttl_ms > 0:
            req["ttl_ms"] = ttl_ms
        version = int(self._call("Register", req).get("version", 0))
        if ttl_ms > 0 and heartbeat:
            t = threading.Thread(
                target=self._heartbeat_loop,
                args=(dict(req), ttl_ms / 3000.0, tag_fn),
                daemon=True)
            t.start()
            self._heartbeats.append(t)
        return version

    def _heartbeat_loop(self, req: dict, period_s: float,
                        tag_fn=None) -> None:
        while not self._stop.wait(period_s):
            try:
                if tag_fn is not None:
                    req["tag"] = str(tag_fn())
                self._call("Register", req)
            except Exception:  # noqa: BLE001 — registry outage: keep trying
                pass

    def deregister(self, cluster: str, addr: str) -> None:
        self._call("Deregister", {"cluster": cluster, "addr": addr})

    @staticmethod
    def _nodes(resp: dict) -> list[dict]:
        return resp.get("nodes", [])

    def list(self, cluster: str) -> tuple[list[dict], int]:
        resp = self._call("List", {"cluster": cluster})
        return self._nodes(resp), int(resp.get("version", 0))

    def watch(self, cluster: str, known_version: int = 0,
              wait_ms: int = 30_000) -> tuple[list[dict], int]:
        """Blocking query: returns when the cluster version passes
        known_version (or wait_ms elapses)."""
        resp = self._call(
            "Watch",
            {"cluster": cluster, "known_version": known_version,
             "wait_ms": wait_ms},
            timeout_s=wait_ms / 1000.0 + 5.0)
        return self._nodes(resp), int(resp.get("version", 0))

    def close(self) -> None:
        self._stop.set()
        with self._conns_mu:
            conns, self._conns = list(self._conns), []
        for conn in conns:
            conn.close()
