"""Fault-tolerance tier: retry policy, backup requests, circuit breaker,
health-check revival.

The reference treats failure handling as a first-class RPC concern —
``RetryPolicy::DoRetry`` with excluded-server backoff (retry_policy.h:28),
timer-fired backup requests (controller.cpp:337), per-node
``CircuitBreaker`` EMA windows feeding ``ExcludedServers``
(circuit_breaker.h:25-48) with a ``ClusterRecoverPolicy`` safety valve,
and periodic health-check revival (details/health_check.cpp:146).  This
module is the Python tier's equivalent, layered over the native fabric:

- :class:`Backoff` — exponential backoff with DETERMINISTIC jitter (a
  seeded hash, not ``random``): the same seed yields the same delay
  sequence, so tests and fault-injection runs are reproducible.  It is
  also the package's one sanctioned blocking-sleep site
  (:func:`sleep_ms`) — the ``fiber-blocking-sleep`` lint check flags bare
  ``time.sleep`` anywhere handler-reachable and points here.
- :class:`RetryPolicy` + :func:`call_with_retry` — retriable-error
  classification over the native error space (transport/timeout errors
  retry, application errors don't) under a per-call *deadline budget*:
  every attempt's native timeout is the REMAINING budget, and backoff
  sleeps are capped by it, so the retry loop can never exceed the
  caller's total deadline.
- :func:`backup_call` — hedged requests: if the primary attempt has not
  answered within ``backup_ms``, a second attempt is started; the first
  completion wins and the loser is cancelled via the native
  ``brt_call_cancel`` (reference ``StartCancel``).  A completed-but-
  failed attempt yields to the other one (hedging is for availability,
  not fail-fast).
- :class:`CircuitBreaker` / :class:`BreakerRegistry` — per-endpoint
  long+short EMA error windows over an injectable clock;
  open / half-open / closed states; isolation duration doubles with
  consecutive isolations; the registry's cluster-recover guard refuses
  an isolation that would leave fewer than ``min_working`` endpoints
  serving (never isolate every shard).
- :class:`HealthProber` — a background fiber probing isolated endpoints
  through the ``_status`` builtin's ``health`` method and reviving them
  on success (``probe_once()`` is public so tests drive it
  deterministically).

This module never imports :mod:`brpc_tpu.rpc` at module level — ``rpc``
imports it for ``Channel.call``'s resilience kwargs, so the dependency
points downward; ``RpcError`` is imported lazily inside functions.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from brpc_tpu import obs
from brpc_tpu import wire as _wire
from brpc_tpu.analysis import race as _race
from brpc_tpu.analysis.race import checked_lock

__all__ = [
    "Backoff", "sleep_ms", "RetryPolicy", "RETRIABLE_CODES",
    "EBREAKEROPEN", "ENOTPRIMARY", "EFENCED", "EMIGRATING",
    "ESCHEMEMOVED", "EBADFRAME", "ELIMIT", "EDEADLINE",
    "call_with_retry",
    "backup_call", "resilient_call", "BreakerOptions", "CircuitBreaker",
    "BreakerRegistry", "HealthProber", "ReplicaScorer",
    "default_registry", "set_default_registry", "health_components",
]

#: python-side error code for a breaker fast-fail (outside the native
#: errors.h space — the call never reached the wire)
EBREAKEROPEN = 2008
#: a write reached a replica that is not (or no longer) the primary for
#: its row range — the caller should re-resolve/promote and re-route
ENOTPRIMARY = 2009
#: a replication message carried a stale fencing epoch: a newer primary
#: exists and the sender must demote itself (never retriable — retrying
#: the same epoch yields the same rejection)
EFENCED = 2010
#: the shard is still IMPORTING its row range (a resharding migration
#: destination before cutover completes): reads should fall back to
#: another partition scheme, writes should wait out the cutover window
EMIGRATING = 2011
#: the shard's partition scheme was retired by a fenced cutover: the
#: caller holds a stale scheme and must refresh its routing (the
#: redirect error that drives client scheme refresh during a live
#: reshard — never retriable against the same scheme)
ESCHEMEMOVED = 2012
#: a malformed frame was rejected by a wire-contract guard before any
#: allocation or state mutation (:mod:`brpc_tpu.wire`) — never
#: retriable: the same bytes parse the same way twice
EBADFRAME = _wire.EBADFRAME
#: the server's concurrency limiter shed the request before the handler
#: ran (native errors.h ELIMIT; brpc_tpu.limiter) — transient by
#: definition, but retriable ONLY with a mandatory backoff: an
#: immediate re-issue lands straight back in the overload that shed it
ELIMIT = 2004
#: the request's propagated deadline budget was exhausted before the
#: handler started (the server shed queued work it could no longer
#: finish in time) — never retriable: the caller's budget is gone, and
#: the answer the retry would fetch is already too late
EDEADLINE = 2014

#: native error codes worth retrying: the request may never have reached
#: the server, or the failure is transient by construction.  Application
#: errors (EINTERNAL 2001, EREQUEST, ENOSERVICE/ENOMETHOD, EAUTH,
#: ERESPONSE, EHTTP), cancellation (2005) and breaker fast-fails are NOT
#: retriable — repeating them burns budget for the same answer.
RETRIABLE_CODES = frozenset({
    -1,     # local transport failure before an error code existed
    1005,   # ETOOMANYFAILS (combo sub-channel failures)
    1008,   # ERPCTIMEDOUT
    1009,   # EFAILEDSOCKET (connection broke mid-call)
    1011,   # EOVERCROWDED (buffered-write pressure)
    2003,   # ELOGOFF (server stopping — another endpoint may serve)
    2004,   # ELIMIT (concurrency limit — transient by definition)
})


def _rpc_error(code: int, text: str):
    from brpc_tpu.rpc import RpcError  # lazy: rpc imports this module
    return RpcError(code, text)


# ---------------------------------------------------------------------------
# backoff (the shared, deterministic-jitter helper)
# ---------------------------------------------------------------------------

_MASK64 = (1 << 64) - 1


def _hash01(seed: int, n: int) -> float:
    """Deterministic uniform-ish [0,1) from (seed, n) — splitmix64
    finalizer, no ``random`` state anywhere."""
    h = (seed * 0x9E3779B97F4A7C15 + (n + 1) * 0xBF58476D1CE4E5B9) & _MASK64
    h ^= h >> 30
    h = (h * 0xBF58476D1CE4E5B9) & _MASK64
    h ^= h >> 27
    h = (h * 0x94D049BB133111EB) & _MASK64
    h ^= h >> 31
    return (h % 1_000_000) / 1_000_000.0


@dataclasses.dataclass(frozen=True)
class Backoff:
    """Exponential backoff with deterministic downward jitter.

    ``delay_ms(attempt)`` is a pure function of ``(seed, attempt)``:
    ``min(max_ms, base_ms * multiplier**attempt)`` scaled into
    ``[1 - jitter, 1]`` by the seeded hash.  Jitter only ever SHRINKS the
    delay, so ``delay_ms`` is also an upper bound — deadline-budget
    arithmetic stays simple.
    """

    base_ms: float = 20.0
    multiplier: float = 2.0
    max_ms: float = 2000.0
    jitter: float = 0.5
    seed: int = 0

    def delay_ms(self, attempt: int) -> float:
        raw = min(self.max_ms, self.base_ms * self.multiplier ** attempt)
        if self.jitter <= 0.0:
            return raw
        return raw * (1.0 - self.jitter * _hash01(self.seed, attempt))


def sleep_ms(ms: float, *, sleep: Callable[[float], None] = time.sleep
             ) -> None:
    """The sanctioned blocking sleep for backoff waits (injectable for
    tests; the ``fiber-blocking-sleep`` lint check routes handler-
    reachable sleeps here)."""
    if ms > 0:
        sleep(ms / 1000.0)


# ---------------------------------------------------------------------------
# retry policy + deadline-budget retry loop
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Retriable-error classification + backoff schedule (reference
    ``RetryPolicy::DoRetry``, retry_policy.h:28).  ``max_attempts``
    counts the first try: 3 means at most 2 retries.

    ``attempt_timeout_ms`` caps any SINGLE attempt's native timeout
    below the total deadline budget — without it, one black-holed
    attempt (lost request, dead peer) eats the whole budget and the
    retries the budget was supposed to buy never run.

    ``limit_backoff_floor_ms`` is the MANDATORY minimum backoff before
    retrying an ``ELIMIT`` shed: a limiter rejection is proof the
    server is past capacity right now, and an immediate re-issue (a
    zero-base backoff, a jittered-to-nothing delay) just feeds the
    overload it bounced off.  The floor still yields to the caller's
    total deadline budget — it raises the sleep, never the deadline."""

    max_attempts: int = 3
    retriable: frozenset = RETRIABLE_CODES
    backoff: Backoff = Backoff()
    attempt_timeout_ms: Optional[float] = None
    limit_backoff_floor_ms: float = 5.0

    def retry_delay_ms(self, exc: BaseException, attempt: int) -> float:
        """The backoff before retrying ``attempt``'s failure: the
        schedule's delay, floored at ``limit_backoff_floor_ms`` for
        ``ELIMIT`` sheds (counted in ``rpc_limit_backoffs``)."""
        delay = self.backoff.delay_ms(attempt)
        if getattr(exc, "code", None) == ELIMIT:
            delay = max(delay, self.limit_backoff_floor_ms)
            if obs.enabled():
                obs.counter("rpc_limit_backoffs").add(1)
        return delay

    def cap_attempt_timeout(
            self, timeout_ms: Optional[int]) -> Optional[int]:
        if self.attempt_timeout_ms is None:
            return timeout_ms
        cap = max(1, int(self.attempt_timeout_ms))
        return cap if timeout_ms is None else min(timeout_ms, cap)

    def do_retry(self, exc: BaseException, attempt: int) -> bool:
        """True when ``exc`` (the failure of 0-based ``attempt``) should
        be retried."""
        if attempt + 1 >= self.max_attempts:
            return False
        return getattr(exc, "code", None) in self.retriable


def call_with_retry(channel, service: str, method: str,
                    request: bytes = b"", *,
                    policy: Optional[RetryPolicy] = None,
                    deadline_ms: Optional[float] = None,
                    breaker: "Optional[CircuitBreaker]" = None,
                    backup_ms: Optional[float] = None,
                    clock: Callable[[], float] = time.monotonic,
                    sleep: Callable[[float], None] = time.sleep) -> bytes:
    """Retrying call under a deadline budget.

    Each attempt's native per-call timeout is the budget still remaining,
    and backoff sleeps are capped so a final attempt always gets >=1ms —
    total wall time across every attempt and sleep stays <= deadline_ms.
    Without ``deadline_ms`` the channel's own timeout bounds each attempt
    (but not the sum).  ``breaker`` (per-endpoint) fast-fails while open
    and is fed every outcome; ``backup_ms`` hedges each attempt via
    :func:`backup_call`.
    """
    policy = policy or RetryPolicy()
    deadline = clock() + deadline_ms / 1000.0 \
        if deadline_ms is not None else None
    attempt = 0
    while True:
        if breaker is not None and breaker.isolated():
            if obs.enabled():
                obs.counter("rpc_breaker_fastfail").add(1)
            raise _rpc_error(
                EBREAKEROPEN,
                f"circuit breaker open for {getattr(breaker, 'name', '?')}"
                f" (fail-fast, no attempt made)")
        attempt_timeout: Optional[int] = None
        if deadline is not None:
            remaining_ms = (deadline - clock()) * 1000.0
            if remaining_ms < 1.0:
                raise _rpc_error(
                    1008, f"deadline budget exhausted after {attempt} "
                          f"attempt(s) of {service}.{method}")
            attempt_timeout = max(1, int(remaining_ms))
        attempt_timeout = policy.cap_attempt_timeout(attempt_timeout)
        try:
            tag = f"attempt={attempt}"
            if backup_ms is not None:
                out = backup_call(channel, service, method, request,
                                  backup_ms=backup_ms,
                                  timeout_ms=attempt_timeout, tag=tag)
            else:
                out = channel.call_async(service, method, request,
                                         timeout_ms=attempt_timeout,
                                         tag=tag).join()
        except Exception as e:  # noqa: BLE001 — classified below
            code = getattr(e, "code", None)
            if code is None:
                raise  # not an RPC failure (programming error): no retry
            if breaker is not None:
                breaker.on_call_end(code)
            if not policy.do_retry(e, attempt):
                if obs.enabled() and attempt > 0:
                    obs.counter("rpc_retry_give_up").add(1)
                raise
            delay = policy.retry_delay_ms(e, attempt)
            if deadline is not None:
                remaining_ms = (deadline - clock()) * 1000.0
                if remaining_ms < 2.0:
                    raise  # no room for a sleep AND an attempt
                # leave at least 1ms of budget for the next attempt
                delay = min(delay, remaining_ms - 1.0)
            if obs.enabled():
                obs.counter("rpc_retries").add(1)
            sleep_ms(delay, sleep=sleep)
            attempt += 1
            continue
        if breaker is not None:
            breaker.on_call_end(0)
        return out


# ---------------------------------------------------------------------------
# backup requests (hedging over call_async + native cancel)
# ---------------------------------------------------------------------------

def backup_call(channel, service: str, method: str, request: bytes = b"",
                *, backup_ms: float, timeout_ms: Optional[int] = None,
                tag: Optional[str] = None, primary=None) -> bytes:
    """Hedged call: start the primary; if it has not completed within
    ``backup_ms``, start a second identical attempt.  The FIRST
    completion wins and the loser is cancelled (native ``StartCancel``)
    then reaped.  An attempt that completes with an error yields to the
    other attempt; only when both fail does the first error propagate.

    ``primary`` may be an already-started PendingCall for the same
    request (the PS fan-out hedges its in-flight shard calls this way);
    it is always consumed — joined, or cancelled and reaped.

    The reference arms this with a timer inside the controller
    (controller.cpp:337); here the hedge rides the native call-group
    fan-in (``rpc.CallGroup``): both attempts signal one CountdownEvent
    and every ``wait_any`` wakes on EXACTLY one completion — no
    ``brt_call_wait`` polling slices anywhere in the loop.  The
    ``rpc_hedge_waits`` counter tracks completions consumed (at most one
    per attempt), not elapsed time — the exactness contract the tests
    assert.
    """
    rec = obs.enabled()

    def _tagged(label: str) -> str:
        return f"{tag},{label}" if tag else label

    if primary is None:
        primary = channel.call_async(service, method, request,
                                     timeout_ms=timeout_ms,
                                     tag=_tagged("hedge=primary"))
    # The arming window: ONE bounded wait on the primary's own completion
    # latch (level-triggered, not a poll loop).
    if primary.wait(backup_ms / 1000.0):
        return primary.join()
    if rec:
        obs.counter("rpc_backup_fired").add(1)
    from brpc_tpu import rpc as _rpc  # lazy: rpc imports this module
    pending: List[Tuple[str, object]] = [("primary", primary)]
    group = _rpc.CallGroup()
    try:
        group.add(primary)
        try:
            backup = channel.call_async(service, method, request,
                                        timeout_ms=timeout_ms,
                                        tag=_tagged("hedge=backup"))
            # hedge registry: the finally reaps every entry not joined
            pending.append(("backup", backup))  # lint: allow-handle-escape
            group.add(backup)
        except Exception as e:  # noqa: BLE001 — hedge must not lose the
            if getattr(e, "code", None) is None:  # primary to a failed
                raise                             # backup start
        first_exc: Optional[Exception] = None
        while pending:
            if rec:
                obs.counter("rpc_hedge_waits").add(1)
            group.wait_any()  # parks until one attempt completes; exact
            done_idx = next((i for i, (_, pc) in enumerate(pending)
                             if pc.wait(0.0)), None)
            if done_idx is None:  # pragma: no cover — wait_any contract
                continue
            label, pc = pending.pop(done_idx)
            try:
                out = pc.join()
            except Exception as e:  # noqa: BLE001 — yield to the hedge
                if getattr(e, "code", None) is None:
                    raise
                if first_exc is None:
                    first_exc = e
                continue
            if rec and label == "backup":
                obs.counter("rpc_backup_wins").add(1)
            return out
        raise first_exc  # both attempts completed, both failed
    finally:
        group.close()
        # Winner path: cancel the loser so it stops consuming the server
        # and the fabric, then reap.  Error paths reap whatever is left.
        for _, pc in pending:
            pc.cancel()
            pc.close()


def resilient_call(channel, service: str, method: str,
                   request: bytes = b"", *,
                   retry: Optional[RetryPolicy] = None,
                   deadline_ms: Optional[float] = None,
                   backup_ms: Optional[float] = None,
                   breaker: "Optional[CircuitBreaker]" = None,
                   timeout_ms: Optional[int] = None) -> bytes:
    """Dispatch for ``Channel.call``'s resilience kwargs: the minimal
    machinery for what was asked.  A bare ``backup_ms`` skips the retry
    loop; anything involving retry/deadline/breaker goes through
    :func:`call_with_retry`."""
    if retry is None and deadline_ms is None and breaker is None:
        if backup_ms is not None:
            return backup_call(channel, service, method, request,
                               backup_ms=backup_ms, timeout_ms=timeout_ms)
        return channel.call_async(service, method, request,
                                  timeout_ms=timeout_ms).join()
    if deadline_ms is None and timeout_ms is not None:
        deadline_ms = timeout_ms  # a per-call timeout IS the budget
    return call_with_retry(channel, service, method, request,
                           policy=retry, deadline_ms=deadline_ms,
                           breaker=breaker, backup_ms=backup_ms)


# ---------------------------------------------------------------------------
# circuit breaker (per-endpoint EMA windows, injectable clock)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class BreakerOptions:
    """Defaults mirror the reference flags (circuit_breaker.h:25-48):
    1% tolerated error rate over the long window, 5% over the short."""

    long_window: int = 1024
    short_window: int = 128
    long_max_error_rate: float = 0.01
    short_max_error_rate: float = 0.05
    min_isolation_ms: float = 100.0
    max_isolation_ms: float = 30_000.0
    #: samples required before the windows may trip (0 = short_window/4)
    min_samples: int = 0

    def effective_min_samples(self) -> int:
        return self.min_samples or max(1, self.short_window // 4)


class CircuitBreaker:
    """Per-endpoint breaker: long+short EMA error windows; tripping
    isolates the endpoint for a duration that doubles with consecutive
    isolations (capped); successful traffic after recovery decays the
    backoff.  ``clock`` is injectable (monotonic seconds) so the state
    machine is testable without wall time.

    States (:meth:`state`): ``closed`` (serving), ``open`` (isolated —
    callers fail fast), ``half_open`` (isolation expired, awaiting the
    first success or probe).  ``isolate_guard``, when set, is consulted
    OUTSIDE the breaker lock before tripping — the registry binds the
    cluster-recover check here.
    """

    def __init__(self, options: Optional[BreakerOptions] = None,
                 clock: Callable[[], float] = time.monotonic,
                 isolate_guard: Optional[Callable[[], bool]] = None,
                 name: str = ""):
        self.opt = options or BreakerOptions()
        self.name = name
        self._clock = clock
        self._isolate_guard = isolate_guard
        self._mu = checked_lock("resilience.breaker")
        # fixed-point EMAs (error rate x10000), like the reference
        self._long_ema = 0
        self._short_ema = 0
        self._samples = 0
        self._isolation_count = 0
        # read lock-free by isolated()/state(): a stale read is benign
        # (one extra call slips through or fast-fails a moment late)
        self._isolated_until = 0.0
        self._probation = False

    # -- lock-free reads ---------------------------------------------------

    def isolated(self) -> bool:
        return self._clock() < self._isolated_until

    def state(self) -> str:
        if self.isolated():
            return "open"
        if self._probation:
            return "half_open"
        return "closed"

    # -- state transitions -------------------------------------------------

    def _update_ema(self, prev: int, err: float, window: int) -> int:
        return prev + (int(err * 10000) - prev) // window

    def on_call_end(self, error_code: int) -> bool:
        """Feed one call outcome.  Returns False when the endpoint is
        (or just became) isolated — the caller should exclude it."""
        if self.isolated():
            return False
        trip = False
        with self._mu:
            err = 0.0 if error_code == 0 else 1.0
            self._long_ema = self._update_ema(
                self._long_ema, err, self.opt.long_window)
            self._short_ema = self._update_ema(
                self._short_ema, err, self.opt.short_window)
            self._samples += 1
            if error_code == 0 and self._probation:
                # first success after isolation: close, decay the backoff
                self._probation = False
                if self._isolation_count > 0:
                    self._isolation_count -= 1
            elif error_code != 0 and self._probation:
                # half-open probe failed: reopen immediately, don't wait
                # for the windows to refill past the sample gate
                trip = True
            if not trip and \
                    self._samples >= self.opt.effective_min_samples() and (
                    self._long_ema > self.opt.long_max_error_rate * 10000
                    or self._short_ema >
                    self.opt.short_max_error_rate * 10000):
                trip = True
        if not trip:
            return True
        # Guard consulted outside the breaker lock: it reads sibling
        # breakers (lock-free) via the registry and must never nest
        # inside this one.
        if self._isolate_guard is not None and not self._isolate_guard():
            if obs.enabled():
                obs.counter("rpc_breaker_guard_skips").add(1)
            with self._mu:
                self._reset_windows_locked()
            return True
        self.isolate()
        return False

    def isolate(self) -> None:
        with self._mu:
            self._isolation_count = min(self._isolation_count + 1, 8)
            dur_ms = min(
                self.opt.min_isolation_ms * (1 << (self._isolation_count
                                                   - 1)),
                self.opt.max_isolation_ms)
            self._isolated_until = self._clock() + dur_ms / 1000.0
            self._probation = True
            self._reset_windows_locked()
        if obs.enabled():
            obs.counter("rpc_breaker_open").add(1)

    def _reset_windows_locked(self) -> None:
        self._long_ema = 0
        self._short_ema = 0
        self._samples = 0

    def revive(self) -> None:
        """Health probe verified the endpoint: lift isolation now
        (reference HealthCheckTask revival)."""
        with self._mu:
            self._isolated_until = 0.0
            self._probation = False
        if obs.enabled():
            obs.counter("rpc_breaker_revived").add(1)

    def snapshot(self) -> Dict[str, object]:
        return {
            "state": self.state(),
            "isolation_count": self._isolation_count,
            "samples": self._samples,
            "long_error_rate": self._long_ema / 10000.0,
            "short_error_rate": self._short_ema / 10000.0,
        }


class BreakerRegistry:
    """Per-endpoint breakers plus the cluster-recover guard: an
    isolation is refused when it would leave fewer than ``min_working``
    endpoints un-isolated (reference cluster_recover_policy.h — a dying
    cluster must keep taking traffic rather than excluding everyone).

    ``redirect=True`` declares the REDIRECT policy for components that
    route over replica groups (the PS fan-out): an open breaker re-routes
    the call to the next live replica instead of raising ``BreakerOpen``
    — availability over fail-fast (SelectiveChannel's "retry picks a
    different sub-channel", selective_channel.cpp).  The registry only
    CARRIES the flag (routing lives with the router); with a single
    replica there is nowhere to redirect and open still means reject."""

    def __init__(self, options: Optional[BreakerOptions] = None,
                 clock: Callable[[], float] = time.monotonic,
                 min_working: int = 1, redirect: bool = False):
        self.options = options or BreakerOptions()
        self.min_working = min_working
        self.redirect = bool(redirect)
        self._clock = clock
        self._mu = checked_lock("resilience.breakers")
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._probes: Dict[str, Dict[str, object]] = {}

    def breaker_for(self, endpoint: str) -> CircuitBreaker:
        b = self._breakers.get(endpoint)
        if b is None:
            with self._mu:
                b = self._breakers.get(endpoint)
                if b is None:
                    b = CircuitBreaker(
                        self.options, clock=self._clock,
                        isolate_guard=self._allow_isolate, name=endpoint)
                    self._breakers[endpoint] = b
        return b

    def _allow_isolate(self) -> bool:
        """True when at least ``min_working`` endpoints would remain
        serving after one more isolation (reads sibling breakers
        lock-free — see CircuitBreaker.isolated)."""
        with self._mu:
            breakers = list(self._breakers.values())
        working = sum(1 for b in breakers if not b.isolated())
        return working - 1 >= self.min_working

    def on_call_end(self, endpoint: str, error_code: int) -> bool:
        return self.breaker_for(endpoint).on_call_end(error_code)

    def isolated(self, endpoint: str) -> bool:
        b = self._breakers.get(endpoint)
        return b is not None and b.isolated()

    def isolated_endpoints(self) -> List[str]:
        with self._mu:
            items = list(self._breakers.items())
        return [ep for ep, b in items if b.isolated()]

    def note_probe(self, endpoint: str, ok: bool, detail: str = "") -> None:
        with self._mu:
            self._probes[endpoint] = {
                "ok": ok, "at": self._clock(), "detail": detail}

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        with self._mu:
            items = list(self._breakers.items())
            probes = dict(self._probes)
        out: Dict[str, Dict[str, object]] = {}
        for ep, b in items:
            d = b.snapshot()
            if ep in probes:
                p = dict(probes[ep])
                p["age_s"] = round(self._clock() - float(p.pop("at")), 3)
                d["last_probe"] = p
            out[ep] = d
        return out


# ---------------------------------------------------------------------------
# replica scoring (the locality-aware LB analog: latency x inflight)
# ---------------------------------------------------------------------------

class ReplicaScorer:
    """Per-endpoint latency+inflight scoring for replica selection (the
    reference's ``la`` locality-aware load balancer,
    locality_aware_load_balancer.cpp / docs/cn/lalb.md, reduced to the
    two signals that matter for a read fan-out): an endpoint's score is
    ``ewma_latency * (inflight + 1)`` — expected queueing-adjusted
    completion time — and the router picks the minimum among live
    replicas.  An endpoint nothing is known about scores as the OPTIMIST
    (``prior_ms`` with its real inflight), so fresh/revived replicas get
    probed by real traffic instead of starving forever behind a warm
    sibling.

    ``note_start``/``note_end`` bracket every routed call; failures count
    as a latency PENALTY (``fail_penalty_ms`` fed to the EWMA) so a
    flapping replica scores itself out of the rotation even before its
    breaker trips.  All state is per-endpoint ints/floats under one lock;
    reads take the same lock (selection is per-batch, not per-byte)."""

    def __init__(self, alpha: float = 0.25, prior_ms: float = 1.0,
                 fail_penalty_ms: float = 100.0):
        self.alpha = alpha
        self.prior_ms = prior_ms
        self.fail_penalty_ms = fail_penalty_ms
        self._mu = checked_lock("resilience.scorer")
        self._ewma_ms: Dict[str, float] = {}
        self._inflight: Dict[str, int] = {}

    def note_start(self, endpoint: str) -> None:
        with self._mu:
            self._inflight[endpoint] = self._inflight.get(endpoint, 0) + 1

    def note_end(self, endpoint: str, latency_s: Optional[float],
                 ok: bool) -> None:
        """One routed call finished.  ``latency_s`` may be None when the
        caller could not measure (start-failure); failures feed the
        penalty either way."""
        sample_ms = (latency_s or 0.0) * 1000.0
        if not ok:
            sample_ms = max(sample_ms, self.fail_penalty_ms)
        with self._mu:
            n = self._inflight.get(endpoint, 0)
            if n > 0:
                self._inflight[endpoint] = n - 1
            prev = self._ewma_ms.get(endpoint)
            if prev is None:
                self._ewma_ms[endpoint] = sample_ms
            else:
                self._ewma_ms[endpoint] = \
                    prev + self.alpha * (sample_ms - prev)

    def score(self, endpoint: str) -> float:
        with self._mu:
            lat = self._ewma_ms.get(endpoint, self.prior_ms)
            inflight = self._inflight.get(endpoint, 0)
        return max(lat, 0.001) * (inflight + 1)

    def pick(self, candidates: List[str]) -> Optional[str]:
        """The lowest-scoring candidate (ties break by order, so a
        deterministic candidate list yields deterministic routing)."""
        best, best_score = None, None
        for ep in candidates:
            s = self.score(ep)
            if best_score is None or s < best_score:
                best, best_score = ep, s
        return best

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._mu:
            eps = set(self._ewma_ms) | set(self._inflight)
            return {ep: {"ewma_ms": round(self._ewma_ms.get(
                             ep, self.prior_ms), 3),
                         "inflight": self._inflight.get(ep, 0)}
                    for ep in sorted(eps)}

    def scoped(self, namespace: str) -> "ReplicaScorer":
        """A view of this scorer whose bookkeeping keys are prefixed
        with ``namespace`` — per-SCHEME replica scoring during a live
        reshard (the same address serving two partition schemes scores
        independently per scheme, so one scheme's routing state can
        drain without poisoning the other's).  An empty namespace is
        this scorer itself."""
        if not namespace:
            return self
        return _ScopedScorer(self, namespace)


class _ScopedScorer:
    """Key-prefixing facade over a shared :class:`ReplicaScorer` (see
    :meth:`ReplicaScorer.scoped`).  ``pick`` accepts and returns RAW
    addresses; only the score bookkeeping is namespaced."""

    __slots__ = ("_base", "_ns")

    def __init__(self, base: ReplicaScorer, namespace: str):
        self._base = base
        self._ns = namespace + "|"

    def note_start(self, endpoint: str) -> None:
        self._base.note_start(self._ns + endpoint)

    def note_end(self, endpoint: str, latency_s: Optional[float],
                 ok: bool) -> None:
        self._base.note_end(self._ns + endpoint, latency_s, ok)

    def score(self, endpoint: str) -> float:
        return self._base.score(self._ns + endpoint)

    def pick(self, candidates: List[str]) -> Optional[str]:
        best, best_score = None, None
        for ep in candidates:
            s = self.score(ep)
            if best_score is None or s < best_score:
                best, best_score = ep, s
        return best

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        full = self._base.snapshot()
        return {ep[len(self._ns):]: d for ep, d in full.items()
                if ep.startswith(self._ns)}


# ---------------------------------------------------------------------------
# health-check prober (background revival fiber)
# ---------------------------------------------------------------------------

class HealthProber:
    """Probes ISOLATED endpoints via the ``_status`` builtin's ``health``
    method and revives their breaker on success (reference
    details/health_check.cpp:146 — failed sockets get a background
    health-check loop, not permanent exile).

    ``probe_once()`` is the testable unit: snapshot the isolated set,
    probe each OUTSIDE every lock, revive on success.  ``start()`` runs
    it on a daemon thread every ``interval_ms`` (deterministically
    jittered via :class:`Backoff` so a fleet of probers doesn't
    synchronize).  Channels are cached per endpoint across probes — the
    native channel reconnects under the hood, so a probe failure does
    not invalidate it.
    """

    def __init__(self, registry: BreakerRegistry,
                 make_channel: Optional[Callable[[str], object]] = None,
                 interval_ms: float = 200.0,
                 probe_timeout_ms: int = 200):
        self.registry = registry
        self._make_channel = make_channel or self._default_channel
        self.interval_ms = interval_ms
        self.probe_timeout_ms = probe_timeout_ms
        self._backoff = Backoff(base_ms=interval_ms, multiplier=1.0,
                                max_ms=interval_ms, jitter=0.25)
        self._mu = checked_lock("resilience.prober")
        self._channels: Dict[str, object] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._ticks = 0

    def _default_channel(self, endpoint: str):
        from brpc_tpu import rpc  # lazy: see module docstring
        return rpc.Channel(endpoint, timeout_ms=self.probe_timeout_ms)

    def _channel_for(self, endpoint: str):
        ch = self._channels.get(endpoint)
        if ch is not None:
            return ch
        new = self._make_channel(endpoint)
        with self._mu:
            cur = self._channels.setdefault(endpoint, new)
        if cur is not new:  # lost a creation race: keep the winner
            new.close()
        return cur

    def probe_once(self) -> Dict[str, bool]:
        """One revival sweep; returns {endpoint: probe_ok} for every
        endpoint that was isolated when the sweep started."""
        results: Dict[str, bool] = {}
        for ep in self.registry.isolated_endpoints():
            try:
                self._channel_for(ep).call("_status", "health")
                ok, detail = True, ""
            except Exception as e:  # noqa: BLE001 — any failure = down
                ok, detail = False, f"{type(e).__name__}: {e}"[:200]
            results[ep] = ok
            self.registry.note_probe(ep, ok, detail)
            if ok:
                self.registry.breaker_for(ep).revive()
            elif obs.enabled():
                obs.counter("rpc_health_probe_failures").add(1)
        return results

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="brt-health-prober")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._ticks += 1
            # Event.wait is the loop's cadence (interruptible by stop()),
            # jittered deterministically per tick.
            if self._stop.wait(
                    self._backoff.delay_ms(self._ticks) / 1000.0):
                break
            try:
                self.probe_once()
            except Exception:  # noqa: BLE001 — prober must never die
                if obs.enabled():
                    obs.counter("rpc_health_probe_errors").add(1)

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)
        with self._mu:
            channels = list(self._channels.values())
            self._channels.clear()
        for ch in channels:
            try:
                ch.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass

    def status(self) -> Dict[str, object]:
        return {
            "running": self._thread is not None,
            "ticks": self._ticks,
            "interval_ms": self.interval_ms,
        }


# ---------------------------------------------------------------------------
# process-wide default registry (the _status health surface)
# ---------------------------------------------------------------------------

_default_mu = checked_lock("resilience.default")
_default_registry: Optional[BreakerRegistry] = None
_default_prober: Optional[HealthProber] = None


def default_registry() -> BreakerRegistry:
    """The process-wide registry (created on first use); components that
    don't pass their own BreakerRegistry share this one, and the
    ``_status`` ``health`` method reports it."""
    global _default_registry
    if _default_registry is None:
        with _default_mu:
            if _default_registry is None:
                _default_registry = BreakerRegistry()
    return _default_registry


def set_default_registry(reg: Optional[BreakerRegistry],
                         prober: Optional[HealthProber] = None) -> None:
    """Install (or clear, with None) the process-wide registry/prober
    pair the health surface reports."""
    global _default_registry, _default_prober
    with _default_mu:
        _default_registry = reg
        _default_prober = prober


def health_components() -> Dict[str, object]:
    """Structured per-component health for the ``_status`` builtin's
    ``health`` method: breaker states per endpoint + last probe results.
    ``status`` degrades to ``"degraded"`` whenever any breaker is open."""
    with _default_mu:
        reg, prober = _default_registry, _default_prober
    breakers = reg.snapshot() if reg is not None else {}
    degraded = any(d.get("state") == "open" for d in breakers.values())
    out: Dict[str, object] = {
        "status": "degraded" if degraded else "ok",
        "components": {
            "breakers": breakers,
            "racecheck": {"enabled": _race.enabled()},
            "obs": {"enabled": obs.enabled()},
        },
    }
    if prober is not None:
        out["components"]["prober"] = prober.status()
    return out
