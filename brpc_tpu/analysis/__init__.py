"""brpc_tpu.analysis — correctness tooling for the fiber/RPC fabric.

Two passes over the hazards the fabric creates (handlers running
concurrently on fiber workers with the GIL released across ctypes,
hand-placed locks, a truncation-prone ctypes boundary):

- **static** (:mod:`brpc_tpu.analysis.lint`, ``python -m
  brpc_tpu.analysis``): an AST linter with framework-specific checks —
  ``ctypes-contract``, ``fiber-shared-state``, ``obs-guard``,
  ``trace-purity``.  ``tests/test_lint_clean.py`` keeps the tree at zero
  findings.
- **dynamic** (:mod:`brpc_tpu.analysis.race`): the :func:`checked_lock`
  factory every fabric lock is created through.  Plain
  ``threading.Lock`` in steady state; under ``BRPC_TPU_RACECHECK=1`` a
  lock-order graph that reports inversion cycles (with both acquisition
  stacks) and locks held across blocking ``brt_*`` calls.

The native side carries the same tier: ``cpp/.clang-tidy``
(concurrency + bugprone) and ``cmake -DBRT_SANITIZE=thread|address``.

This module stays stdlib-only below ``obs``/``rpc`` in the import
order — both import :func:`checked_lock` from here.
"""

from brpc_tpu.analysis.race import (  # noqa: F401
    CheckedLock,
    checked_lock,
    note_blocking,
)
from brpc_tpu.analysis import race  # noqa: F401

__all__ = ["checked_lock", "CheckedLock", "note_blocking", "race"]
