"""brpc_tpu.analysis — correctness tooling for the fiber/RPC fabric.

Four layers over the hazards the fabric creates (handlers running
concurrently on fiber workers with the GIL released across ctypes,
hand-placed locks, a truncation-prone ctypes boundary, explicit-destroy
native handles):

- **call graph** (:mod:`brpc_tpu.analysis.callgraph`): a whole-package
  resolver over the tree's ASTs — module functions, methods through
  ``self``/bases, imports, ``functools.partial`` targets — that the
  static checks traverse (the lockdep/TSan polarity: interprocedural by
  construction).
- **static** (:mod:`brpc_tpu.analysis.lint`, ``python -m
  brpc_tpu.analysis``): an AST linter with framework-specific checks —
  ``ctypes-contract``, ``fiber-shared-state`` (handler-reachable
  mutation across modules), ``obs-guard``, ``trace-purity`` (transitive,
  with call chains + host-callback hazards), and ``lock-order`` (static
  inversion cycles over the ``with checked_lock`` nesting graph; locks
  resolve through module/class/parameter bindings and module-level
  literal dict containers — ``LOCKS["a"]`` binds by key).
  Findings carry stable ids; ``--baseline`` diffs against an accepted
  set.  ``tests/test_lint_clean.py`` keeps the tree at zero new
  findings.
- **dynamic** (:mod:`brpc_tpu.analysis.race`): the :func:`checked_lock`
  factory every fabric lock is created through.  Plain
  ``threading.Lock`` in steady state; under ``BRPC_TPU_RACECHECK=1`` a
  lock-order graph that confirms the static pass's cycles at runtime
  (with both acquisition stacks) and flags locks held across blocking
  ``brt_*`` calls.  ``BRPC_TPU_RACECHECK_SAMPLE=N`` keeps edge/cycle
  detection exact while sampling stack capture down to production-usable
  cost.
- **handles** (:mod:`brpc_tpu.analysis.handles`): the dynamic handle
  ledger — under ``BRPC_TPU_HANDLECHECK=1``, ``rpc._load()`` wraps every
  owning ``brt_*_new``/``_destroy`` pair so live native handles are
  tracked with creation stacks (LeakSanitizer-shaped, sampling shared
  with RACECHECK), cross-checked against the C++ side's own counters
  (``brt_debug_handle_counts``).  The static complement is the
  ``handle-lifecycle`` lint check; the tier-1 leak gate in
  ``tests/conftest.py`` asserts zero net leaked handles per native
  test.

The native side carries the same tier: ``cpp/.clang-tidy``
(concurrency + bugprone) and ``cmake -DBRT_SANITIZE=thread|address``.

This module stays stdlib-only below ``obs``/``rpc`` in the import
order — both import :func:`checked_lock` from here (``lint`` and
``callgraph`` are tool-side, imported only by the CLI and tests).
"""

from brpc_tpu.analysis.race import (  # noqa: F401
    CheckedLock,
    CheckedRWLock,
    RWLock,
    checked_lock,
    checked_rwlock,
    note_blocking,
)
from brpc_tpu.analysis import handles  # noqa: F401
from brpc_tpu.analysis import race  # noqa: F401

__all__ = ["checked_lock", "checked_rwlock", "CheckedLock",
           "CheckedRWLock", "RWLock", "note_blocking", "race", "handles"]
