"""Dynamic lock-order race detector for the fiber/RPC fabric.

The reference ships runtime concurrency tooling alongside its scheduler —
the contention profiler (/contention), bthread diagnostics, sanitizer
annotations in the fiber runtime.  This module is the Python tier's
equivalent: every lock in ``rpc``, ``ps_remote``, and ``obs`` is created
through :func:`checked_lock`, and under ``BRPC_TPU_RACECHECK=1`` each one
becomes a :class:`CheckedLock` that feeds a per-process lock-order graph.

What the harness reports (``findings()`` / ``report()``):

- **lock-inversion** — acquiring lock ``B`` while holding ``A`` records the
  edge ``A→B``; if the graph already carries a path ``B→…→A`` the two
  orders can deadlock under the right interleaving, and the finding
  captures the acquisition stacks of BOTH edges.
- **blocking-call** — the native call sites (``Channel.call``, device
  staging/fetch/execute) report into :func:`note_blocking`; if the calling
  thread holds any checked lock at that point, the lock is serialized
  across a fiber-parking native call, which collapses handler concurrency.

When ``BRPC_TPU_RACECHECK`` is unset, :func:`checked_lock` returns a plain
``threading.Lock`` — the steady-state fabric carries zero extra overhead
(asserted by ``bench_analysis.py`` / ``tests/test_race_harness.py``).

Ordering edges are keyed by lock *name*, not instance: the fabric creates
many instances per name (every reducer has a ``_mu``), and it is the
cross-site ordering discipline that prevents deadlock.  Same-name nesting
is therefore not recorded as an edge.  Stacks are captured at FIRST
observation of an edge; repeat acquisitions only bump a counter.

:func:`checked_rwlock` is the readers/writer companion (used by the PS
read-parallel serving path): off mode returns a plain :class:`RWLock`
(``with rw.read():`` shares, ``with rw.write():`` excludes), checked mode
a :class:`CheckedRWLock` whose BOTH sides feed the order graph and the
blocking-call report under the lock's one name — a read-side hold across
an inverted write-side hold deadlocks just the same.

**Sampling mode** (``BRPC_TPU_RACECHECK_SAMPLE=N`` or
:func:`set_sample`): the ~26µs/acquire checked-mode cost is almost all
stack capture.  Under sampling only every Nth acquisition per lock
captures its stack eagerly — but the FIRST observation of a new ordering
edge always captures the acquiring stack (lazily, at edge-record time),
so the order graph itself stays exact: sampling degrades stack
*context* on repeat acquisitions (shown as a placeholder), never edge or
cycle detection.  ``bench_analysis.py`` records the sampled overhead.

This module imports only the stdlib — it sits below ``obs`` and ``rpc``
in the dependency order, never above.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import traceback
from typing import Dict, List, Optional, Set, Tuple

__all__ = [
    "checked_lock", "checked_rwlock", "enabled", "set_enabled",
    "CheckedLock", "CheckedRWLock", "RWLock", "note_blocking", "findings",
    "clear", "report", "Finding", "sample_every", "set_sample",
]

_override: Optional[bool] = None
_sample_override: Optional[int] = None

#: held-stack placeholder for acquisitions whose capture was sampled out
SAMPLED_OUT = ("<stack not captured: sampled out — lower "
               "BRPC_TPU_RACECHECK_SAMPLE for full context>\n")


def enabled() -> bool:
    """True when lock checking is on (``set_enabled`` override first,
    else the ``BRPC_TPU_RACECHECK`` env var)."""
    if _override is not None:
        return _override
    return os.environ.get("BRPC_TPU_RACECHECK", "") not in (
        "", "0", "false", "off")


def set_enabled(on: Optional[bool]) -> None:
    """Force checking on/off for this process (``None`` restores the env
    var's verdict).  Affects locks created AFTER the call."""
    global _override
    _override = on


_sample_env_cache: Optional[int] = None


def sample_every() -> int:
    """Stack-capture sampling period: 1 = capture every acquisition
    (full-fidelity, ~26µs/acquire), N>1 = capture every Nth per lock
    (``set_sample`` override first, else ``BRPC_TPU_RACECHECK_SAMPLE``).
    The env var is parsed once and cached — this runs on every
    acquisition."""
    global _sample_env_cache
    if _sample_override is not None:
        return max(_sample_override, 1)
    if _sample_env_cache is None:
        try:
            _sample_env_cache = max(
                int(os.environ.get("BRPC_TPU_RACECHECK_SAMPLE", "1")), 1)
        except ValueError:
            _sample_env_cache = 1
    return _sample_env_cache


def set_sample(n: Optional[int]) -> None:
    """Force the sampling period for this process (``None`` restores the
    env var's verdict and re-reads it).  Takes effect on the next
    acquisition."""
    global _sample_override, _sample_env_cache
    _sample_override = n
    _sample_env_cache = None


@dataclasses.dataclass
class Finding:
    kind: str                 # "lock-inversion" | "blocking-call"
    locks: List[str]          # cycle path, or held locks at a blocking call
    message: str
    stacks: Dict[str, str]    # label -> formatted acquisition stack

    def format(self) -> str:
        out = [f"[{self.kind}] {self.message}"]
        for label, stack in self.stacks.items():
            out.append(f"  --- {label} ---")
            out.extend("  " + ln for ln in stack.rstrip().splitlines())
        return "\n".join(out)


# Graph state.  _state_mu is a plain lock and the ONLY lock the harness
# itself takes; nothing inside its critical sections can re-enter the
# checked path.
_state_mu = threading.Lock()
_adj: Dict[str, Set[str]] = {}
_edge_stacks: Dict[Tuple[str, str], Tuple[str, str]] = {}
_findings: List[Finding] = []
_tls = threading.local()


def _held() -> List[Tuple[str, str]]:
    """This thread's (lock name, acquisition stack) list, outermost first."""
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _stack(skip: int = 2) -> str:
    return "".join(traceback.format_stack()[:-skip])


def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """DFS path src -> dst in the order graph (None when unreachable)."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in _adj.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _note_acquire_intent(name: str,
                         acq_stack: Optional[str]) -> Optional[str]:
    """Record ordering edges BEFORE blocking on the lock, so a real
    deadlock still gets its inversion reported.  ``acq_stack`` is None
    when this acquisition was sampled out; a NEW edge then captures the
    stack lazily (first observation of an edge is always captured).
    Returns the stack actually recorded (still None when nothing needed
    it)."""
    held = _held()
    if not held:
        return acq_stack
    with _state_mu:
        for held_name, held_stack in held:
            if held_name == name:
                continue  # sibling instances of one name: not an ordering
            edge = (held_name, name)
            if edge in _edge_stacks:
                continue
            if acq_stack is None:
                # sampled out, but this edge is new: capture after all
                acq_stack = _stack(skip=3)
            # New edge: does the reverse direction already exist?
            cycle = _find_path(name, held_name)
            _adj.setdefault(held_name, set()).add(name)
            _edge_stacks[edge] = (held_stack, acq_stack)
            if cycle is None:
                continue
            rev_stacks = _edge_stacks.get(
                (cycle[0], cycle[1]), ("<unrecorded>", "<unrecorded>"))
            _findings.append(Finding(
                kind="lock-inversion",
                locks=[held_name] + cycle,
                message=(
                    f"acquiring '{name}' while holding '{held_name}' "
                    f"closes the lock-order cycle "
                    f"{' -> '.join([held_name] + cycle)} (potential "
                    f"deadlock)"),
                stacks={
                    f"'{held_name}' held here": held_stack,
                    f"'{name}' acquired here (this order)": acq_stack,
                    f"'{cycle[0]}' held here (opposite order)":
                        rev_stacks[0],
                    f"'{cycle[1]}' acquired here (opposite order)":
                        rev_stacks[1],
                },
            ))
    return acq_stack


class CheckedLock:
    """``threading.Lock`` work-alike that feeds the lock-order graph."""

    __slots__ = ("name", "_lock", "_acquires")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._acquires = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        n = sample_every()
        self._acquires += 1
        # Stack capture is ~the whole checked-mode cost; under sampling
        # only every Nth acquisition (and the first) pays it eagerly.
        acq_stack = _stack(skip=2) if n <= 1 or \
            self._acquires % n == 1 else None
        acq_stack = _note_acquire_intent(self.name, acq_stack)
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            _held().append((self.name,
                            acq_stack if acq_stack is not None
                            else SAMPLED_OUT))
        return ok

    def release(self) -> None:
        self._lock.release()
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == self.name:
                del held[i]
                break

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "CheckedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<CheckedLock {self.name!r} locked={self.locked()}>"


def checked_lock(name: str):
    """The fabric's lock factory.  Plain ``threading.Lock`` when checking
    is off (zero steady-state overhead); a named :class:`CheckedLock`
    under ``BRPC_TPU_RACECHECK=1``."""
    if not enabled():
        return threading.Lock()
    return CheckedLock(name)


class _ReaderSide:
    """Reusable ``with rw.read():`` context (state-free: safe to share
    across concurrent holders)."""

    __slots__ = ("_rw",)

    def __init__(self, rw: "RWLock"):
        self._rw = rw

    def __enter__(self) -> "_ReaderSide":
        self._rw.acquire_read()
        return self

    def __exit__(self, *exc) -> None:
        self._rw.release_read()


class _WriterSide:
    __slots__ = ("_rw",)

    def __init__(self, rw: "RWLock"):
        self._rw = rw

    def __enter__(self) -> "_WriterSide":
        self._rw.acquire_write()
        return self

    def __exit__(self, *exc) -> None:
        self._rw.release_write()


class RWLock:
    """Write-preferring readers/writer lock — the Python-tier analog of
    ``cpp/fiber/sync.h`` FiberRWLock.  ``with rw.read():`` shares with
    other readers; ``with rw.write():`` excludes everyone.  Pending
    writers block NEW readers so a read stream cannot starve a writer.
    Non-reentrant on both sides, like ``threading.Lock``."""

    __slots__ = ("_cond", "_readers", "_writer", "_wwaiters", "_r", "_w")

    def __init__(self):
        self._cond = threading.Condition(threading.Lock())
        self._readers = 0
        self._writer = False
        self._wwaiters = 0
        self._r = _ReaderSide(self)
        self._w = _WriterSide(self)

    def read(self) -> _ReaderSide:
        return self._r

    def write(self) -> _WriterSide:
        return self._w

    def acquire_read(self) -> None:
        with self._cond:
            while self._writer or self._wwaiters:
                self._cond.wait()
            self._readers += 1

    def release_read(self) -> None:
        with self._cond:
            self._readers -= 1
            if not self._readers:
                self._cond.notify_all()

    def acquire_write(self) -> None:
        with self._cond:
            self._wwaiters += 1
            while self._writer or self._readers:
                self._cond.wait()
            self._wwaiters -= 1
            self._writer = True

    def release_write(self) -> None:
        with self._cond:
            self._writer = False
            self._cond.notify_all()


class _CheckedSide:
    """One side of a :class:`CheckedRWLock` (state-free, shared)."""

    __slots__ = ("_owner", "_write")

    def __init__(self, owner: "CheckedRWLock", write: bool):
        self._owner = owner
        self._write = write

    def __enter__(self) -> "_CheckedSide":
        self._owner._enter(self._write)
        return self

    def __exit__(self, *exc) -> None:
        self._owner._exit(self._write)


class CheckedRWLock:
    """:class:`RWLock` work-alike whose read AND write sides feed the
    lock-order graph under the lock's one name — ordering edges are keyed
    by name (see module docstring), and splitting the sides would hide
    inversions between a reader and a writer of the same lock.  Sampling
    behaves exactly as on :class:`CheckedLock`."""

    __slots__ = ("name", "_rw", "_acquires")

    def __init__(self, name: str):
        self.name = name
        self._rw = RWLock()
        self._acquires = 0

    def read(self) -> _CheckedSide:
        return _CheckedSide(self, False)

    def write(self) -> _CheckedSide:
        return _CheckedSide(self, True)

    def _enter(self, write: bool) -> None:
        n = sample_every()
        self._acquires += 1
        acq_stack = _stack(skip=3) if n <= 1 or \
            self._acquires % n == 1 else None
        acq_stack = _note_acquire_intent(self.name, acq_stack)
        if write:
            self._rw.acquire_write()
        else:
            self._rw.acquire_read()
        _held().append((self.name,
                        acq_stack if acq_stack is not None
                        else SAMPLED_OUT))

    def _exit(self, write: bool) -> None:
        if write:
            self._rw.release_write()
        else:
            self._rw.release_read()
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == self.name:
                del held[i]
                break

    def __repr__(self) -> str:
        return f"<CheckedRWLock {self.name!r}>"


def checked_rwlock(name: str):
    """Readers/writer companion of :func:`checked_lock`: a plain
    :class:`RWLock` when checking is off, a named :class:`CheckedRWLock`
    under ``BRPC_TPU_RACECHECK=1``.  Both sides participate in the order
    graph and in :func:`note_blocking` held-lock reporting."""
    if not enabled():
        return RWLock()
    return CheckedRWLock(name)


def note_blocking(what: str) -> None:
    """Called by native-boundary call sites (``brt_*`` wrappers) under
    RACECHECK: flags any checked lock held across the blocking call —
    the fiber worker parks inside the native core while every other
    handler contends on the held lock."""
    held = _held()
    if not held:
        return
    names = [n for n, _ in held]
    site = _stack(skip=2)
    with _state_mu:
        for f in _findings:
            # One finding per (call, held-set) shape keeps reruns bounded.
            if f.kind == "blocking-call" and f.locks == names \
                    and what in f.message:
                return
        _findings.append(Finding(
            kind="blocking-call",
            locks=list(names),
            message=(f"lock(s) {names} held across blocking native call "
                     f"{what} — serializes fiber workers"),
            stacks={f"{what} called here": site,
                    f"'{names[-1]}' held here": held[-1][1]},
        ))


def findings() -> List[Finding]:
    with _state_mu:
        return list(_findings)


def clear() -> None:
    """Drop the order graph and findings (test isolation). Held-lock
    tracking in live threads is untouched."""
    with _state_mu:
        _adj.clear()
        _edge_stacks.clear()
        _findings.clear()


def report() -> str:
    fs = findings()
    if not fs:
        return "racecheck: no findings"
    return "\n\n".join(f.format() for f in fs)
