"""Framework-invariant AST linter for the Python tier.

The reference enforces its concurrency contracts with purpose-built
tooling (contention profiler, bthread diagnostics, builtin hazard pages);
this is the equivalent static pass for the hazards our fabric creates.
Five checks, each encoding an invariant the runtime cannot enforce, the
concurrency ones interprocedural over the whole-package call graph
(:mod:`brpc_tpu.analysis.callgraph` — the lockdep/TSan polarity: follow
the calls, not the file):

- ``ctypes-contract`` — every ``*.brt_*`` symbol used anywhere must have
  BOTH ``argtypes`` and ``restype`` declared somewhere in the scanned
  tree (``rpc._load()`` is the canonical site).  ctypes defaults an
  undeclared restype to c_int, which silently truncates 64-bit handles
  on the way out of the native core.  Also: a ``CFUNCTYPE`` callback
  passed inline to a ``brt_*`` call is owned by nobody — the native core
  keeps the raw function pointer while Python GCs the closure.
- ``fiber-shared-state`` — methods reachable from a handler registered
  via ``add_service``/``add_async_service`` run concurrently on fiber
  workers (the trampoline releases the GIL across ctypes); any mutation
  of ``self``/module state anywhere in the handler-reachable set — across
  modules, through helpers — must sit inside a ``with self._mu``-style
  block.  Rwlock sides are understood: ``with self._mu.write():`` is an
  exclusive hold, ``with self._mu.read():`` is SHARED and never
  legitimizes mutation.  Thread-local state (``self._local.*``/``*tls*``)
  is exempt.
- ``obs-guard`` — instrumentation outside ``brpc_tpu/obs`` must go
  through the no-op-able helpers (``obs.counter``/``obs.recorder``/
  ``obs.record_span``); constructing reducers or touching the Registry
  directly bypasses the ``enabled()`` gate.
- ``trace-purity`` — no wall-clock reads, ``print``, lock traffic, or
  ``obs`` calls anywhere transitively reachable (through in-package
  helpers) from a function handed to ``jax.jit``/``shard_map``; they run
  once at trace time and vanish from the compiled program.  Findings
  carry the full call chain from the traced root to the impure site.
  Host callbacks (``jax.debug.print``, ``pure_callback``/``io_callback``)
  under trace are a separate hazard class: they DON'T vanish — they
  stage a host round-trip into every step — and must be allowlisted
  per-site with ``# lint: allow-host-callback`` when intended.
  DELIBERATE trace-time effects (e.g. counters of programs built) are
  declared with ``# lint: allow-trace-impure`` on the call line or on
  the helper's ``def`` line — the walk neither flags nor descends
  there.
- ``lock-order`` — the static half of the RACECHECK harness: derives
  the ``with <checked_lock>`` nesting graph over the call graph and
  reports inversion cycles without running anything; the dynamic
  harness (:mod:`brpc_tpu.analysis.race`) becomes the confirmer, not
  the only detector.  ``checked_rwlock`` participates too: both
  ``.read()`` and ``.write()`` contexts acquire under the lock's one
  name, matching the dynamic graph's keying.
- ``fiber-blocking-sleep`` — a bare ``time.sleep`` anywhere
  handler-reachable (interprocedural, same walk as
  ``fiber-shared-state``) parks the fiber worker PTHREAD, not just the
  fiber, stalling every handler scheduled on that worker.  The
  sanctioned path is :mod:`brpc_tpu.resilience` (``sleep_ms`` +
  ``Backoff``: deadline-capped, deterministically jittered) — calls
  resolving into that module are not followed, and its own sleeps are
  exempt.

Findings carry a stable id (hash of check + package-relative path +
message, deliberately line-free) so CI can diff against an accepted
baseline (``--baseline FILE`` suppresses known ids; ``--write-baseline``
emits one).

Entry points: :func:`run_lint` (in-process, returns findings) and
:func:`main` (the ``python -m brpc_tpu.analysis`` CLI; exit 0 = clean,
1 = findings, 2 = usage error — unknown ``--check`` names are rejected
with the valid set listed).
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import hashlib
import json
import os
import sys
from typing import (Dict, Iterable, List, Optional, Sequence, Set, Tuple)

from brpc_tpu.analysis.callgraph import (CallGraph, FuncNode,
                                         build_callgraph)

__all__ = ["Finding", "run_lint", "lint_files", "main", "ALL_CHECKS",
           "load_baseline", "apply_baseline"]

ALL_CHECKS = ("ctypes-contract", "fiber-shared-state", "obs-guard",
              "trace-purity", "lock-order", "fiber-blocking-sleep")

#: checks that need the whole-package call graph
_GRAPH_CHECKS = {"fiber-shared-state", "trace-purity", "lock-order",
                 "fiber-blocking-sleep"}

#: attribute names that look like a lock on self / a module
_LOCKISH = ("mu", "lock", "mutex")
#: rwlock side methods (checked_rwlock's read()/write() contexts)
_RW_SIDES = ("read", "write")
#: container methods that mutate their receiver in place
_MUTATORS = {
    "append", "appendleft", "extend", "insert", "remove", "pop", "popleft",
    "clear", "update", "setdefault", "add", "discard", "sort", "reverse",
}
#: obs surface that hot paths must NOT touch directly (the no-op-able
#: helpers counter/recorder/record_span/span/enabled stay allowed)
_OBS_GUARDED = {
    "Registry", "default_registry", "expose", "Adder", "Maxer", "Miner",
    "LatencyRecorder", "Window", "PerSecond", "PassiveStatus",
}
_TRACERS = {"jit", "shard_map", "pjit"}
_TIME_FNS = {"time", "time_ns", "monotonic", "monotonic_ns",
             "perf_counter", "perf_counter_ns", "sleep"}
#: bare/attr names that stage a host callback into a traced program
_HOST_CALLBACKS = {"pure_callback", "io_callback"}
#: per-site pragma that allowlists a host callback under trace
_ALLOW_HOST_CB = "lint: allow-host-callback"
#: pragma declaring DELIBERATE trace-time impurity: on a call line, the
#: call is neither flagged nor followed from traced roots; on a `def`
#: line, traced walks never descend into that function (the canonical
#: use: trace-time instrumentation like collective program counters,
#: which by design runs once per trace and must not be reported as a
#: vanishing side effect)
_ALLOW_TRACE_IMPURE = "lint: allow-trace-impure"


def _stable_path(path: str) -> str:
    """Package-relative posix path (machine-independent id component)."""
    parts = os.path.normpath(path).replace(os.sep, "/").split("/")
    if "brpc_tpu" in parts:
        return "/".join(parts[parts.index("brpc_tpu"):])
    return parts[-1]


@dataclasses.dataclass
class Finding:
    check: str
    path: str
    line: int
    message: str
    #: stable id: hash over check + package-relative path + message (no
    #: line number, so pure drift doesn't churn baselines)
    id: str = ""

    def __post_init__(self) -> None:
        if not self.id:
            raw = f"{self.check}|{_stable_path(self.path)}|{self.message}"
            self.id = hashlib.sha1(raw.encode()).hexdigest()[:12]

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.check}:{self.id}] " \
               f"{self.message}"

    def to_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# small AST helpers
# ---------------------------------------------------------------------------

def _last_name(expr: ast.AST) -> Optional[str]:
    """'jax.jit' -> 'jit', 'jit' -> 'jit', else None."""
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _root_name(expr: ast.AST) -> Optional[str]:
    """'a.b.c' -> 'a' (the base Name of a dotted chain)."""
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        expr = expr.value
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _is_self_rooted(expr: ast.AST) -> bool:
    return _root_name(expr) == "self"


def _is_tls_path(expr: ast.AST) -> bool:
    """True for thread-local chains (``self._local.cell``) — per-thread
    state needs no lock."""
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        if isinstance(expr, ast.Attribute):
            low = expr.attr.lower()
            if "local" in low or "tls" in low:
                return True
        expr = expr.value
    return False


def _is_lockish_ctx(expr: ast.AST) -> bool:
    """True for `with self._mu:` / `with _load_mu:` style context exprs,
    including rwlock sides (`with self._mu.read():` / `.write()`)."""
    name = None
    if isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Name):
        name = expr.id
    elif isinstance(expr, ast.Call):
        f = expr.func
        if isinstance(f, ast.Attribute) and f.attr in _RW_SIDES:
            # with self._mu.read()/.write(): lockish iff the receiver is
            return _is_lockish_ctx(f.value)
        # with self._mu.acquire_timeout(...) style — treat lock method
        # calls on a lockish receiver as lock context too
        return _is_lockish_ctx(f)
    if name is None:
        return False
    low = name.lower()
    return any(part in low for part in _LOCKISH)


def _lock_ctx_kind(expr: ast.AST) -> Optional[str]:
    """Classify a with-item context: ``"lock"`` for exclusive holds
    (plain locks, rwlock ``.write()``), ``"read"`` for the SHARED rwlock
    side, ``None`` for non-lock contexts.  The distinction matters to
    `fiber-shared-state`: a read-side hold serializes against writers but
    not against sibling readers, so it must never legitimize mutation."""
    if not _is_lockish_ctx(expr):
        return None
    if isinstance(expr, ast.Call) and \
            isinstance(expr.func, ast.Attribute) and \
            expr.func.attr == "read":
        return "read"
    return "lock"


def _describe(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse of synthetic nodes
        return "<expr>"


def _local_binds(fn: ast.AST) -> Set[str]:
    """Names bound locally inside ``fn`` (params, plain assigns, loop and
    with targets) — these shadow module globals for the shared-state
    check."""
    out: Set[str] = set()
    args = getattr(fn, "args", None)
    if args is not None:
        for a in (list(args.posonlyargs) + list(args.args) +
                  list(args.kwonlyargs)):
            out.add(a.arg)
        if args.vararg:
            out.add(args.vararg.arg)
        if args.kwarg:
            out.add(args.kwarg.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Global):
            out -= set(node.names)  # `global x` un-shadows
            continue
        tgt_lists: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            tgt_lists = list(node.targets)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            tgt_lists = [node.target]
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            tgt_lists = [node.target]
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            tgt_lists = [i.optional_vars for i in node.items
                         if i.optional_vars is not None]
        for tgt in tgt_lists:
            if isinstance(tgt, ast.Name):
                out.add(tgt.id)
            elif isinstance(tgt, (ast.Tuple, ast.List)):
                for leaf in ast.walk(tgt):
                    if isinstance(leaf, ast.Name):
                        out.add(leaf.id)
    return out


def _node_display(node: FuncNode) -> str:
    if node.cls is not None:
        return f"{node.cls}.{node.name}"
    if node.qual == "<module>":
        return f"{node.module}:<module>"
    return node.qual


# ---------------------------------------------------------------------------
# per-file scan state
# ---------------------------------------------------------------------------

class _FileScan:
    """One parsed file plus everything the checks extract from it."""

    def __init__(self, path: str, tree: ast.Module,
                 src_lines: Optional[List[str]] = None):
        self.path = path
        self.tree = tree
        self.src_lines = src_lines or []
        # ctypes-contract
        self.native_decls: Dict[str, Set[str]] = {}  # brt_x -> declared kinds
        self.native_uses: List[Tuple[str, int]] = []  # (brt_x, line)
        self.cfunctype_protos: Set[str] = set()
        # obs-guard bookkeeping: names bound to obs modules / obs imports
        self.obs_module_aliases: Set[str] = set()
        self.obs_imported_names: Set[str] = set()
        self._collect()

    def _collect(self) -> None:
        decl_nodes: Set[int] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    self._note_decl(tgt, decl_nodes)
                if isinstance(node.value, ast.Call) and \
                        _last_name(node.value.func) == "CFUNCTYPE":
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            self.cfunctype_protos.add(tgt.id)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.endswith(".obs") or ".obs." in alias.name:
                        self.obs_module_aliases.add(
                            alias.asname or alias.name.split(".")[-1])
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod == "brpc_tpu" or mod.endswith(".obs"):
                    for alias in node.names:
                        if alias.name == "obs" or mod.endswith(".obs"):
                            tgt = alias.asname or alias.name
                            if alias.name == "obs":
                                self.obs_module_aliases.add(tgt)
                            else:
                                self.obs_imported_names.add(tgt)
                elif ".obs." in mod or mod.startswith("obs."):
                    for alias in node.names:
                        self.obs_imported_names.add(alias.asname or alias.name)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Attribute) and \
                    node.attr.startswith("brt_") and id(node) not in decl_nodes:
                self.native_uses.append((node.attr, node.lineno))

    def _note_decl(self, tgt: ast.AST, decl_nodes: Set[int]) -> None:
        if isinstance(tgt, ast.Attribute) and \
                tgt.attr in ("argtypes", "restype") and \
                isinstance(tgt.value, ast.Attribute) and \
                tgt.value.attr.startswith("brt_"):
            self.native_decls.setdefault(tgt.value.attr, set()).add(tgt.attr)
            decl_nodes.add(id(tgt.value))

    def line_has(self, lineno: int, marker: str) -> bool:
        if 1 <= lineno <= len(self.src_lines):
            return marker in self.src_lines[lineno - 1]
        return False


# ---------------------------------------------------------------------------
# check: ctypes-contract
# ---------------------------------------------------------------------------

def _check_ctypes_contract(scans: List[_FileScan]) -> List[Finding]:
    findings: List[Finding] = []
    decls: Dict[str, Set[str]] = {}
    for sc in scans:
        for name, kinds in sc.native_decls.items():
            decls.setdefault(name, set()).update(kinds)
    reported: Set[Tuple[str, str]] = set()
    for sc in scans:
        for name, line in sc.native_uses:
            have = decls.get(name, set())
            missing = [k for k in ("argtypes", "restype") if k not in have]
            if not missing or (name, sc.path) in reported:
                continue
            reported.add((name, sc.path))
            findings.append(Finding(
                "ctypes-contract", sc.path, line,
                f"native symbol '{name}' used without "
                f"{' and '.join(missing)} declared anywhere in the scanned "
                f"tree (ctypes defaults restype to c_int — 64-bit handles "
                f"truncate); declare it in rpc._load()"))
    for sc in scans:
        findings.extend(_check_cfunctype_pinning(sc))
    return findings


def _check_cfunctype_pinning(sc: _FileScan) -> List[Finding]:
    protos = sc.cfunctype_protos
    if not protos:
        return []
    findings: List[Finding] = []
    # 1) inline construction passed straight to the native core (one walk
    #    over the whole tree so each call site reports exactly once)
    for node in ast.walk(sc.tree):
        if not isinstance(node, ast.Call):
            continue
        fn_last = _last_name(node.func)
        if fn_last is None or not fn_last.startswith("brt_"):
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Call) and _last_name(arg.func) in protos:
                findings.append(Finding(
                    "ctypes-contract", sc.path, arg.lineno,
                    f"CFUNCTYPE callback constructed inline in a "
                    f"'{fn_last}' call — nothing owns it and the GC frees "
                    f"it under the native core's feet; store it on the "
                    f"owner object first"))
    # 2) named callbacks passed to the native core but never pinned.
    #    Callbacks are attributed to the scope that DIRECTLY defines them;
    #    pinning/passing is searched through that whole scope subtree.
    #    MODULE-scope callbacks are exempt: a module-level name is held by
    #    the module namespace for the life of the process — it cannot be
    #    GC'd under the native core (only function locals can).
    scopes: List[ast.AST] = [
        n for n in ast.walk(sc.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for scope in scopes:
        callbacks = _callback_locals_shallow(scope, protos)
        if not callbacks:
            continue
        passed_to_native: Dict[str, int] = {}
        pinned: Set[str] = set()
        # `global X; X = cb` pins on the module namespace — as immortal
        # as self.<attr> on a long-lived owner.
        declared_global: Set[str] = set()
        for node in ast.walk(scope):
            if isinstance(node, ast.Global):
                declared_global.update(node.names)
        for node in ast.walk(scope):
            if isinstance(node, ast.Call):
                fn_last = _last_name(node.func)
                is_native = fn_last is not None and fn_last.startswith("brt_")
                for arg in list(node.args) + \
                        [kw.value for kw in node.keywords]:
                    if isinstance(arg, ast.Name) and arg.id in callbacks:
                        if is_native:
                            passed_to_native.setdefault(arg.id, arg.lineno)
                        else:
                            # arg of append()/add()/...: the owner keeps it
                            pinned.add(arg.id)
            elif isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id in callbacks:
                for tgt in node.targets:
                    if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                        pinned.add(node.value.id)
                    elif isinstance(tgt, ast.Name) and \
                            tgt.id in declared_global:
                        pinned.add(node.value.id)
        for name, line in sorted(passed_to_native.items()):
            if name not in pinned:
                findings.append(Finding(
                    "ctypes-contract", sc.path, line,
                    f"CFUNCTYPE callback '{name}' is passed to the native "
                    f"core but never pinned on an owner object "
                    f"(self.<attr> = {name} or self.<list>.append({name})) "
                    f"— it is GC'd while the core still holds the pointer"))
    return findings


def _callback_locals_shallow(scope: ast.AST, protos: Set[str]
                             ) -> Dict[str, int]:
    """Callback names defined as DIRECT children of the scope (nested
    function scopes audit their own callbacks)."""
    out: Dict[str, int] = {}
    body = scope.body if hasattr(scope, "body") else []
    for node in body:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                and _last_name(node.value.func) in protos:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = node.lineno
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _last_name(dec) in protos:
                    out[node.name] = node.lineno
    return out


# ---------------------------------------------------------------------------
# check: fiber-shared-state (interprocedural over the call graph)
# ---------------------------------------------------------------------------

def _find_handler_roots(sc: _FileScan, graph: CallGraph,
                        top: Optional[FuncNode]) -> List[str]:
    """Node ids of handlers registered via add_service/add_async_service
    anywhere in this file (``self.X`` methods, bare function names,
    partial targets)."""
    roots: List[str] = []

    def visit(node: ast.AST, ctx: Optional[FuncNode]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            inner = graph.node_for_ast(node)
            for child in ast.iter_child_nodes(node):
                visit(child, inner or ctx)
            return
        if isinstance(node, ast.Call) and ctx is not None and \
                _last_name(node.func) in ("add_service",
                                          "add_async_service"):
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                tgt = graph.resolve_callable_expr(arg, ctx)
                if tgt is not None:
                    roots.append(tgt)
        for child in ast.iter_child_nodes(node):
            visit(child, ctx)

    visit(sc.tree, top)
    return roots


def _check_fiber_shared_state(scans: List[_FileScan],
                              graph: CallGraph) -> List[Finding]:
    sc_by_path = {sc.path: sc for sc in scans}
    mi_by_path = {mi.path: mi for mi in graph.modules.values()}
    roots: List[str] = []
    for sc in scans:
        mi = mi_by_path.get(sc.path)
        top = graph.nodes.get(f"{mi.name}:<module>") if mi else None
        roots.extend(_find_handler_roots(sc, graph, top))
    findings: List[Finding] = []
    visited: Set[Tuple[str, bool]] = set()
    queue: List[Tuple[str, bool, Tuple[str, ...]]] = [
        (r, False, (_node_display(graph.nodes[r]),))
        for r in roots if r in graph.nodes]
    while queue:
        node_id, locked, chain = queue.pop()
        if (node_id, locked) in visited:
            continue
        visited.add((node_id, locked))
        node = graph.nodes.get(node_id)
        if node is None or node.path not in sc_by_path:
            continue
        _scan_shared_state(sc_by_path[node.path], graph, node, locked,
                           chain, queue, findings)
    return findings


def _scan_shared_state(sc: _FileScan, graph: CallGraph, node: FuncNode,
                       locked0: bool, chain: Tuple[str, ...],
                       queue: List[Tuple[str, bool, Tuple[str, ...]]],
                       findings: List[Finding]) -> None:
    fn = node.fn
    mi = graph.modules[node.module]
    display = _node_display(node)
    global_names = {name for n in ast.walk(fn) if isinstance(n, ast.Global)
                    for name in n.names}
    mod_state = (mi.module_globals - _local_binds(fn)) | global_names
    # A constructor mutating its OWN self is initializing an object no
    # other fiber can see yet (publication happens after __init__
    # returns) — never a race.  Module-state mutation in a reachable
    # __init__ still counts.
    fresh_self = node.name == "__init__"

    def mutation(n: ast.AST, what: str, in_read: bool = False) -> None:
        via = ""
        if len(chain) > 1:
            via = f" [reached via {' -> '.join(chain)}]"
        hint = (" (a read-side `.read()` hold is SHARED — sibling "
                "readers run concurrently; mutation needs the write "
                "side)" if in_read else "")
        findings.append(Finding(
            "fiber-shared-state", sc.path, n.lineno,
            f"handler-reachable {display} mutates {what} outside a "
            f"`with self._mu` block{hint} — handlers run concurrently on "
            f"fiber workers (the ctypes trampoline releases the GIL)"
            f"{via}"))

    def scan(n: ast.AST, locked: bool, in_read: bool = False) -> None:
        if isinstance(n, (ast.With, ast.AsyncWith)):
            kinds = [_lock_ctx_kind(item.context_expr) for item in n.items]
            now_locked = locked or "lock" in kinds
            now_read = (in_read or "read" in kinds) and not now_locked
            for item in n.items:
                scan(item.context_expr, locked, in_read)
            for child in n.body:
                scan(child, now_locked, now_read)
            return
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            return  # nested defs get their own audit when reachable
        if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = n.targets if isinstance(n, ast.Assign) else [n.target]
            for tgt in targets:
                if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                    if _is_tls_path(tgt) or locked:
                        continue
                    if node.cls is not None and _is_self_rooted(tgt):
                        if not fresh_self:
                            mutation(tgt, _describe(tgt), in_read)
                    else:
                        root = _root_name(tgt)
                        if root is not None and root in mod_state:
                            mutation(tgt, f"module state "
                                          f"'{_describe(tgt)}'", in_read)
                elif isinstance(tgt, ast.Name) and tgt.id in global_names \
                        and not locked:
                    mutation(tgt, f"module global '{tgt.id}'", in_read)
        if isinstance(n, ast.Call):
            f = n.func
            if isinstance(f, ast.Attribute) and not locked:
                if f.attr == "at" and n.args and not _is_tls_path(n.args[0]):
                    # np.<ufunc>.at(self.table, ...) mutates in place
                    if node.cls is not None and _is_self_rooted(n.args[0]):
                        if not fresh_self:
                            mutation(n, _describe(n.args[0]), in_read)
                    elif isinstance(n.args[0], ast.Name) and \
                            n.args[0].id in mod_state:
                        mutation(n, f"module state '{n.args[0].id}'",
                                 in_read)
                elif f.attr in _MUTATORS and not _is_tls_path(f.value) \
                        and graph.call_target(n) is None:
                    # A receiver whose method RESOLVES in the call graph
                    # (attr-type/local-type map) is not a raw container:
                    # the interprocedural walk below analyzes the callee's
                    # body — its own mutations get checked against its own
                    # locking, so the heuristic must not double-report
                    # (e.g. an internally-synchronized combiner's .add()).
                    if node.cls is not None and _is_self_rooted(f.value):
                        if not fresh_self:
                            mutation(n, f"{_describe(f.value)} "
                                        f"(via .{f.attr}())", in_read)
                    elif isinstance(f.value, ast.Name) and \
                            f.value.id in mod_state:
                        mutation(n, f"module state '{f.value.id}' "
                                    f"(via .{f.attr}())", in_read)
            tgt = graph.call_target(n)
            if tgt is not None and tgt in graph.nodes:
                # Lock context propagates through calls; a read-side hold
                # does NOT (the callee's mutations still race siblings).
                queue.append((tgt, locked,
                              chain + (_node_display(graph.nodes[tgt]),)))
        for child in ast.iter_child_nodes(n):
            scan(child, locked, in_read)

    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for child in body:
        scan(child, locked0)


# ---------------------------------------------------------------------------
# check: fiber-blocking-sleep (interprocedural over the call graph)
# ---------------------------------------------------------------------------

def _is_sanctioned_sleep_module(path: str) -> bool:
    """The resilience module OWNS blocking sleeps (``sleep_ms`` /
    ``Backoff`` — deadline-capped, deterministically jittered); its
    internals are exempt and calls resolving into it are not followed."""
    return _stable_path(path).startswith("brpc_tpu/resilience")


def _time_sleep_aliases(sc: _FileScan) -> Tuple[Set[str], Set[str]]:
    """(module aliases of ``time``, bare names bound to ``time.sleep``)
    in this file."""
    mods: Set[str] = set()
    bares: Set[str] = set()
    for node in ast.walk(sc.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "time":
                    mods.add(alias.asname or "time")
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name == "sleep":
                    bares.add(alias.asname or "sleep")
    return mods, bares


def _check_fiber_blocking_sleep(scans: List[_FileScan],
                                graph: CallGraph) -> List[Finding]:
    sc_by_path = {sc.path: sc for sc in scans}
    mi_by_path = {mi.path: mi for mi in graph.modules.values()}
    aliases: Dict[str, Tuple[Set[str], Set[str]]] = {}
    roots: List[str] = []
    for sc in scans:
        mi = mi_by_path.get(sc.path)
        top = graph.nodes.get(f"{mi.name}:<module>") if mi else None
        roots.extend(_find_handler_roots(sc, graph, top))
    findings: List[Finding] = []
    visited: Set[str] = set()
    queue: List[Tuple[str, Tuple[str, ...]]] = [
        (r, (_node_display(graph.nodes[r]),))
        for r in roots if r in graph.nodes]
    while queue:
        node_id, chain = queue.pop()
        if node_id in visited:
            continue
        visited.add(node_id)
        node = graph.nodes.get(node_id)
        if node is None or node.path not in sc_by_path:
            continue
        if _is_sanctioned_sleep_module(node.path):
            continue
        sc = sc_by_path[node.path]
        if sc.path not in aliases:
            aliases[sc.path] = _time_sleep_aliases(sc)
        time_mods, sleep_bares = aliases[sc.path]
        display = _node_display(node)

        def flag(n: ast.AST, desc: str) -> None:
            via = f" [reached via {' -> '.join(chain)}]" \
                if len(chain) > 1 else ""
            findings.append(Finding(
                "fiber-blocking-sleep", sc.path, n.lineno,
                f"handler-reachable {display} calls {desc} — it parks "
                f"the fiber worker PTHREAD (not just the fiber), "
                f"stalling every handler scheduled on it; use "
                f"brpc_tpu.resilience sleep_ms/Backoff (deadline-capped "
                f"backoff) or an event wait{via}"))

        def scan(n: ast.AST) -> None:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
                return  # nested defs audit when reachable themselves
            if isinstance(n, ast.Call):
                f = n.func
                if isinstance(f, ast.Attribute) and f.attr == "sleep" \
                        and _root_name(f) in time_mods:
                    flag(n, f"{_describe(f)}()")
                elif isinstance(f, ast.Name) and f.id in sleep_bares:
                    flag(n, f"{f.id}() (imported from time)")
                tgt = graph.call_target(n)
                if tgt is not None and tgt in graph.nodes and \
                        not _is_sanctioned_sleep_module(
                            graph.nodes[tgt].path):
                    queue.append(
                        (tgt, chain + (_node_display(graph.nodes[tgt]),)))
            for child in ast.iter_child_nodes(n):
                scan(child)

        body = node.fn.body if isinstance(node.fn.body, list) \
            else [node.fn.body]
        for child in body:
            scan(child)
    return findings


# ---------------------------------------------------------------------------
# check: obs-guard
# ---------------------------------------------------------------------------

def _in_pkg_dir(path: str, dirname: str) -> bool:
    parts = os.path.normpath(path).split(os.sep)
    return dirname in parts


def _check_obs_guard(sc: _FileScan) -> List[Finding]:
    if _in_pkg_dir(sc.path, "obs"):
        return []  # the obs package itself owns the Registry
    findings: List[Finding] = []
    for node in ast.walk(sc.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        hit: Optional[str] = None
        if isinstance(fn, ast.Name) and fn.id in _OBS_GUARDED and \
                fn.id in sc.obs_imported_names:
            hit = fn.id
        elif isinstance(fn, ast.Attribute) and fn.attr in _OBS_GUARDED:
            root = _root_name(fn)
            if root in sc.obs_module_aliases:
                hit = f"{root}.{fn.attr}"
            elif fn.attr == "expose" and isinstance(fn.value, ast.Call) and \
                    _last_name(fn.value.func) in _OBS_GUARDED:
                hit = f"{_describe(fn.value.func)}().expose"
        if hit:
            findings.append(Finding(
                "obs-guard", sc.path, node.lineno,
                f"direct obs call '{hit}' outside brpc_tpu/obs — hot-path "
                f"instrumentation must use the no-op-able helpers "
                f"(obs.counter / obs.recorder / obs.record_span) so "
                f"disabling observability disables the cost"))
    return findings


# ---------------------------------------------------------------------------
# check: trace-purity (interprocedural over the call graph)
# ---------------------------------------------------------------------------

def _is_tracer_expr(expr: ast.AST) -> bool:
    return _last_name(expr) in _TRACERS


def _is_tracing_decorator(dec: ast.AST) -> bool:
    if _is_tracer_expr(dec):
        return True
    if isinstance(dec, ast.Call):
        if _is_tracer_expr(dec.func):
            return True  # @jax.jit(...) / @shard_map(mesh=...)
        if _last_name(dec.func) == "partial" and dec.args and \
                _is_tracer_expr(dec.args[0]):
            return True  # @partial(jax.jit, ...) / @partial(shard_map, ...)
    return False


def _traced_functions(tree: ast.Module) -> List[ast.AST]:
    traced: List[ast.AST] = []
    by_name: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name[node.name] = node
            if any(_is_tracing_decorator(d) for d in node.decorator_list):
                traced.append(node)
        elif isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Lambda):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    by_name[tgt.id] = node.value
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_tracer_expr(node.func) \
                and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Lambda):
                traced.append(arg)
            elif isinstance(arg, ast.Name) and arg.id in by_name:
                traced.append(by_name[arg.id])
    # dedup while keeping order
    seen: Set[int] = set()
    out = []
    for fn in traced:
        if id(fn) not in seen:
            seen.add(id(fn))
            out.append(fn)
    return out


def _host_callback_desc(node: ast.Call) -> Optional[str]:
    f = node.func
    if _last_name(f) in _HOST_CALLBACKS:
        return _describe(f)
    if isinstance(f, ast.Attribute) and \
            f.attr in ("print", "callback", "breakpoint") and \
            _last_name(f.value) == "debug":
        return _describe(f)  # jax.debug.print / debug.callback / ...
    return None


def _check_trace_purity(scans: List[_FileScan],
                        graph: CallGraph) -> List[Finding]:
    findings: List[Finding] = []
    sc_by_path = {sc.path: sc for sc in scans}
    for sc in scans:
        for fn in _traced_functions(sc.tree):
            root_name = getattr(fn, "name", "<lambda>")
            _walk_traced(sc, fn, root_name, graph, sc_by_path, findings)
    return findings


def _walk_traced(root_sc: _FileScan, root_fn: ast.AST, root_name: str,
                 graph: CallGraph, sc_by_path: Dict[str, _FileScan],
                 findings: List[Finding]) -> None:
    scanned: Set[int] = set()
    visited_nodes: Set[str] = set()
    # (fn ast, owning scan, display name, chain from the traced root)
    stack: List[Tuple[ast.AST, _FileScan, str, Tuple[str, ...]]] = [
        (root_fn, root_sc, root_name, (root_name,))]

    def impure(sc: _FileScan, node: ast.AST, name: str,
               chain: Tuple[str, ...], what: str) -> None:
        if len(chain) > 1:
            where = (f"{what} inside '{name}' reached from traced "
                     f"'{root_name}' via call chain {' -> '.join(chain)}")
        else:
            where = (f"{what} inside '{name}' which is traced by "
                     f"jax.jit/shard_map")
        findings.append(Finding(
            "trace-purity", sc.path, node.lineno,
            f"{where} — it runs once at trace time and vanishes from the "
            f"compiled program"))

    def host_cb(sc: _FileScan, node: ast.AST, name: str,
                chain: Tuple[str, ...], desc: str) -> None:
        via = (f" via call chain {' -> '.join(chain)}"
               if len(chain) > 1 else "")
        findings.append(Finding(
            "trace-purity", sc.path, node.lineno,
            f"host callback '{desc}' inside '{name}' under "
            f"jax.jit/shard_map trace{via} — it stages a host round-trip "
            f"into every compiled step; allowlist the site with "
            f"`# {_ALLOW_HOST_CB}` if intended"))

    while stack:
        fn, sc, name, chain = stack.pop()
        if id(fn) in scanned:
            continue
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                scanned.add(id(node))
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if _is_lockish_ctx(item.context_expr):
                        impure(sc, node, name, chain,
                               f"lock acquisition "
                               f"'{_describe(item.context_expr)}'")
            if not isinstance(node, ast.Call):
                continue
            if sc.line_has(node.lineno, _ALLOW_TRACE_IMPURE):
                continue  # declared deliberate trace-time effect
            cb = _host_callback_desc(node)
            if cb is not None and not sc.line_has(node.lineno,
                                                 _ALLOW_HOST_CB):
                host_cb(sc, node, name, chain, cb)
            f = node.func
            if isinstance(f, ast.Name) and f.id == "print":
                impure(sc, node, name, chain, "print()")
            elif isinstance(f, ast.Attribute):
                root = _root_name(f)
                if root == "time" and f.attr in _TIME_FNS:
                    impure(sc, node, name, chain,
                           f"wall-clock call time.{f.attr}()")
                elif f.attr in ("acquire", "release") and \
                        _is_lockish_ctx(f.value):
                    impure(sc, node, name, chain,
                           f"lock call '{_describe(f)}()'")
                elif root == "obs" or root in sc.obs_module_aliases:
                    impure(sc, node, name, chain,
                           f"obs instrumentation '{_describe(f)}()'")
                elif root == "threading" and f.attr in ("Lock", "RLock"):
                    impure(sc, node, name, chain, "lock construction")
            tgt = graph.call_target(node)
            if tgt is not None and tgt not in visited_nodes:
                visited_nodes.add(tgt)
                callee = graph.nodes.get(tgt)
                if callee is None or callee.qual == "<module>":
                    continue
                callee_sc = sc_by_path.get(callee.path)
                if callee_sc is not None and callee_sc.line_has(
                        getattr(callee.fn, "lineno", 0),
                        _ALLOW_TRACE_IMPURE):
                    continue  # def-level: deliberate trace-time function
                if callee_sc is not None and id(callee.fn) not in scanned:
                    stack.append((callee.fn, callee_sc,
                                  _node_display(callee),
                                  chain + (_node_display(callee),)))


# ---------------------------------------------------------------------------
# check: lock-order (static inversion cycles over the call graph)
# ---------------------------------------------------------------------------

def _collect_checked_locks(scans: List[_FileScan], graph: CallGraph
                           ) -> Tuple[Dict[str, Dict[str, str]],
                                      Dict[Tuple[str, str], Dict[str, str]]]:
    """Map ``x = checked_lock("name")`` assignments to lock names:
    per-module ``var -> name`` and per-class ``self.attr -> name``."""
    mi_by_path = {mi.path: mi for mi in graph.modules.values()}
    mod_locks: Dict[str, Dict[str, str]] = {}
    cls_locks: Dict[Tuple[str, str], Dict[str, str]] = {}

    def lock_name(value: ast.AST) -> Optional[str]:
        if isinstance(value, ast.Call) and \
                _last_name(value.func) in ("checked_lock",
                                           "checked_rwlock") and \
                value.args and \
                isinstance(value.args[0], ast.Constant) and \
                isinstance(value.args[0].value, str):
            return value.args[0].value
        return None

    for sc in scans:
        mi = mi_by_path.get(sc.path)
        if mi is None:
            continue
        for node in ast.walk(sc.tree):
            if not isinstance(node, ast.Assign):
                continue
            name = lock_name(node.value)
            if name is None:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    mod_locks.setdefault(mi.name, {})[tgt.id] = name
        for stmt in sc.tree.body:
            if not isinstance(stmt, ast.ClassDef):
                continue
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Assign):
                    continue
                name = lock_name(node.value)
                if name is None:
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == "self":
                        cls_locks.setdefault(
                            (mi.name, stmt.name), {})[tgt.attr] = name
    return mod_locks, cls_locks


def _order_path(adj: Dict[str, Set[str]], src: str,
                dst: str) -> Optional[List[str]]:
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in sorted(adj.get(node, ())):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _check_lock_order(scans: List[_FileScan],
                      graph: CallGraph) -> List[Finding]:
    mod_locks, cls_locks = _collect_checked_locks(scans, graph)
    if not mod_locks and not cls_locks:
        return []

    def resolve_lock(expr: ast.AST, node: FuncNode) -> Optional[str]:
        if isinstance(expr, ast.Call):
            # rwlock sides: `with rw.read():` / `.write()` acquire under
            # the lock's one name, exactly as the dynamic harness keys
            # them (a read-vs-write split would hide r/w inversions).
            f = expr.func
            if isinstance(f, ast.Attribute) and f.attr in _RW_SIDES:
                return resolve_lock(f.value, node)
            return None
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) and expr.value.id == "self" \
                    and node.cls is not None:
                return cls_locks.get((node.module, node.cls),
                                     {}).get(expr.attr)
            root = _root_name(expr)
            if root is None:
                return None
            mi = graph.modules[node.module]
            target_name = mi.import_aliases.get(root)
            if target_name is None and root in mi.from_imports:
                m, orig = mi.from_imports[root]
                target_name = f"{m}.{orig}" if m else orig
            if target_name:
                target = graph._find_module(target_name)
                if target is not None:
                    return mod_locks.get(target.name, {}).get(expr.attr)
            return None
        if isinstance(expr, ast.Name):
            return mod_locks.get(node.module, {}).get(expr.id)
        return None

    # acquisition edges: (held, acquired) -> first site (path, line, chain)
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    adj: Dict[str, Set[str]] = {}
    memo: Set[Tuple[str, Tuple[str, ...]]] = set()

    def walk(node_id: str, held: Tuple[str, ...],
             chain: Tuple[str, ...]) -> None:
        key = (node_id, tuple(sorted(set(held))))
        if key in memo or len(chain) > 25:
            return
        memo.add(key)
        node = graph.nodes.get(node_id)
        if node is None:
            return

        def scan(n: ast.AST, held: Tuple[str, ...]) -> None:
            if isinstance(n, (ast.With, ast.AsyncWith)):
                new_held = held
                for item in n.items:
                    ln = resolve_lock(item.context_expr, node)
                    if ln is None:
                        continue
                    for h in new_held:
                        if h != ln and (h, ln) not in edges:
                            edges[(h, ln)] = (node.path, n.lineno,
                                              " -> ".join(chain))
                            adj.setdefault(h, set()).add(ln)
                    if ln not in new_held:
                        new_held = new_held + (ln,)
                for child in n.body:
                    scan(child, new_held)
                return
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
                return
            if isinstance(n, ast.Call):
                tgt = graph.call_target(n)
                if tgt is not None and tgt in graph.nodes:
                    walk(tgt, held,
                         chain + (_node_display(graph.nodes[tgt]),))
            for child in ast.iter_child_nodes(n):
                scan(child, held)

        body = node.fn.body if isinstance(node.fn.body, list) \
            else [node.fn.body]
        for child in body:
            scan(child, held)

    for node_id in sorted(graph.nodes):
        node = graph.nodes[node_id]
        walk(node_id, (), (_node_display(node),))

    findings: List[Finding] = []
    reported: Set[frozenset] = set()
    for (a, b), (path, line, chain_desc) in sorted(edges.items()):
        cyc = _order_path(adj, b, a)
        if cyc is None:
            continue
        cyc_set = frozenset([a] + cyc)
        if cyc_set in reported:
            continue
        reported.add(cyc_set)
        opposite = edges.get((cyc[0], cyc[1])) if len(cyc) > 1 else None
        opp_desc = f"; opposite order acquired in {opposite[2]}" \
            if opposite else ""
        findings.append(Finding(
            "lock-order", path, line,
            f"static lock-order inversion: acquiring '{b}' while holding "
            f"'{a}' (in {chain_desc}) closes the cycle "
            f"{' -> '.join([a] + cyc)} — the two orders can deadlock under "
            f"the right interleaving{opp_desc}"))
    return findings


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def _iter_py_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git", "build")]
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
    return out


def lint_files(files: Iterable[str],
               checks: Optional[Sequence[str]] = None) -> List[Finding]:
    active = set(checks or ALL_CHECKS)
    unknown = active - set(ALL_CHECKS)
    if unknown:
        raise ValueError(
            f"unknown checks: {sorted(unknown)}; "
            f"valid checks: {', '.join(ALL_CHECKS)}")
    scans: List[_FileScan] = []
    findings: List[Finding] = []
    for path in files:
        try:
            with open(path, "r", encoding="utf-8") as f:
                src = f.read()
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            findings.append(Finding(
                "syntax", path, e.lineno or 0, f"does not parse: {e.msg}"))
            continue
        scans.append(_FileScan(path, tree, src.splitlines()))
    graph: Optional[CallGraph] = None
    if active & _GRAPH_CHECKS:
        graph = build_callgraph((sc.path, sc.tree) for sc in scans)
    for sc in scans:
        if "obs-guard" in active:
            findings.extend(_check_obs_guard(sc))
    if graph is not None:
        if "fiber-shared-state" in active:
            findings.extend(_check_fiber_shared_state(scans, graph))
        if "trace-purity" in active:
            findings.extend(_check_trace_purity(scans, graph))
        if "lock-order" in active:
            findings.extend(_check_lock_order(scans, graph))
        if "fiber-blocking-sleep" in active:
            findings.extend(_check_fiber_blocking_sleep(scans, graph))
    if "ctypes-contract" in active:
        findings.extend(_check_ctypes_contract(scans))
    # dedup (a nested def can be reached both inside its parent's subtree
    # and as its own call-graph node), then stable order
    seen: Set[Tuple[str, str, int, str]] = set()
    unique: List[Finding] = []
    for f in findings:
        key = (f.check, f.path, f.line, f.message)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    unique.sort(key=lambda f: (f.path, f.line, f.check))
    return unique


def run_lint(paths: Sequence[str],
             checks: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint every ``.py`` file under ``paths`` (files or directories)."""
    return lint_files(_iter_py_files(paths), checks)


def load_baseline(path: str) -> Set[str]:
    """Accepted finding ids from a baseline file: either the
    ``--format=json`` / ``--write-baseline`` output or a plain list of
    ids."""
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    items: Iterable = ()
    if isinstance(data, dict):
        items = data.get("ids") or data.get("findings") or ()
    elif isinstance(data, list):
        items = data
    ids: Set[str] = set()
    for item in items:
        if isinstance(item, str):
            ids.add(item)
        elif isinstance(item, dict) and "id" in item:
            ids.add(str(item["id"]))
    return ids


def apply_baseline(findings: Sequence[Finding], baseline_ids: Set[str]
                   ) -> Tuple[List[Finding], List[Finding]]:
    """Split findings into (new, suppressed-by-baseline)."""
    new = [f for f in findings if f.id not in baseline_ids]
    old = [f for f in findings if f.id in baseline_ids]
    return new, old


def _default_target() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m brpc_tpu.analysis",
        description="Framework-invariant linter for the brpc_tpu fabric")
    parser.add_argument("paths", nargs="*",
                        help="files/directories to lint "
                             "(default: the brpc_tpu package)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--check", action="append", metavar="NAME",
                        help=f"run only the named check(s); "
                             f"known: {', '.join(ALL_CHECKS)}")
    parser.add_argument("--baseline", metavar="FILE",
                        help="suppress findings whose stable id appears in "
                             "FILE (json: --write-baseline output, "
                             "--format=json output, or a list of ids)")
    parser.add_argument("--write-baseline", metavar="FILE",
                        help="write the current findings as an accepted "
                             "baseline and exit 0")
    args = parser.parse_args(argv)
    try:
        findings = run_lint(args.paths or [_default_target()], args.check)
    except ValueError as e:
        parser.error(str(e))  # exit 2, lists the valid check set
    if args.write_baseline:
        with open(args.write_baseline, "w", encoding="utf-8") as f:
            json.dump({"ids": sorted({x.id for x in findings}),
                       "findings": [x.to_dict() for x in findings]},
                      f, indent=2)
            f.write("\n")
        print(f"baseline: {len(findings)} finding(s) -> "
              f"{args.write_baseline}", file=sys.stderr)
        return 0
    suppressed: List[Finding] = []
    if args.baseline:
        try:
            baseline_ids = load_baseline(args.baseline)
        except (OSError, json.JSONDecodeError) as e:
            parser.error(f"cannot read baseline {args.baseline}: {e}")
        findings, suppressed = apply_baseline(findings, baseline_ids)
    if args.format == "json":
        payload = {
            "count": len(findings),
            "checks": list(args.check or ALL_CHECKS),
            "findings": [f.to_dict() for f in findings],
        }
        if args.baseline:
            payload["suppressed_count"] = len(suppressed)
        print(json.dumps(payload, indent=2))
    else:
        for f in findings:
            print(f.format())
        tail = f", {len(suppressed)} suppressed by baseline" \
            if suppressed else ""
        print((f"{len(findings)} finding(s){tail}" if findings
               else f"clean: no findings{tail}"), file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
