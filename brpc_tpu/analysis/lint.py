"""Framework-invariant AST linter for the Python tier.

The reference enforces its concurrency contracts with purpose-built
tooling (contention profiler, bthread diagnostics, builtin hazard pages);
this is the equivalent static pass for the hazards our fabric creates.
Fourteen checks, each encoding an invariant the runtime cannot enforce,
the concurrency ones interprocedural over the whole-package call graph
(:mod:`brpc_tpu.analysis.callgraph` — the lockdep/TSan polarity: follow
the calls, not the file):

- ``ctypes-contract`` — every ``*.brt_*`` symbol used anywhere must have
  BOTH ``argtypes`` and ``restype`` declared somewhere in the scanned
  tree (``rpc._load()`` is the canonical site).  ctypes defaults an
  undeclared restype to c_int, which silently truncates 64-bit handles
  on the way out of the native core.  Also: a ``CFUNCTYPE`` callback
  passed inline to a ``brt_*`` call is owned by nobody — the native core
  keeps the raw function pointer while Python GCs the closure.
- ``fiber-shared-state`` — methods reachable from a handler registered
  via ``add_service``/``add_async_service`` run concurrently on fiber
  workers (the trampoline releases the GIL across ctypes); any mutation
  of ``self``/module state anywhere in the handler-reachable set — across
  modules, through helpers — must sit inside a ``with self._mu``-style
  block.  Rwlock sides are understood: ``with self._mu.write():`` is an
  exclusive hold, ``with self._mu.read():`` is SHARED and never
  legitimizes mutation.  Thread-local state (``self._local.*``/``*tls*``)
  is exempt.
- ``obs-guard`` — instrumentation outside ``brpc_tpu/obs`` must go
  through the no-op-able helpers (``obs.counter``/``obs.recorder``/
  ``obs.record_span``); constructing reducers or touching the Registry
  directly bypasses the ``enabled()`` gate.
- ``trace-purity`` — no wall-clock reads, ``print``, lock traffic, or
  ``obs`` calls anywhere transitively reachable (through in-package
  helpers) from a function handed to ``jax.jit``/``shard_map``; they run
  once at trace time and vanish from the compiled program.  Findings
  carry the full call chain from the traced root to the impure site.
  Host callbacks (``jax.debug.print``, ``pure_callback``/``io_callback``)
  under trace are a separate hazard class: they DON'T vanish — they
  stage a host round-trip into every step — and must be allowlisted
  per-site with ``# lint: allow-host-callback`` when intended.
  DELIBERATE trace-time effects (e.g. counters of programs built) are
  declared with ``# lint: allow-trace-impure`` on the call line or on
  the helper's ``def`` line — the walk neither flags nor descends
  there.
- ``lock-order`` — the static half of the RACECHECK harness: derives
  the ``with <checked_lock>`` nesting graph over the call graph and
  reports inversion cycles without running anything; the dynamic
  harness (:mod:`brpc_tpu.analysis.race`) becomes the confirmer, not
  the only detector.  ``checked_rwlock`` participates too: both
  ``.read()`` and ``.write()`` contexts acquire under the lock's one
  name, matching the dynamic graph's keying.  Locks resolve through
  module/class/parameter bindings AND literal dict containers at
  module scope (``LOCKS["a"]``) or class scope (``self.LOCKS["a"]``,
  including containers inherited from base classes — the direct class
  bodies along the base chain are walked, nearest assignment wins) —
  constant keys bind by key; dynamic keys and mutated containers stay
  unresolved (dynamic-harness territory).
- ``fiber-blocking-sleep`` — a bare ``time.sleep`` anywhere
  handler-reachable (interprocedural, same walk as
  ``fiber-shared-state``) parks the fiber worker PTHREAD, not just the
  fiber, stalling every handler scheduled on that worker.  The
  sanctioned path is :mod:`brpc_tpu.resilience` (``sleep_ms`` +
  ``Backoff``: deadline-capped, deterministically jittered) — calls
  resolving into that module are not followed, and its own sleeps are
  exempt.
- ``handle-lifecycle`` — every call that returns an OWNING native
  handle (constructors/factories of ``rpc``'s owner classes — Server,
  Channel, PendingCall, CallGroup, Stream, PsShard, DeviceClient,
  DeviceExecutable — plus in-package functions inferred to return a
  fresh one) must, on every normal-flow path, reach its release
  (``close``/``join``/``abort``), be returned to the caller, or be
  stored on an object whose own close-style method releases it
  (ownership transfer, audited through the attr/local/return type
  maps).  Escapes into containers or thread targets are reported;
  deliberate registries carry ``# lint: allow-handle-escape``.  The
  flow analysis is may-leak at explicit exits (an early ``return``
  with a live handle is THE classic leak) and trusts a release seen on
  any branch (the guard idiom) — no false positives from merges.
  Exception paths are fully in scope: a handle acquired and still
  live at an explicit ``raise`` is a leak unless a ``finally``, a
  ``with``, or an enclosing ``except`` handler that actually covers
  the raised type releases it — handler trust is SCOPED to the
  statements inside the handler's own ``try`` and to the exception
  types it can catch (resolved through the in-package class hierarchy
  plus the builtin exception tree), replacing the old
  context-insensitive trust.  The deferred dataflow is closed too:
  handles appended into a local container become a tracked may-leak
  set (drained by iterating-and-releasing, discharged by returning or
  storing the container; ``# lint: allow-handle-escape`` on the append
  still marks a deliberate registry), rebinding a name over an
  un-released handle (``h = new(); h = other``) is flagged as a drop
  of the first obligation, and module-scope producer assignments are
  audited like attrs (some function in the module must release the
  global, or the singleton is declared with the pragma).  The
  ABI half audits ``rpc._load()``'s restype
  registry itself: every ``c_void_p``-returning constructor symbol
  needs its destroy symbol declared.  The dynamic complement is the
  handle ledger (:mod:`brpc_tpu.analysis.handles`,
  ``BRPC_TPU_HANDLECHECK=1``).
- ``exception-flow`` — the interprocedural half of exception-safe
  handle lifecycle, built on the may-throw fixpoint in
  :mod:`brpc_tpu.analysis.callgraph`: every in-package function gets a
  summary of the exception types it can raise (explicit ``raise`` and
  ``assert`` propagated through resolved call edges, with
  ``except``-guarded calls absorbing what their handlers can catch),
  and a live handle at a call site whose callee PROVABLY may throw is
  an exit — a leak on the unwinding edge unless an enclosing
  ``finally``/``with`` or a handler covering that call (and that
  thrown type) releases it.  Unresolvable/external callees carry a
  low-confidence ``external`` bit and are deliberately silent, so a
  finding never rests on a false chain.
- ``lock-exception-safety`` — same machinery pointed at locks and
  obligations: a ``checked_lock``/``checked_rwlock`` acquired
  manually (``.acquire()`` outside ``with``) and still held across a
  may-throw site is left locked forever on the unwinding edge unless
  a ``finally`` (or a covering handler) releases it; and a fence-flag
  obligation (``self._x = True`` … ``self._x = False`` in the same
  block) with a may-throw site between set and reset unwinds
  half-done unless the reset sits in a ``finally``.  No pragma
  escape — these are fixed, not baselined.
- ``wire-contract`` — frame-schema symmetry and parse-path bounds for
  every hand-rolled framing: ``_pack_X``/``_unpack_X`` pairs must move
  the same field stream (order + width), every site registered in
  :mod:`brpc_tpu.wire`'s schema registry must match its declared
  scalar sequence (exactly for dedicated functions; shared multi-frame
  handlers like ``_serve_control`` are checked by **exact segmented
  matching** — each schema binds to its dispatch-discriminant branch
  via the schema's ``segments`` declaration and that branch's stream
  must equal the schema exactly; shared reads BEFORE the dispatch
  branch — ``_serve``'s header — are declared per-site with the
  schema's ``prebranch`` field and prepended to the branch stream for
  the exact comparison, stale declarations included, leaving in-order
  subsequence only for shared sites with no segment key), struct
  formats must
  be
  explicit little-endian, counts/lengths read off the wire on
  handler-reachable parse paths must reach a bounds check before they
  drive a size/loop, and every declared schema/text parser must have a
  fuzz target (:mod:`brpc_tpu.analysis.fuzz` — the "fuzzers for every
  parser" gate).  The dynamic complement is the structure-aware fuzzer
  itself.
- ``wire-contract-native`` / ``native-errors`` /
  ``native-handle-balance`` / ``native-endian`` — the cross-language tier
  (:mod:`brpc_tpu.analysis.native`): a clang-free tokenizer +
  function-body extractor over ``cpp/capi/*.cc`` checks every
  ``wire.REGISTRY`` schema with a declared ``native_sites`` twin
  field-for-field against the C++ parser's extracted read sequence
  (widths, order, literal offsets, count-before-bounds, magic
  sentinels; stale site declarations and undeclared native parsers are
  findings too), resolves every ``SetFailed`` constant against
  ``errors.h``/errno and holds serve-path handlers to the live
  fuzzer's sanctioned code set (static/dynamic parity), and flags
  ``handle_inc`` ledger bumps left unbalanced on native error-return
  paths.  ``native-endian`` closes the byte-order hole: every native
  parser a schema claims whose extracted read stream contains a
  multi-byte scalar must be covered by a runtime parity-fuzz target
  (cross-checked against :func:`brpc_tpu.analysis.fuzz.coverage_map`),
  so an endianness mismatch cannot hide in a parser no fuzzer drives.
  These run only when the scan covers the real package (the
  native tree is located relative to ``brpc_tpu/``).

Findings carry a stable id (hash of check + package-relative path +
message, deliberately line-free) so CI can diff against an accepted
baseline (``--baseline FILE`` suppresses known ids; ``--write-baseline``
emits one).

Entry points: :func:`run_lint` (in-process, returns findings) and
:func:`main` (the ``python -m brpc_tpu.analysis`` CLI; exit 0 = clean,
1 = findings, 2 = usage error — unknown ``--check`` names are rejected
with the valid set listed).
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import hashlib
import json
import os
import sys
from typing import (Dict, Iterable, List, Optional, Sequence, Set, Tuple)

from brpc_tpu.analysis.callgraph import (CallGraph, FuncNode,
                                         build_callgraph)

__all__ = ["Finding", "run_lint", "lint_files", "main", "ALL_CHECKS",
           "load_baseline", "apply_baseline"]

ALL_CHECKS = ("ctypes-contract", "fiber-shared-state", "obs-guard",
              "trace-purity", "lock-order", "fiber-blocking-sleep",
              "handle-lifecycle", "exception-flow",
              "lock-exception-safety", "wire-contract",
              "wire-contract-native", "native-errors",
              "native-handle-balance", "native-endian")

#: checks implemented by the cross-language tier (analysis.native)
_NATIVE_CHECKS = ("wire-contract-native", "native-errors",
                  "native-handle-balance", "native-endian")

#: checks that need the whole-package call graph
_GRAPH_CHECKS = {"fiber-shared-state", "trace-purity", "lock-order",
                 "fiber-blocking-sleep", "handle-lifecycle",
                 "exception-flow", "lock-exception-safety",
                 "wire-contract"}

#: attribute names that look like a lock on self / a module
_LOCKISH = ("mu", "lock", "mutex")
#: rwlock side methods (checked_rwlock's read()/write() contexts)
_RW_SIDES = ("read", "write")
#: container methods that mutate their receiver in place
_MUTATORS = {
    "append", "appendleft", "extend", "insert", "remove", "pop", "popleft",
    "clear", "update", "setdefault", "add", "discard", "sort", "reverse",
}
#: obs surface that hot paths must NOT touch directly (the no-op-able
#: helpers counter/recorder/record_span/span/enabled stay allowed)
_OBS_GUARDED = {
    "Registry", "default_registry", "expose", "Adder", "Maxer", "Miner",
    "LatencyRecorder", "Window", "PerSecond", "PassiveStatus",
}
_TRACERS = {"jit", "shard_map", "pjit"}
_TIME_FNS = {"time", "time_ns", "monotonic", "monotonic_ns",
             "perf_counter", "perf_counter_ns", "sleep"}
#: bare/attr names that stage a host callback into a traced program
_HOST_CALLBACKS = {"pure_callback", "io_callback"}
#: per-site pragma that allowlists a host callback under trace
_ALLOW_HOST_CB = "lint: allow-host-callback"
#: pragma declaring DELIBERATE trace-time impurity: on a call line, the
#: call is neither flagged nor followed from traced roots; on a `def`
#: line, traced walks never descend into that function (the canonical
#: use: trace-time instrumentation like collective program counters,
#: which by design runs once per trace and must not be reported as a
#: vanishing side effect)
_ALLOW_TRACE_IMPURE = "lint: allow-trace-impure"
#: pragma declaring a DELIBERATE handle escape (a managed registry /
#: fan-out set whose owner releases its members out of the static
#: check's sight) — suppresses handle-lifecycle escape/leak findings on
#: that line
_ALLOW_HANDLE_ESCAPE = "lint: allow-handle-escape"

# ---- handle-lifecycle owner tables -----------------------------------------
# Owning native-handle classes of brpc_tpu.rpc (each wraps a brt_* handle
# that MUST be explicitly destroyed) -> the methods that release it.  The
# table mirrors rpc._load()'s restype registry: every class here fronts a
# brt_* constructor declared with a c_void_p restype (the ABI-pairing
# sub-check below keeps that registry itself paired new<->destroy).
_HANDLE_OWNERS: Dict[str, frozenset] = {
    "Server": frozenset({"close"}),
    "Channel": frozenset({"close"}),
    "PendingCall": frozenset({"join", "close"}),
    "CallGroup": frozenset({"close"}),
    "Stream": frozenset({"close", "abort"}),
    "PsShard": frozenset({"close"}),
    "DeviceClient": frozenset({"close"}),
    "DeviceExecutable": frozenset({"close"}),
}
#: factory methods returning a FRESH owning handle: (class, method) ->
#: produced owner class
_HANDLE_FACTORIES = {
    ("Channel", "call_async"): "PendingCall",
    ("Channel", "stream"): "Stream",
    ("DeviceClient", "compile"): "DeviceExecutable",
}
#: method-NAME fallback for receivers the type maps cannot resolve
#: (`self.channels[s].call_async(...)`): the name is unambiguous enough
#: to imply ownership even without a resolved receiver
_FACTORY_NAME_FALLBACK = {"call_async": "PendingCall"}
#: methods whose body counts as "releases what self.<attr> holds" for
#: the ownership-transfer audit of attr-stored handles
_RELEASEISH_METHODS = {"close", "stop", "shutdown", "abort", "__exit__",
                       "__del__", "clear", "reset"}
#: ABI pairing for c_void_p-returning symbols that don't follow the
#: brt_X_new -> brt_X_destroy naming rule
_ABI_NEW_PAIRS = {
    "brt_channel_call_start": "brt_call_destroy",
    "brt_channel_call_start_opts": "brt_call_destroy",
    "brt_device_compile": "brt_device_executable_destroy",
    "brt_mlir_module": "brt_free",
    "brt_debug_handle_counts": "brt_free",
}


def _stable_path(path: str) -> str:
    """Package-relative posix path (machine-independent id component).
    Native-tier findings anchor on ``cpp/`` the same way Python ones
    anchor on ``brpc_tpu/``."""
    parts = os.path.normpath(path).replace(os.sep, "/").split("/")
    for anchor in ("brpc_tpu", "cpp"):
        if anchor in parts:
            return "/".join(parts[parts.index(anchor):])
    return parts[-1]


@dataclasses.dataclass
class Finding:
    check: str
    path: str
    line: int
    message: str
    #: stable id: hash over check + package-relative path + message (no
    #: line number, so pure drift doesn't churn baselines)
    id: str = ""

    def __post_init__(self) -> None:
        if not self.id:
            raw = f"{self.check}|{_stable_path(self.path)}|{self.message}"
            self.id = hashlib.sha1(raw.encode()).hexdigest()[:12]

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.check}:{self.id}] " \
               f"{self.message}"

    def to_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# small AST helpers
# ---------------------------------------------------------------------------

def _last_name(expr: ast.AST) -> Optional[str]:
    """'jax.jit' -> 'jit', 'jit' -> 'jit', else None."""
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _root_name(expr: ast.AST) -> Optional[str]:
    """'a.b.c' -> 'a' (the base Name of a dotted chain)."""
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        expr = expr.value
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _is_self_rooted(expr: ast.AST) -> bool:
    return _root_name(expr) == "self"


def _is_tls_path(expr: ast.AST) -> bool:
    """True for thread-local chains (``self._local.cell``) — per-thread
    state needs no lock."""
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        if isinstance(expr, ast.Attribute):
            low = expr.attr.lower()
            if "local" in low or "tls" in low:
                return True
        expr = expr.value
    return False


def _is_lockish_ctx(expr: ast.AST) -> bool:
    """True for `with self._mu:` / `with _load_mu:` style context exprs,
    including rwlock sides (`with self._mu.read():` / `.write()`)."""
    name = None
    if isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Name):
        name = expr.id
    elif isinstance(expr, ast.Call):
        f = expr.func
        if isinstance(f, ast.Attribute) and f.attr in _RW_SIDES:
            # with self._mu.read()/.write(): lockish iff the receiver is
            return _is_lockish_ctx(f.value)
        # with self._mu.acquire_timeout(...) style — treat lock method
        # calls on a lockish receiver as lock context too
        return _is_lockish_ctx(f)
    if name is None:
        return False
    low = name.lower()
    return any(part in low for part in _LOCKISH)


def _lock_ctx_kind(expr: ast.AST) -> Optional[str]:
    """Classify a with-item context: ``"lock"`` for exclusive holds
    (plain locks, rwlock ``.write()``), ``"read"`` for the SHARED rwlock
    side, ``None`` for non-lock contexts.  The distinction matters to
    `fiber-shared-state`: a read-side hold serializes against writers but
    not against sibling readers, so it must never legitimize mutation."""
    if not _is_lockish_ctx(expr):
        return None
    if isinstance(expr, ast.Call) and \
            isinstance(expr.func, ast.Attribute) and \
            expr.func.attr == "read":
        return "read"
    return "lock"


def _describe(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse of synthetic nodes
        return "<expr>"


def _local_binds(fn: ast.AST) -> Set[str]:
    """Names bound locally inside ``fn`` (params, plain assigns, loop and
    with targets) — these shadow module globals for the shared-state
    check."""
    out: Set[str] = set()
    args = getattr(fn, "args", None)
    if args is not None:
        for a in (list(args.posonlyargs) + list(args.args) +
                  list(args.kwonlyargs)):
            out.add(a.arg)
        if args.vararg:
            out.add(args.vararg.arg)
        if args.kwarg:
            out.add(args.kwarg.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Global):
            out -= set(node.names)  # `global x` un-shadows
            continue
        tgt_lists: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            tgt_lists = list(node.targets)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            tgt_lists = [node.target]
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            tgt_lists = [node.target]
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            tgt_lists = [i.optional_vars for i in node.items
                         if i.optional_vars is not None]
        for tgt in tgt_lists:
            if isinstance(tgt, ast.Name):
                out.add(tgt.id)
            elif isinstance(tgt, (ast.Tuple, ast.List)):
                for leaf in ast.walk(tgt):
                    if isinstance(leaf, ast.Name):
                        out.add(leaf.id)
    return out


def _node_display(node: FuncNode) -> str:
    if node.cls is not None:
        return f"{node.cls}.{node.name}"
    if node.qual == "<module>":
        return f"{node.module}:<module>"
    return node.qual


# ---------------------------------------------------------------------------
# per-file scan state
# ---------------------------------------------------------------------------

class _FileScan:
    """One parsed file plus everything the checks extract from it."""

    def __init__(self, path: str, tree: ast.Module,
                 src_lines: Optional[List[str]] = None):
        self.path = path
        self.tree = tree
        self.src_lines = src_lines or []
        # ctypes-contract
        self.native_decls: Dict[str, Set[str]] = {}  # brt_x -> declared kinds
        self.native_uses: List[Tuple[str, int]] = []  # (brt_x, line)
        # brt_x -> (restype name, decl line) — the restype registry the
        # handle-lifecycle ABI-pairing sub-check audits
        self.native_restypes: Dict[str, Tuple[str, int]] = {}
        self.cfunctype_protos: Set[str] = set()
        # obs-guard bookkeeping: names bound to obs modules / obs imports
        self.obs_module_aliases: Set[str] = set()
        self.obs_imported_names: Set[str] = set()
        self._collect()

    def _collect(self) -> None:
        decl_nodes: Set[int] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    self._note_decl(tgt, node.value, decl_nodes)
                if isinstance(node.value, ast.Call) and \
                        _last_name(node.value.func) == "CFUNCTYPE":
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            self.cfunctype_protos.add(tgt.id)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.endswith(".obs") or ".obs." in alias.name:
                        self.obs_module_aliases.add(
                            alias.asname or alias.name.split(".")[-1])
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod == "brpc_tpu" or mod.endswith(".obs"):
                    for alias in node.names:
                        if alias.name == "obs" or mod.endswith(".obs"):
                            tgt = alias.asname or alias.name
                            if alias.name == "obs":
                                self.obs_module_aliases.add(tgt)
                            else:
                                self.obs_imported_names.add(tgt)
                elif ".obs." in mod or mod.startswith("obs."):
                    for alias in node.names:
                        self.obs_imported_names.add(alias.asname or alias.name)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Attribute) and \
                    node.attr.startswith("brt_") and id(node) not in decl_nodes:
                self.native_uses.append((node.attr, node.lineno))

    def _note_decl(self, tgt: ast.AST, value: ast.AST,
                   decl_nodes: Set[int]) -> None:
        if isinstance(tgt, ast.Attribute) and \
                tgt.attr in ("argtypes", "restype") and \
                isinstance(tgt.value, ast.Attribute) and \
                tgt.value.attr.startswith("brt_"):
            self.native_decls.setdefault(tgt.value.attr, set()).add(tgt.attr)
            decl_nodes.add(id(tgt.value))
            if tgt.attr == "restype":
                rname = _last_name(value)
                if rname is not None:
                    self.native_restypes[tgt.value.attr] = (rname,
                                                            tgt.lineno)

    def line_has(self, lineno: int, marker: str) -> bool:
        if 1 <= lineno <= len(self.src_lines):
            return marker in self.src_lines[lineno - 1]
        return False


# ---------------------------------------------------------------------------
# check: ctypes-contract
# ---------------------------------------------------------------------------

def _check_ctypes_contract(scans: List[_FileScan]) -> List[Finding]:
    findings: List[Finding] = []
    decls: Dict[str, Set[str]] = {}
    for sc in scans:
        for name, kinds in sc.native_decls.items():
            decls.setdefault(name, set()).update(kinds)
    reported: Set[Tuple[str, str]] = set()
    for sc in scans:
        for name, line in sc.native_uses:
            have = decls.get(name, set())
            missing = [k for k in ("argtypes", "restype") if k not in have]
            if not missing or (name, sc.path) in reported:
                continue
            reported.add((name, sc.path))
            findings.append(Finding(
                "ctypes-contract", sc.path, line,
                f"native symbol '{name}' used without "
                f"{' and '.join(missing)} declared anywhere in the scanned "
                f"tree (ctypes defaults restype to c_int — 64-bit handles "
                f"truncate); declare it in rpc._load()"))
    for sc in scans:
        findings.extend(_check_cfunctype_pinning(sc))
    return findings


def _check_cfunctype_pinning(sc: _FileScan) -> List[Finding]:
    protos = sc.cfunctype_protos
    if not protos:
        return []
    findings: List[Finding] = []
    # 1) inline construction passed straight to the native core (one walk
    #    over the whole tree so each call site reports exactly once)
    for node in ast.walk(sc.tree):
        if not isinstance(node, ast.Call):
            continue
        fn_last = _last_name(node.func)
        if fn_last is None or not fn_last.startswith("brt_"):
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Call) and _last_name(arg.func) in protos:
                findings.append(Finding(
                    "ctypes-contract", sc.path, arg.lineno,
                    f"CFUNCTYPE callback constructed inline in a "
                    f"'{fn_last}' call — nothing owns it and the GC frees "
                    f"it under the native core's feet; store it on the "
                    f"owner object first"))
    # 2) named callbacks passed to the native core but never pinned.
    #    Callbacks are attributed to the scope that DIRECTLY defines them;
    #    pinning/passing is searched through that whole scope subtree.
    #    MODULE-scope callbacks are exempt: a module-level name is held by
    #    the module namespace for the life of the process — it cannot be
    #    GC'd under the native core (only function locals can).
    scopes: List[ast.AST] = [
        n for n in ast.walk(sc.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for scope in scopes:
        callbacks = _callback_locals_shallow(scope, protos)
        if not callbacks:
            continue
        passed_to_native: Dict[str, int] = {}
        pinned: Set[str] = set()
        # `global X; X = cb` pins on the module namespace — as immortal
        # as self.<attr> on a long-lived owner.
        declared_global: Set[str] = set()
        for node in ast.walk(scope):
            if isinstance(node, ast.Global):
                declared_global.update(node.names)
        for node in ast.walk(scope):
            if isinstance(node, ast.Call):
                fn_last = _last_name(node.func)
                is_native = fn_last is not None and fn_last.startswith("brt_")
                for arg in list(node.args) + \
                        [kw.value for kw in node.keywords]:
                    if isinstance(arg, ast.Name) and arg.id in callbacks:
                        if is_native:
                            passed_to_native.setdefault(arg.id, arg.lineno)
                        else:
                            # arg of append()/add()/...: the owner keeps it
                            pinned.add(arg.id)
            elif isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id in callbacks:
                for tgt in node.targets:
                    if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                        pinned.add(node.value.id)
                    elif isinstance(tgt, ast.Name) and \
                            tgt.id in declared_global:
                        pinned.add(node.value.id)
        for name, line in sorted(passed_to_native.items()):
            if name not in pinned:
                findings.append(Finding(
                    "ctypes-contract", sc.path, line,
                    f"CFUNCTYPE callback '{name}' is passed to the native "
                    f"core but never pinned on an owner object "
                    f"(self.<attr> = {name} or self.<list>.append({name})) "
                    f"— it is GC'd while the core still holds the pointer"))
    return findings


def _callback_locals_shallow(scope: ast.AST, protos: Set[str]
                             ) -> Dict[str, int]:
    """Callback names defined as DIRECT children of the scope (nested
    function scopes audit their own callbacks)."""
    out: Dict[str, int] = {}
    body = scope.body if hasattr(scope, "body") else []
    for node in body:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                and _last_name(node.value.func) in protos:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = node.lineno
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _last_name(dec) in protos:
                    out[node.name] = node.lineno
    return out


# ---------------------------------------------------------------------------
# check: fiber-shared-state (interprocedural over the call graph)
# ---------------------------------------------------------------------------

def _find_handler_roots(sc: _FileScan, graph: CallGraph,
                        top: Optional[FuncNode],
                        register_names: Tuple[str, ...] = (
                            "add_service", "add_async_service"),
                        ) -> List[str]:
    """Node ids of handlers registered via add_service/add_async_service
    anywhere in this file (``self.X`` methods, bare function names,
    partial targets).  ``register_names`` widens the registration set
    (the wire-contract check also treats ``add_ps_service`` /
    ``add_stream_handler`` trampoline targets as hostile-input roots)."""
    roots: List[str] = []

    def visit(node: ast.AST, ctx: Optional[FuncNode]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            inner = graph.node_for_ast(node)
            for child in ast.iter_child_nodes(node):
                visit(child, inner or ctx)
            return
        if isinstance(node, ast.Call) and ctx is not None and \
                _last_name(node.func) in register_names:
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                tgt = graph.resolve_callable_expr(arg, ctx)
                if tgt is not None:
                    roots.append(tgt)
        for child in ast.iter_child_nodes(node):
            visit(child, ctx)

    visit(sc.tree, top)
    return roots


def _check_fiber_shared_state(scans: List[_FileScan],
                              graph: CallGraph) -> List[Finding]:
    sc_by_path = {sc.path: sc for sc in scans}
    mi_by_path = {mi.path: mi for mi in graph.modules.values()}
    roots: List[str] = []
    for sc in scans:
        mi = mi_by_path.get(sc.path)
        top = graph.nodes.get(f"{mi.name}:<module>") if mi else None
        roots.extend(_find_handler_roots(sc, graph, top))
    findings: List[Finding] = []
    visited: Set[Tuple[str, bool]] = set()
    queue: List[Tuple[str, bool, Tuple[str, ...]]] = [
        (r, False, (_node_display(graph.nodes[r]),))
        for r in roots if r in graph.nodes]
    while queue:
        node_id, locked, chain = queue.pop()
        if (node_id, locked) in visited:
            continue
        visited.add((node_id, locked))
        node = graph.nodes.get(node_id)
        if node is None or node.path not in sc_by_path:
            continue
        _scan_shared_state(sc_by_path[node.path], graph, node, locked,
                           chain, queue, findings)
    return findings


def _scan_shared_state(sc: _FileScan, graph: CallGraph, node: FuncNode,
                       locked0: bool, chain: Tuple[str, ...],
                       queue: List[Tuple[str, bool, Tuple[str, ...]]],
                       findings: List[Finding]) -> None:
    fn = node.fn
    mi = graph.modules[node.module]
    display = _node_display(node)
    global_names = {name for n in ast.walk(fn) if isinstance(n, ast.Global)
                    for name in n.names}
    mod_state = (mi.module_globals - _local_binds(fn)) | global_names
    # A constructor mutating its OWN self is initializing an object no
    # other fiber can see yet (publication happens after __init__
    # returns) — never a race.  Module-state mutation in a reachable
    # __init__ still counts.
    fresh_self = node.name == "__init__"

    def mutation(n: ast.AST, what: str, in_read: bool = False) -> None:
        via = ""
        if len(chain) > 1:
            via = f" [reached via {' -> '.join(chain)}]"
        hint = (" (a read-side `.read()` hold is SHARED — sibling "
                "readers run concurrently; mutation needs the write "
                "side)" if in_read else "")
        findings.append(Finding(
            "fiber-shared-state", sc.path, n.lineno,
            f"handler-reachable {display} mutates {what} outside a "
            f"`with self._mu` block{hint} — handlers run concurrently on "
            f"fiber workers (the ctypes trampoline releases the GIL)"
            f"{via}"))

    def scan(n: ast.AST, locked: bool, in_read: bool = False) -> None:
        if isinstance(n, (ast.With, ast.AsyncWith)):
            kinds = [_lock_ctx_kind(item.context_expr) for item in n.items]
            now_locked = locked or "lock" in kinds
            now_read = (in_read or "read" in kinds) and not now_locked
            for item in n.items:
                scan(item.context_expr, locked, in_read)
            for child in n.body:
                scan(child, now_locked, now_read)
            return
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            return  # nested defs get their own audit when reachable
        if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = n.targets if isinstance(n, ast.Assign) else [n.target]
            for tgt in targets:
                if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                    if _is_tls_path(tgt) or locked:
                        continue
                    if node.cls is not None and _is_self_rooted(tgt):
                        if not fresh_self:
                            mutation(tgt, _describe(tgt), in_read)
                    else:
                        root = _root_name(tgt)
                        if root is not None and root in mod_state:
                            mutation(tgt, f"module state "
                                          f"'{_describe(tgt)}'", in_read)
                elif isinstance(tgt, ast.Name) and tgt.id in global_names \
                        and not locked:
                    mutation(tgt, f"module global '{tgt.id}'", in_read)
        if isinstance(n, ast.Call):
            f = n.func
            if isinstance(f, ast.Attribute) and not locked:
                if f.attr == "at" and n.args and not _is_tls_path(n.args[0]):
                    # np.<ufunc>.at(self.table, ...) mutates in place
                    if node.cls is not None and _is_self_rooted(n.args[0]):
                        if not fresh_self:
                            mutation(n, _describe(n.args[0]), in_read)
                    elif isinstance(n.args[0], ast.Name) and \
                            n.args[0].id in mod_state:
                        mutation(n, f"module state '{n.args[0].id}'",
                                 in_read)
                elif f.attr in _MUTATORS and not _is_tls_path(f.value) \
                        and graph.call_target(n) is None:
                    # A receiver whose method RESOLVES in the call graph
                    # (attr-type/local-type map) is not a raw container:
                    # the interprocedural walk below analyzes the callee's
                    # body — its own mutations get checked against its own
                    # locking, so the heuristic must not double-report
                    # (e.g. an internally-synchronized combiner's .add()).
                    if node.cls is not None and _is_self_rooted(f.value):
                        if not fresh_self:
                            mutation(n, f"{_describe(f.value)} "
                                        f"(via .{f.attr}())", in_read)
                    elif isinstance(f.value, ast.Name) and \
                            f.value.id in mod_state:
                        mutation(n, f"module state '{f.value.id}' "
                                    f"(via .{f.attr}())", in_read)
            tgt = graph.call_target(n)
            if tgt is not None and tgt in graph.nodes:
                # Lock context propagates through calls; a read-side hold
                # does NOT (the callee's mutations still race siblings).
                queue.append((tgt, locked,
                              chain + (_node_display(graph.nodes[tgt]),)))
        for child in ast.iter_child_nodes(n):
            scan(child, locked, in_read)

    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for child in body:
        scan(child, locked0)


# ---------------------------------------------------------------------------
# check: fiber-blocking-sleep (interprocedural over the call graph)
# ---------------------------------------------------------------------------

def _is_sanctioned_sleep_module(path: str) -> bool:
    """The resilience module OWNS blocking sleeps (``sleep_ms`` /
    ``Backoff`` — deadline-capped, deterministically jittered); its
    internals are exempt and calls resolving into it are not followed."""
    return _stable_path(path).startswith("brpc_tpu/resilience")


def _time_sleep_aliases(sc: _FileScan) -> Tuple[Set[str], Set[str]]:
    """(module aliases of ``time``, bare names bound to ``time.sleep``)
    in this file."""
    mods: Set[str] = set()
    bares: Set[str] = set()
    for node in ast.walk(sc.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "time":
                    mods.add(alias.asname or "time")
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name == "sleep":
                    bares.add(alias.asname or "sleep")
    return mods, bares


def _check_fiber_blocking_sleep(scans: List[_FileScan],
                                graph: CallGraph) -> List[Finding]:
    sc_by_path = {sc.path: sc for sc in scans}
    mi_by_path = {mi.path: mi for mi in graph.modules.values()}
    aliases: Dict[str, Tuple[Set[str], Set[str]]] = {}
    roots: List[str] = []
    for sc in scans:
        mi = mi_by_path.get(sc.path)
        top = graph.nodes.get(f"{mi.name}:<module>") if mi else None
        roots.extend(_find_handler_roots(sc, graph, top))
    findings: List[Finding] = []
    visited: Set[str] = set()
    queue: List[Tuple[str, Tuple[str, ...]]] = [
        (r, (_node_display(graph.nodes[r]),))
        for r in roots if r in graph.nodes]
    while queue:
        node_id, chain = queue.pop()
        if node_id in visited:
            continue
        visited.add(node_id)
        node = graph.nodes.get(node_id)
        if node is None or node.path not in sc_by_path:
            continue
        if _is_sanctioned_sleep_module(node.path):
            continue
        sc = sc_by_path[node.path]
        if sc.path not in aliases:
            aliases[sc.path] = _time_sleep_aliases(sc)
        time_mods, sleep_bares = aliases[sc.path]
        display = _node_display(node)

        def flag(n: ast.AST, desc: str) -> None:
            via = f" [reached via {' -> '.join(chain)}]" \
                if len(chain) > 1 else ""
            findings.append(Finding(
                "fiber-blocking-sleep", sc.path, n.lineno,
                f"handler-reachable {display} calls {desc} — it parks "
                f"the fiber worker PTHREAD (not just the fiber), "
                f"stalling every handler scheduled on it; use "
                f"brpc_tpu.resilience sleep_ms/Backoff (deadline-capped "
                f"backoff) or an event wait{via}"))

        def scan(n: ast.AST) -> None:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
                return  # nested defs audit when reachable themselves
            if isinstance(n, ast.Call):
                f = n.func
                if isinstance(f, ast.Attribute) and f.attr == "sleep" \
                        and _root_name(f) in time_mods:
                    flag(n, f"{_describe(f)}()")
                elif isinstance(f, ast.Name) and f.id in sleep_bares:
                    flag(n, f"{f.id}() (imported from time)")
                tgt = graph.call_target(n)
                if tgt is not None and tgt in graph.nodes and \
                        not _is_sanctioned_sleep_module(
                            graph.nodes[tgt].path):
                    queue.append(
                        (tgt, chain + (_node_display(graph.nodes[tgt]),)))
            for child in ast.iter_child_nodes(n):
                scan(child)

        body = node.fn.body if isinstance(node.fn.body, list) \
            else [node.fn.body]
        for child in body:
            scan(child)
    return findings


# ---------------------------------------------------------------------------
# check: obs-guard
# ---------------------------------------------------------------------------

def _in_pkg_dir(path: str, dirname: str) -> bool:
    parts = os.path.normpath(path).split(os.sep)
    return dirname in parts


def _check_obs_guard(sc: _FileScan) -> List[Finding]:
    if _in_pkg_dir(sc.path, "obs"):
        return []  # the obs package itself owns the Registry
    findings: List[Finding] = []
    for node in ast.walk(sc.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        hit: Optional[str] = None
        if isinstance(fn, ast.Name) and fn.id in _OBS_GUARDED and \
                fn.id in sc.obs_imported_names:
            hit = fn.id
        elif isinstance(fn, ast.Attribute) and fn.attr in _OBS_GUARDED:
            root = _root_name(fn)
            if root in sc.obs_module_aliases:
                hit = f"{root}.{fn.attr}"
            elif fn.attr == "expose" and isinstance(fn.value, ast.Call) and \
                    _last_name(fn.value.func) in _OBS_GUARDED:
                hit = f"{_describe(fn.value.func)}().expose"
        if hit:
            findings.append(Finding(
                "obs-guard", sc.path, node.lineno,
                f"direct obs call '{hit}' outside brpc_tpu/obs — hot-path "
                f"instrumentation must use the no-op-able helpers "
                f"(obs.counter / obs.recorder / obs.record_span) so "
                f"disabling observability disables the cost"))
    return findings


# ---------------------------------------------------------------------------
# check: trace-purity (interprocedural over the call graph)
# ---------------------------------------------------------------------------

def _is_tracer_expr(expr: ast.AST) -> bool:
    return _last_name(expr) in _TRACERS


def _is_tracing_decorator(dec: ast.AST) -> bool:
    if _is_tracer_expr(dec):
        return True
    if isinstance(dec, ast.Call):
        if _is_tracer_expr(dec.func):
            return True  # @jax.jit(...) / @shard_map(mesh=...)
        if _last_name(dec.func) == "partial" and dec.args and \
                _is_tracer_expr(dec.args[0]):
            return True  # @partial(jax.jit, ...) / @partial(shard_map, ...)
    return False


def _traced_functions(tree: ast.Module) -> List[ast.AST]:
    traced: List[ast.AST] = []
    by_name: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name[node.name] = node
            if any(_is_tracing_decorator(d) for d in node.decorator_list):
                traced.append(node)
        elif isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Lambda):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    by_name[tgt.id] = node.value
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_tracer_expr(node.func) \
                and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Lambda):
                traced.append(arg)
            elif isinstance(arg, ast.Name) and arg.id in by_name:
                traced.append(by_name[arg.id])
    # dedup while keeping order
    seen: Set[int] = set()
    out = []
    for fn in traced:
        if id(fn) not in seen:
            seen.add(id(fn))
            out.append(fn)
    return out


def _host_callback_desc(node: ast.Call) -> Optional[str]:
    f = node.func
    if _last_name(f) in _HOST_CALLBACKS:
        return _describe(f)
    if isinstance(f, ast.Attribute) and \
            f.attr in ("print", "callback", "breakpoint") and \
            _last_name(f.value) == "debug":
        return _describe(f)  # jax.debug.print / debug.callback / ...
    return None


def _check_trace_purity(scans: List[_FileScan],
                        graph: CallGraph) -> List[Finding]:
    findings: List[Finding] = []
    sc_by_path = {sc.path: sc for sc in scans}
    for sc in scans:
        for fn in _traced_functions(sc.tree):
            root_name = getattr(fn, "name", "<lambda>")
            _walk_traced(sc, fn, root_name, graph, sc_by_path, findings)
    return findings


def _walk_traced(root_sc: _FileScan, root_fn: ast.AST, root_name: str,
                 graph: CallGraph, sc_by_path: Dict[str, _FileScan],
                 findings: List[Finding]) -> None:
    scanned: Set[int] = set()
    visited_nodes: Set[str] = set()
    # (fn ast, owning scan, display name, chain from the traced root)
    stack: List[Tuple[ast.AST, _FileScan, str, Tuple[str, ...]]] = [
        (root_fn, root_sc, root_name, (root_name,))]

    def impure(sc: _FileScan, node: ast.AST, name: str,
               chain: Tuple[str, ...], what: str) -> None:
        if len(chain) > 1:
            where = (f"{what} inside '{name}' reached from traced "
                     f"'{root_name}' via call chain {' -> '.join(chain)}")
        else:
            where = (f"{what} inside '{name}' which is traced by "
                     f"jax.jit/shard_map")
        findings.append(Finding(
            "trace-purity", sc.path, node.lineno,
            f"{where} — it runs once at trace time and vanishes from the "
            f"compiled program"))

    def host_cb(sc: _FileScan, node: ast.AST, name: str,
                chain: Tuple[str, ...], desc: str) -> None:
        via = (f" via call chain {' -> '.join(chain)}"
               if len(chain) > 1 else "")
        findings.append(Finding(
            "trace-purity", sc.path, node.lineno,
            f"host callback '{desc}' inside '{name}' under "
            f"jax.jit/shard_map trace{via} — it stages a host round-trip "
            f"into every compiled step; allowlist the site with "
            f"`# {_ALLOW_HOST_CB}` if intended"))

    while stack:
        fn, sc, name, chain = stack.pop()
        if id(fn) in scanned:
            continue
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                scanned.add(id(node))
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if _is_lockish_ctx(item.context_expr):
                        impure(sc, node, name, chain,
                               f"lock acquisition "
                               f"'{_describe(item.context_expr)}'")
            if not isinstance(node, ast.Call):
                continue
            if sc.line_has(node.lineno, _ALLOW_TRACE_IMPURE):
                continue  # declared deliberate trace-time effect
            cb = _host_callback_desc(node)
            if cb is not None and not sc.line_has(node.lineno,
                                                 _ALLOW_HOST_CB):
                host_cb(sc, node, name, chain, cb)
            f = node.func
            if isinstance(f, ast.Name) and f.id == "print":
                impure(sc, node, name, chain, "print()")
            elif isinstance(f, ast.Attribute):
                root = _root_name(f)
                if root == "time" and f.attr in _TIME_FNS:
                    impure(sc, node, name, chain,
                           f"wall-clock call time.{f.attr}()")
                elif f.attr in ("acquire", "release") and \
                        _is_lockish_ctx(f.value):
                    impure(sc, node, name, chain,
                           f"lock call '{_describe(f)}()'")
                elif root == "obs" or root in sc.obs_module_aliases:
                    impure(sc, node, name, chain,
                           f"obs instrumentation '{_describe(f)}()'")
                elif root == "threading" and f.attr in ("Lock", "RLock"):
                    impure(sc, node, name, chain, "lock construction")
            tgt = graph.call_target(node)
            if tgt is not None and tgt not in visited_nodes:
                visited_nodes.add(tgt)
                callee = graph.nodes.get(tgt)
                if callee is None or callee.qual == "<module>":
                    continue
                callee_sc = sc_by_path.get(callee.path)
                if callee_sc is not None and callee_sc.line_has(
                        getattr(callee.fn, "lineno", 0),
                        _ALLOW_TRACE_IMPURE):
                    continue  # def-level: deliberate trace-time function
                if callee_sc is not None and id(callee.fn) not in scanned:
                    stack.append((callee.fn, callee_sc,
                                  _node_display(callee),
                                  chain + (_node_display(callee),)))


# ---------------------------------------------------------------------------
# check: lock-order (static inversion cycles over the call graph)
# ---------------------------------------------------------------------------

def _collect_checked_locks(scans: List[_FileScan], graph: CallGraph
                           ) -> Tuple[Dict[str, Dict[str, str]],
                                      Dict[Tuple[str, str], Dict[str, str]],
                                      Dict[str, Dict[str, Dict[str, str]]],
                                      Dict[Tuple[str, str],
                                           Dict[str, Dict[str, str]]]]:
    """Map ``x = checked_lock("name")`` assignments to lock names:
    per-module ``var -> name``, per-class ``self.attr -> name``,
    per-module literal-dict CONTAINERS ``var -> {key -> name}`` (a
    module-level ``LOCKS = {"a": checked_lock(...), "b": A}`` makes
    ``LOCKS["a"]`` resolvable by key), and per-CLASS literal-dict
    containers ``(module, cls) -> attr -> {key -> name}`` (a class-scope
    ``LOCKS = {...}`` makes ``self.LOCKS["a"]`` resolvable the same
    way)."""
    mi_by_path = {mi.path: mi for mi in graph.modules.values()}
    mod_locks: Dict[str, Dict[str, str]] = {}
    cls_locks: Dict[Tuple[str, str], Dict[str, str]] = {}
    cont_locks: Dict[str, Dict[str, Dict[str, str]]] = {}
    ccont_locks: Dict[Tuple[str, str], Dict[str, Dict[str, str]]] = {}

    def lock_name(value: ast.AST) -> Optional[str]:
        if isinstance(value, ast.Call) and \
                _last_name(value.func) in ("checked_lock",
                                           "checked_rwlock") and \
                value.args and \
                isinstance(value.args[0], ast.Constant) and \
                isinstance(value.args[0].value, str):
            return value.args[0].value
        return None

    for sc in scans:
        mi = mi_by_path.get(sc.path)
        if mi is None:
            continue
        for node in ast.walk(sc.tree):
            if not isinstance(node, ast.Assign):
                continue
            name = lock_name(node.value)
            if name is None:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    mod_locks.setdefault(mi.name, {})[tgt.id] = name
        for stmt in sc.tree.body:
            if not isinstance(stmt, ast.ClassDef):
                continue
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Assign):
                    continue
                name = lock_name(node.value)
                if name is None:
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == "self":
                        cls_locks.setdefault(
                            (mi.name, stmt.name), {})[tgt.attr] = name
    # Second sweep: MODULE-LEVEL literal dict containers.  Values may be
    # direct checked_lock(...) calls or names of locks collected above
    # (same module), so this runs after the direct pass.
    for sc in scans:
        mi = mi_by_path.get(sc.path)
        if mi is None:
            continue

        def dict_entries(value: ast.Dict) -> Dict[str, str]:
            entries: Dict[str, str] = {}
            for k, v in zip(value.keys, value.values):
                if not (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)):
                    continue
                name = lock_name(v)
                if name is None and isinstance(v, ast.Name):
                    name = mod_locks.get(mi.name, {}).get(v.id)
                if name is not None:
                    entries[k.value] = name
            return entries

        for stmt in sc.tree.body:
            if isinstance(stmt, ast.Assign) and \
                    isinstance(stmt.value, ast.Dict):
                entries = dict_entries(stmt.value)
                if entries:
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name):
                            cont_locks.setdefault(
                                mi.name, {})[tgt.id] = entries
            elif isinstance(stmt, ast.ClassDef):
                # class-scope literal dicts: `self.LOCKS["a"]` binds by
                # key exactly like the module-level form
                for inner in stmt.body:
                    if not (isinstance(inner, ast.Assign)
                            and isinstance(inner.value, ast.Dict)):
                        continue
                    entries = dict_entries(inner.value)
                    if entries:
                        for tgt in inner.targets:
                            if isinstance(tgt, ast.Name):
                                ccont_locks.setdefault(
                                    (mi.name, stmt.name),
                                    {})[tgt.id] = entries
    # Third sweep: INHERITED class-scope containers.  `self.LOCKS["a"]`
    # in a subclass resolves through the base chain's DIRECT class
    # bodies (nearest assignment wins, bases left-to-right depth-first
    # through the call graph's class resolution).  Any direct
    # assignment of the same name in a nearer class shadows the
    # inherited mapping — a class that rebuilds the container
    # non-literally stays deferred — and a container MUTATED anywhere
    # along the chain (subscript-store or in-place mutator on
    # ``self.<attr>``) is never inherited: dynamic-harness territory,
    # same policy as the module-level form.
    cls_defs: Dict[Tuple[str, str], Tuple[object, ast.ClassDef]] = {}
    cls_assigned: Dict[Tuple[str, str], Set[str]] = {}
    cls_mutated: Dict[Tuple[str, str], Set[str]] = {}
    for sc in scans:
        mi = mi_by_path.get(sc.path)
        if mi is None:
            continue
        for stmt in sc.tree.body:
            if not isinstance(stmt, ast.ClassDef):
                continue
            key = (mi.name, stmt.name)
            cls_defs[key] = (mi, stmt)
            names: Set[str] = set()
            for inner in stmt.body:
                if isinstance(inner, ast.Assign):
                    names.update(t.id for t in inner.targets
                                 if isinstance(t, ast.Name))
                elif isinstance(inner, ast.AnnAssign) and \
                        isinstance(inner.target, ast.Name):
                    names.add(inner.target.id)
            cls_assigned[key] = names
            mut: Set[str] = set()
            for node in ast.walk(stmt):
                if isinstance(node, (ast.Assign, ast.Delete)):
                    for t in node.targets:
                        if isinstance(t, ast.Subscript) and \
                                isinstance(t.value, ast.Attribute):
                            mut.add(t.value.attr)
                elif isinstance(node, ast.AugAssign) and \
                        isinstance(node.target, ast.Subscript) and \
                        isinstance(node.target.value, ast.Attribute):
                    mut.add(node.target.value.attr)
                elif isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr in _MUTATORS and \
                        isinstance(node.func.value, ast.Attribute):
                    mut.add(node.func.value.attr)
            cls_mutated[key] = mut

    def chain(key: Tuple[str, str],
              seen: Set[Tuple[str, str]]) -> List[Tuple[str, str]]:
        if key in seen or key not in cls_defs:
            return []
        seen.add(key)
        cmi, cdef = cls_defs[key]
        out = [key]
        for base in cdef.bases:
            bname = _last_name(base)
            if bname is None:
                continue
            binfo = graph._resolve_class(cmi, bname)
            if binfo is None:
                continue
            out.extend(chain((binfo.module, binfo.name), seen))
        return out

    for key in list(cls_defs):
        order = chain(key, set())
        if len(order) < 2:
            continue
        mutated_chain: Set[str] = set()
        for k in order:
            mutated_chain |= cls_mutated.get(k, set())
        claimed: Set[str] = set()
        for k in order:
            for attr in sorted(cls_assigned.get(k, ())):
                if attr in claimed:
                    continue
                claimed.add(attr)
                if k == key:
                    continue          # direct entries already collected
                entries = ccont_locks.get(k, {}).get(attr)
                if entries and attr not in mutated_chain:
                    ccont_locks.setdefault(key, {})[attr] = dict(entries)
    return mod_locks, cls_locks, cont_locks, ccont_locks


def _order_path(adj: Dict[str, Set[str]], src: str,
                dst: str) -> Optional[List[str]]:
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in sorted(adj.get(node, ())):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _make_lock_resolver(graph: CallGraph,
                        mod_locks: Dict[str, Dict[str, str]],
                        cls_locks: Dict[Tuple[str, str], Dict[str, str]],
                        cont_locks: Dict[str, Dict[str, Dict[str, str]]],
                        ccont_locks: Dict[Tuple[str, str],
                                          Dict[str, Dict[str, str]]]):
    """Shared lock-expression resolver over the maps from
    :func:`_collect_checked_locks` — used by ``lock-order`` and
    ``lock-exception-safety`` so both checks name locks identically."""

    def _target_module(node: FuncNode, root: str):
        """Resolve an imported-module alias / from-import in ``node``'s
        module to the graph module it names (or None)."""
        mi = graph.modules[node.module]
        target_name = mi.import_aliases.get(root)
        if target_name is None and root in mi.from_imports:
            m, orig = mi.from_imports[root]
            target_name = f"{m}.{orig}" if m else orig
        return graph._find_module(target_name) if target_name else None

    def resolve_lock(expr: ast.AST, node: FuncNode,
                     param_locks: Optional[Dict[str, str]] = None
                     ) -> Optional[str]:
        if isinstance(expr, ast.Call):
            # rwlock sides: `with rw.read():` / `.write()` acquire under
            # the lock's one name, exactly as the dynamic harness keys
            # them (a read-vs-write split would hide r/w inversions).
            f = expr.func
            if isinstance(f, ast.Attribute) and f.attr in _RW_SIDES:
                return resolve_lock(f.value, node, param_locks)
            return None
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) and expr.value.id == "self" \
                    and node.cls is not None:
                return cls_locks.get((node.module, node.cls),
                                     {}).get(expr.attr)
            root = _root_name(expr)
            if root is None:
                return None
            mi = graph.modules[node.module]
            target_name = mi.import_aliases.get(root)
            if target_name is None and root in mi.from_imports:
                m, orig = mi.from_imports[root]
                target_name = f"{m}.{orig}" if m else orig
            if target_name:
                target = graph._find_module(target_name)
                if target is not None:
                    return mod_locks.get(target.name, {}).get(expr.attr)
            return None
        if isinstance(expr, ast.Subscript):
            # Container-stored locks: `LOCKS["a"]` where LOCKS is a
            # module-level literal dict — the subscript load binds by
            # key (closes the last PR-3 lock blind spot; non-constant
            # keys and non-literal containers stay unresolved).
            sl = expr.slice
            if not (isinstance(sl, ast.Constant)
                    and isinstance(sl.value, str)):
                return None
            base = expr.value
            if isinstance(base, ast.Attribute) and \
                    isinstance(base.value, ast.Name) and \
                    base.value.id == "self" and node.cls is not None:
                # `self.LOCKS["a"]`: class-scope literal-dict container
                hit = ccont_locks.get((node.module, node.cls),
                                      {}).get(base.attr, {}).get(sl.value)
                if hit is not None:
                    return hit
            if isinstance(base, ast.Name):
                cont = cont_locks.get(node.module, {}).get(base.id)
                if cont is None:
                    # `from mod import LOCKS`: the container lives in
                    # the source module under its original name.
                    mi = graph.modules[node.module]
                    if base.id in mi.from_imports:
                        m, orig = mi.from_imports[base.id]
                        target = graph._find_module(m) if m else None
                        if target is not None:
                            cont = cont_locks.get(target.name,
                                                  {}).get(orig)
                return cont.get(sl.value) if cont else None
            if isinstance(base, ast.Attribute) and \
                    isinstance(base.value, ast.Name):
                # `mod.LOCKS["a"]` through an imported module
                target = _target_module(node, base.value.id)
                if target is not None:
                    return cont_locks.get(target.name,
                                          {}).get(base.attr,
                                                  {}).get(sl.value)
            return None
        if isinstance(expr, ast.Name):
            if param_locks and expr.id in param_locks:
                # a lock received as a function PARAMETER, named by
                # binding the caller's argument through the call graph
                return param_locks[expr.id]
            return mod_locks.get(node.module, {}).get(expr.id)
        return None

    return resolve_lock


def _check_lock_order(scans: List[_FileScan],
                      graph: CallGraph) -> List[Finding]:
    mod_locks, cls_locks, cont_locks, ccont_locks = \
        _collect_checked_locks(scans, graph)
    if not mod_locks and not cls_locks and not cont_locks \
            and not ccont_locks:
        return []
    resolve_lock = _make_lock_resolver(graph, mod_locks, cls_locks,
                                       cont_locks, ccont_locks)

    # acquisition edges: (held, acquired) -> first site (path, line, chain)
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    adj: Dict[str, Set[str]] = {}
    memo: Set[Tuple[str, Tuple[str, ...], Tuple[Tuple[str, str], ...]]] = \
        set()

    def callee_bindings(call: ast.Call, node: FuncNode,
                        callee: FuncNode,
                        params: Dict[str, str]) -> Dict[str, str]:
        """Bind lock-valued arguments of `call` to the callee's parameter
        names, so `def use(lk): with lk:` acquires under the CALLER's
        lock name (with module-literal containers also resolved, the
        PR-3 lock blind spots are closed; locks in mutated/non-literal
        containers stay dynamic-harness-only)."""
        cargs = getattr(callee.fn, "args", None)
        if cargs is None:
            return {}
        names = [a.arg for a in (list(cargs.posonlyargs) +
                                 list(cargs.args))]
        offset = 1 if callee.cls is not None and names and \
            names[0] == "self" else 0
        out: Dict[str, str] = {}
        for i, arg in enumerate(call.args):
            ln = resolve_lock(arg, node, params)
            if ln is not None and offset + i < len(names):
                out[names[offset + i]] = ln
        kw_ok = set(names) | {a.arg for a in cargs.kwonlyargs}
        for kw in call.keywords:
            if kw.arg is None:
                continue
            ln = resolve_lock(kw.value, node, params)
            if ln is not None and kw.arg in kw_ok:
                out[kw.arg] = ln
        return out

    def walk(node_id: str, held: Tuple[str, ...],
             chain: Tuple[str, ...],
             param_locks: Tuple[Tuple[str, str], ...] = ()) -> None:
        key = (node_id, tuple(sorted(set(held))), param_locks)
        if key in memo or len(chain) > 25:
            return
        memo.add(key)
        node = graph.nodes.get(node_id)
        if node is None:
            return
        params = dict(param_locks)

        def scan(n: ast.AST, held: Tuple[str, ...]) -> None:
            if isinstance(n, (ast.With, ast.AsyncWith)):
                new_held = held
                for item in n.items:
                    ln = resolve_lock(item.context_expr, node, params)
                    if ln is None:
                        continue
                    for h in new_held:
                        if h != ln and (h, ln) not in edges:
                            edges[(h, ln)] = (node.path, n.lineno,
                                              " -> ".join(chain))
                            adj.setdefault(h, set()).add(ln)
                    if ln not in new_held:
                        new_held = new_held + (ln,)
                for child in n.body:
                    scan(child, new_held)
                return
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
                return
            if isinstance(n, ast.Call):
                tgt = graph.call_target(n)
                if tgt is not None and tgt in graph.nodes:
                    callee = graph.nodes[tgt]
                    bound = callee_bindings(n, node, callee, params)
                    walk(tgt, held,
                         chain + (_node_display(callee),),
                         tuple(sorted(bound.items())))
            for child in ast.iter_child_nodes(n):
                scan(child, held)

        body = node.fn.body if isinstance(node.fn.body, list) \
            else [node.fn.body]
        for child in body:
            scan(child, held)

    for node_id in sorted(graph.nodes):
        node = graph.nodes[node_id]
        walk(node_id, (), (_node_display(node),))

    findings: List[Finding] = []
    reported: Set[frozenset] = set()
    for (a, b), (path, line, chain_desc) in sorted(edges.items()):
        cyc = _order_path(adj, b, a)
        if cyc is None:
            continue
        cyc_set = frozenset([a] + cyc)
        if cyc_set in reported:
            continue
        reported.add(cyc_set)
        opposite = edges.get((cyc[0], cyc[1])) if len(cyc) > 1 else None
        opp_desc = f"; opposite order acquired in {opposite[2]}" \
            if opposite else ""
        findings.append(Finding(
            "lock-order", path, line,
            f"static lock-order inversion: acquiring '{b}' while holding "
            f"'{a}' (in {chain_desc}) closes the cycle "
            f"{' -> '.join([a] + cyc)} — the two orders can deadlock under "
            f"the right interleaving{opp_desc}"))
    return findings


# ---------------------------------------------------------------------------
# check: lock-exception-safety (manual acquire/release across throwing edges)
# ---------------------------------------------------------------------------


def _check_lock_exception_safety(scans: List[_FileScan],
                                 graph: CallGraph) -> List[Finding]:
    """Two exception-unwind obligations on the may-throw fixpoint:

    1. a ``checked_lock``/``checked_rwlock`` acquired via a bare
       ``.acquire()`` (outside ``with``) and still held at a site the
       fixpoint PROVES can raise — unless an enclosing ``finally``
       releases the lock or a handler that catches every thrown type
       does — leaves the lock held forever on the unwinding edge;
    2. a fence flag (``self.x = True`` … ``self.x = False`` in the same
       block) with a proven-throwing site between set and reset and no
       ``try/finally`` resetting it — the flag is left half-done.

    Unresolved calls (external confidence) never produce findings."""
    mod_locks, cls_locks, cont_locks, ccont_locks = \
        _collect_checked_locks(scans, graph)
    findings: List[Finding] = []
    resolve_lock = _make_lock_resolver(graph, mod_locks, cls_locks,
                                       cont_locks, ccont_locks)
    sc_paths = {sc.path for sc in scans}
    reported: Set[Tuple[str, str]] = set()

    def releases_in(stmts: List[ast.AST], fnode: FuncNode) -> Set[str]:
        out: Set[str] = set()
        for s in stmts:
            for n in ast.walk(s):
                if isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute) and \
                        n.func.attr == "release":
                    ln = resolve_lock(n.func.value, fnode)
                    if ln is not None:
                        out.add(ln)
        return out

    def throw_events(n: ast.AST
                     ) -> Optional[Tuple[List[Optional[str]], str]]:
        """(thrown types, description) when ``n`` is a proven-throwing
        site — an explicit raise or a call with a proven summary."""
        if isinstance(n, ast.Raise):
            t = graph.raised_type_name(n)
            return [t], f"raise {t or 'of a dynamic type'}"
        if isinstance(n, ast.Call):
            tgt = graph.call_target(n)
            if tgt is None:
                return None
            summ = graph.throw_summary(tgt)
            if not summ.may_throw:
                return None
            thrown = list(summ.types) + ([None] if summ.unknown else [])
            callee = graph.nodes.get(tgt)
            cdisp = _node_display(callee) if callee else tgt
            tdesc = "/".join(summ.types) if summ.types else "an exception"
            return thrown, f"call to {cdisp}, which can raise {tdesc}"
        return None

    def flag_held(fnode: FuncNode, held: Dict[str, int], line: int,
                  thrown: List[Optional[str]], desc: str,
                  fin_locks: Set[str], scopes: Tuple) -> None:
        for lname in sorted(held):
            if lname in fin_locks:
                continue
            if all(any(graph.exception_catches(c, t) and lname in rel
                       for c, rel in scopes) for t in thrown):
                continue
            key = (fnode.node_id, lname)
            if key in reported:
                continue
            reported.add(key)
            findings.append(Finding(
                "lock-exception-safety", fnode.path, line,
                f"{_node_display(fnode)}: checked lock '{lname}' "
                f"acquired at line {held[lname]} outside `with` is "
                f"still held at this may-throw site ({desc}) — the "
                f"unwinding edge leaves it locked forever; acquire "
                f"with `with` or pair acquire/release in try/finally"))

    def scan(n: ast.AST, fnode: FuncNode, held: Dict[str, int],
             fin_locks: Set[str], scopes: Tuple) -> None:
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            return
        if isinstance(n, ast.Try):
            fin2 = fin_locks | releases_in(list(n.finalbody), fnode)
            sc2 = scopes + tuple(
                (graph.handler_catch_names(h),
                 frozenset(releases_in(list(h.body), fnode)))
                for h in n.handlers)
            for s in n.body:
                scan(s, fnode, held, fin2, sc2)
            for s in n.orelse:
                scan(s, fnode, held, fin2, scopes)
            for h in n.handlers:
                for s in h.body:
                    scan(s, fnode, held, fin_locks, scopes)
            for s in n.finalbody:
                scan(s, fnode, held, fin_locks, scopes)
            return
        if isinstance(n, ast.Call):
            f = n.func
            if isinstance(f, ast.Attribute) and \
                    f.attr in ("acquire", "release"):
                ln = resolve_lock(f.value, fnode)
                if ln is not None:
                    if f.attr == "acquire":
                        held[ln] = n.lineno
                    else:
                        held.pop(ln, None)
                    return
            ev = throw_events(n)
            if ev is not None and held:
                flag_held(fnode, held, n.lineno, ev[0], ev[1],
                          fin_locks, scopes)
        elif isinstance(n, ast.Raise) and held:
            ev = throw_events(n)
            flag_held(fnode, held, n.lineno, ev[0], ev[1], fin_locks,
                      scopes)
        for child in ast.iter_child_nodes(n):
            scan(child, fnode, held, fin_locks, scopes)

    def scan_flags(fnode: FuncNode) -> None:
        """Fence flags: self.<x> = True ... self.<x> = False with a
        proven-throwing site between, no finally resetting it."""

        def flag_attr(s: ast.AST, value: bool) -> Optional[str]:
            if isinstance(s, ast.Assign) and len(s.targets) == 1 and \
                    isinstance(s.value, ast.Constant) and \
                    s.value.value is value:
                return _self_attr_of(s.targets[0])
            return None

        def first_throw_in(s: ast.AST, attr: str
                           ) -> Optional[Tuple[int, str]]:
            # skip subtrees protected by a finally that resets the flag
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
                return None
            if isinstance(s, ast.Try) and any(
                    flag_attr(fs, False) == attr or
                    flag_attr(fs, True) == attr
                    for fs in s.finalbody):
                return None
            ev = throw_events(s)
            if ev is not None:
                return s.lineno, ev[1]
            for child in ast.iter_child_nodes(s):
                hit = first_throw_in(child, attr)
                if hit is not None:
                    return hit
            return None

        def blocks(n: ast.AST):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)) and \
                    n is not fnode.fn:
                return
            for field in ("body", "orelse", "finalbody"):
                b = getattr(n, field, None)
                if isinstance(b, list) and b and isinstance(b[0], ast.stmt):
                    yield b
            for child in ast.iter_child_nodes(n):
                yield from blocks(child)

        for block in blocks(fnode.fn):
            pending: Dict[str, Tuple[int, int]] = {}
            for idx, s in enumerate(block):
                a_set = flag_attr(s, True)
                if a_set is not None:
                    pending[a_set] = (s.lineno, idx)
                    continue
                a_clr = flag_attr(s, False)
                if a_clr is not None and a_clr in pending:
                    set_line, set_idx = pending.pop(a_clr)
                    for span_stmt in block[set_idx + 1:idx]:
                        hit = first_throw_in(span_stmt, a_clr)
                        if hit is None:
                            continue
                        key = (fnode.node_id, f"flag:{a_clr}")
                        if key in reported:
                            break
                        reported.add(key)
                        findings.append(Finding(
                            "lock-exception-safety", fnode.path, hit[0],
                            f"{_node_display(fnode)}: fence flag "
                            f"self.{a_clr} is set at line {set_line} "
                            f"and reset at line {s.lineno}, but this "
                            f"may-throw site between them ({hit[1]}) "
                            f"can unwind with the flag still set — "
                            f"half-done obligation; reset it in a "
                            f"finally"))
                        break

    for node_id in sorted(graph.nodes):
        fnode = graph.nodes[node_id]
        if not isinstance(fnode.fn, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
            continue
        if fnode.path not in sc_paths:
            continue
        held: Dict[str, int] = {}
        for stmt in fnode.fn.body:
            scan(stmt, fnode, held, set(), ())
        scan_flags(fnode)
    return findings


# ---------------------------------------------------------------------------
# check: handle-lifecycle (interprocedural ownership over the call graph)
# ---------------------------------------------------------------------------

class _HBinding:
    """One live owned handle bound to a local name.  Branch copies of the
    flow state SHARE binding objects, so a release observed on any path
    marks the same object every sibling path sees — reporting stays
    may-leak at explicit exits (the state at THAT point) and must-leak
    nowhere (no false positives from merge order).

    A binding with ``members is not None`` is a LOCAL CONTAINER (``pcs =
    []``) rather than a handle: appends of owned handles move their
    obligation into ``members`` (the may-leak set), and the container is
    released by draining it (a loop or comprehension releasing each
    element), returning it, or storing it on an owner."""

    __slots__ = ("kind", "line", "origin", "released", "members")

    def __init__(self, kind: str, line: int, origin: str = "",
                 members: Optional[Set[str]] = None):
        self.kind = kind
        self.line = line
        self.origin = origin
        self.released = False
        self.members = members

    @property
    def live(self) -> bool:
        """Carries an unmet obligation (a container is only live once it
        actually holds handles)."""
        if self.released:
            return False
        return self.members is None or bool(self.members)


def _handle_producer_nodes(graph: CallGraph) -> Dict[str, str]:
    """node id -> produced owner class, for the constructors and factory
    methods of the ``rpc`` module's owner table."""
    producers: Dict[str, str] = {}
    for mi in graph.modules.values():
        if mi.name != "brpc_tpu.rpc" and mi.name.split(".")[-1] != "rpc":
            continue
        for cls in _HANDLE_OWNERS:
            ci = mi.classes.get(cls)
            if ci is not None and "__init__" in ci.methods:
                producers[ci.methods["__init__"]] = cls
        for (cls, meth), kind in _HANDLE_FACTORIES.items():
            ci = mi.classes.get(cls)
            if ci is not None and meth in ci.methods:
                producers[ci.methods[meth]] = kind
    return producers


def _name_chain(expr: ast.AST) -> Optional[List[str]]:
    """['rpc', 'Channel'] for ``rpc.Channel``; None unless Name-rooted."""
    parts: List[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return list(reversed(parts))
    return None


def _is_rpc_module_name(name: str) -> bool:
    return name == "brpc_tpu.rpc" or name.split(".")[-1] == "rpc"


def _producer_kind(call: ast.Call, graph: CallGraph, module: str,
                   producers: Dict[str, str],
                   sources: Dict[str, Tuple[str, str]]
                   ) -> Optional[Tuple[str, str]]:
    """(owner kind, origin description) when this call returns a FRESH
    owning handle; None otherwise.  ``module`` is the calling module (for
    import-aware constructor resolution)."""
    tgt = graph.call_target(call)
    if tgt is not None:
        kind = producers.get(tgt)
        if kind is not None:
            return kind, ""
        src = sources.get(tgt)
        if src is not None:
            return src
        return None
    f = call.func
    # Constructor of an owner class (covers classes whose __init__ is
    # inherited/implicit, where no call edge exists)
    parts = _name_chain(f)
    mi = graph.modules.get(module)
    if parts is not None and mi is not None:
        hit = graph._class_from_dotted(parts, mi)
        if hit is not None and _is_rpc_module_name(hit[0].name) and \
                hit[1] in _HANDLE_OWNERS:
            return hit[1], ""
    if isinstance(f, ast.Attribute) and f.attr in _FACTORY_NAME_FALLBACK:
        return _FACTORY_NAME_FALLBACK[f.attr], ""
    return None


def _handle_sources(graph: CallGraph, producers: Dict[str, str]
                    ) -> Dict[str, Tuple[str, str]]:
    """Functions that hand a FRESH owning handle to their caller: every
    valued top-scope ``return`` is a producer call, or a local whose
    every top-scope assignment is a producer call of one kind (``return
    None`` error arms are neutral).  Cached accessors — a local that is
    ALSO assigned from a dict lookup, like ``obs.recorder`` — do not
    qualify: they return a handle the callee still owns, and claiming
    ownership at the caller would be a false finding."""
    sources: Dict[str, Tuple[str, str]] = {}
    for node in graph.nodes.values():
        fn = node.fn
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) or \
                node.node_id in producers:
            continue
        # top-scope assignments per local name (nested scopes excluded)
        assigns: Dict[str, List[ast.AST]] = {}
        returns: List[ast.expr] = []

        def scan(n: ast.AST) -> None:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
                return
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                    isinstance(n.targets[0], ast.Name):
                assigns.setdefault(n.targets[0].id, []).append(n.value)
            elif isinstance(n, ast.Return) and n.value is not None:
                returns.append(n.value)
            for child in ast.iter_child_nodes(n):
                scan(child)

        for stmt in fn.body:
            scan(stmt)
        kinds: Set[str] = set()
        fresh = bool(returns)
        for value in returns:
            if isinstance(value, ast.Constant) and value.value is None:
                continue  # error arm: neutral
            pk = _producer_kind(value, graph, node.module,
                                producers, {}) \
                if isinstance(value, ast.Call) else None
            if pk is not None:
                kinds.add(pk[0])
                continue
            if isinstance(value, ast.Name):
                vals = assigns.get(value.id, [])
                val_kinds = set()
                ok = bool(vals)
                for v in vals:
                    p = _producer_kind(v, graph, node.module,
                                       producers, {}) \
                        if isinstance(v, ast.Call) else None
                    if p is None:
                        ok = False  # mixed origin: may be a cached handle
                        break
                    val_kinds.add(p[0])
                if ok and len(val_kinds) == 1:
                    kinds.add(next(iter(val_kinds)))
                    continue
            fresh = False
            break
        if fresh and len(kinds) == 1:
            kind = next(iter(kinds))
            sources[node.node_id] = (
                kind, f" (fresh {kind} produced by {_node_display(node)})")
    return sources


def _self_attr_of(tgt: ast.AST) -> Optional[str]:
    """'attr' for self.<attr> or self.<attr>[...] targets, else None."""
    if isinstance(tgt, ast.Subscript):
        tgt = tgt.value
    if isinstance(tgt, ast.Attribute) and \
            isinstance(tgt.value, ast.Name) and tgt.value.id == "self":
        return tgt.attr
    return None


def _check_handle_lifecycle(scans: List[_FileScan], graph: CallGraph,
                            active: Set[str]) -> List[Finding]:
    """Runs the shared handle-flow machinery; normal-path findings carry
    check ``handle-lifecycle``, implicit-exception-edge findings carry
    ``exception-flow`` — ``active`` picks which of the two surface."""
    sc_by_path = {sc.path: sc for sc in scans}
    producers = _handle_producer_nodes(graph)
    findings: List[Finding] = []
    if "handle-lifecycle" in active:
        findings.extend(_check_abi_pairing(scans))
    if not producers:
        return findings
    sources = _handle_sources(graph, producers)
    # (module, class, attr, kind, line, path) for the attr-store audit
    attr_stores: List[Tuple[str, str, str, str, int, str]] = []
    for node in graph.nodes.values():
        if not isinstance(node.fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        sc = sc_by_path.get(node.path)
        if sc is None:
            continue
        _flow_handles(sc, graph, node, producers, sources, attr_stores,
                      findings, active)
    if "handle-lifecycle" in active:
        findings.extend(_audit_attr_stores(attr_stores, graph, sc_by_path))
        findings.extend(_audit_module_producers(graph, sc_by_path,
                                                producers, sources))
    return findings


def _audit_module_producers(graph: CallGraph,
                            sc_by_path: Dict[str, "_FileScan"],
                            producers: Dict[str, str],
                            sources: Dict[str, Tuple[str, str]]
                            ) -> List[Finding]:
    """Module-scope producers audited like attr stores: a global bound
    to a fresh owning handle at import time is fine only if some
    function in the same module releases it (a shutdown/atexit path) —
    otherwise nothing can ever free it."""
    findings: List[Finding] = []
    for mod_name in sorted(graph.modules):
        mi = graph.modules[mod_name]
        sc = sc_by_path.get(mi.path)
        if sc is None:
            continue
        # (global name, kind, line) for module-level producer assigns;
        # walk top-level statements but never into defs/classes (those
        # flows are audited per-function)
        bound: List[Tuple[str, str, int]] = []

        def top_walk(n: ast.AST) -> None:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
                return
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                pk = _producer_kind(n.value, graph, mod_name, producers,
                                    sources)
                if pk is not None:
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            bound.append((t.id, pk[0], n.lineno))
            for child in ast.iter_child_nodes(n):
                top_walk(child)

        for stmt in mi.tree.body:
            top_walk(stmt)
        for gname, kind, line in bound:
            if sc.line_has(line, _ALLOW_HANDLE_ESCAPE):
                continue
            releases = _HANDLE_OWNERS.get(kind, frozenset({"close"}))
            released = False
            for node in graph.nodes.values():
                if node.module != mod_name or released:
                    continue
                for n in ast.walk(node.fn):
                    if isinstance(n, ast.Call) and \
                            isinstance(n.func, ast.Attribute) and \
                            isinstance(n.func.value, ast.Name) and \
                            n.func.value.id == gname and \
                            n.func.attr in releases:
                        released = True
                        break
            if not released:
                findings.append(Finding(
                    "handle-lifecycle", sc.path, line,
                    f"module-scope {kind} bound to global '{gname}' at "
                    f"import time, but no function in this module ever "
                    f"releases it ({'/'.join(sorted(releases))}) — the "
                    f"native handle lives until process exit with no "
                    f"shutdown path; add one (atexit or an explicit "
                    f"close hook) or mark a deliberate singleton with "
                    f"`# {_ALLOW_HANDLE_ESCAPE}`"))
    return findings


def _check_abi_pairing(scans: List[_FileScan]) -> List[Finding]:
    """The restype-registry half: every c_void_p-returning constructor
    symbol must have its destroy symbol declared in the same tree — a
    handle type nothing can free leaks by construction."""
    restypes: Dict[str, Tuple[str, int, str]] = {}
    declared: Set[str] = set()
    for sc in scans:
        declared.update(sc.native_decls)
        for name, (rname, line) in sc.native_restypes.items():
            restypes.setdefault(name, (rname, line, sc.path))
    findings: List[Finding] = []
    for name in sorted(restypes):
        rname, line, path = restypes[name]
        if rname != "c_void_p":
            continue
        if name in _ABI_NEW_PAIRS:
            expected = _ABI_NEW_PAIRS[name]
        elif name.endswith("_new"):
            expected = name[:-len("_new")] + "_destroy"
        else:
            continue
        if expected not in declared:
            findings.append(Finding(
                "handle-lifecycle", path, line,
                f"constructor symbol '{name}' returns an owning c_void_p "
                f"handle but its destroy symbol '{expected}' is not "
                f"declared anywhere in the scanned tree — handles of this "
                f"type cannot be freed"))
    return findings


def _flow_handles(sc: _FileScan, graph: CallGraph, node: FuncNode,
                  producers: Dict[str, str],
                  sources: Dict[str, Tuple[str, str]],
                  attr_stores: List[Tuple[str, str, str, str, int, str]],
                  findings: List[Finding], active: Set[str]) -> None:
    """Abstract interpretation of one function body: owning handles must
    reach a release on every normal-flow path, be returned, be stored on
    self (audited separately), or carry the escape pragma.

    Exception paths are modeled at explicit ``raise`` statements AND at
    every call whose resolved callee the may-throw fixpoint PROVES can
    raise (``exception-flow`` findings): a handle still live there leaks
    unless an enclosing ``finally``/``with`` releases it or an ``except``
    handler that (a) lexically encloses that site and (b) can catch the
    thrown type releases it — handler trust is scoped per ``try`` and
    per exception type, never context-insensitive.  Unresolved calls
    carry only the low-confidence ``external`` tag and never produce a
    finding.

    Handles appended to LOCAL containers become a tracked may-leak set
    (the container must be drained/returned/stored), rebinding a live
    handle's only name is a drop, and module-scope producers are audited
    separately (:func:`_audit_module_producers`)."""
    display = _node_display(node)

    def kind_of(call: ast.Call) -> Optional[Tuple[str, str]]:
        return _producer_kind(call, graph, node.module, producers,
                              sources)

    def allow(line: int) -> bool:
        return sc.line_has(line, _ALLOW_HANDLE_ESCAPE)

    def releases_of(kind: str) -> frozenset:
        return _HANDLE_OWNERS.get(kind, frozenset({"close"}))

    def report(line: int, msg: str, check: str = "handle-lifecycle"
               ) -> None:
        if check in active and not allow(line):
            findings.append(Finding(check, sc.path, line, msg))

    # producer calls consumed inline by a chained release
    # (`ch.call_async(...).join()`): collected up front, skipped later
    consumed: Set[int] = set()
    for n in ast.walk(node.fn):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and isinstance(n.func.value, ast.Call):
            pk = kind_of(n.func.value)
            if pk is not None and n.func.attr in releases_of(pk[0]):
                consumed.add(id(n.func.value))

    def release_name(state: Dict[str, _HBinding], name: str) -> None:
        b = state.get(name)
        if b is not None:
            b.released = True

    def fork_state(state: Dict[str, _HBinding]) -> Dict[str, _HBinding]:
        """A copy with CLONED bindings: releases observed inside it stay
        inside it.  Except-handler bodies run on forks — a handler's
        release covers only the exception edges of its own try (via the
        scope entries), never the fall-through path after the try."""
        out: Dict[str, _HBinding] = {}
        for name, b in state.items():
            nb = _HBinding(b.kind, b.line, b.origin,
                           None if b.members is None else set(b.members))
            nb.released = b.released
            out[name] = nb
        return out

    def handler_covers(name: str, raised: Optional[str],
                       scopes: Tuple[Tuple[Optional[frozenset],
                                           frozenset], ...]) -> bool:
        """Does some enclosing handler that can catch ``raised`` release
        ``name``?  Scoped trust: ``scopes`` holds only the handlers of
        the trys lexically enclosing the SITE being judged."""
        return any(graph.exception_catches(catch, raised) and name in rel
                   for catch, rel in scopes)

    # exception-flow reports at most one throwing site per binding — the
    # first unprotected one is the leak edge worth fixing
    throw_reported: Set[int] = set()

    def report_throw(state: Dict[str, _HBinding], call: ast.Call,
                     tgt: str, summ, fin_rel: Set[str],
                     scopes: Tuple) -> None:
        thrown = list(summ.types) + ([None] if summ.unknown else [])
        callee = graph.nodes.get(tgt)
        cdisp = _node_display(callee) if callee else tgt
        tdesc = "/".join(summ.types) if summ.types else "an exception"
        if summ.unknown and summ.types:
            tdesc += " (and unknown types)"
        for name, b in sorted(state.items()):
            if not b.live or name in fin_rel or allow(b.line):
                continue
            if all(handler_covers(name, t, scopes) for t in thrown):
                continue
            if id(b) in throw_reported:
                continue
            throw_reported.add(id(b))
            if b.members is not None:
                what = (f"container '{name}' holding owned "
                        f"{'/'.join(sorted(b.members))} handles "
                        f"(filled since line {b.line})")
            else:
                what = (f"{b.kind} '{name}' (created line {b.line}"
                        f"{b.origin})")
            report(call.lineno,
                   f"{display}: {what} is live across this call to "
                   f"{cdisp}, which can raise {tdesc} — on that "
                   f"unwinding edge the handle leaks; hold it in a "
                   f"`with`/try-finally or release it before the call",
                   check="exception-flow")

    def maybe_report_throw(call: ast.Call, state: Dict[str, _HBinding],
                           fin_rel: Set[str], scopes: Tuple) -> None:
        if "exception-flow" not in active:
            return
        tgt = graph.call_target(call)
        if tgt is None:
            return  # unresolved: external-only confidence, no finding
        summ = graph.throw_summary(tgt)
        if summ.may_throw:
            report_throw(state, call, tgt, summ, fin_rel, scopes)

    def scan_expr(n: ast.AST, state: Dict[str, _HBinding],
                  transfer: bool, fin_rel: Set[str] = frozenset(),
                  scopes: Tuple = ()) -> None:
        """Generic walk of an expression: classifies producer calls and
        owned-name stores that the statement dispatch didn't already
        claim.  `transfer` marks return-value context (everything the
        expression mentions goes to the caller).  ``fin_rel``/``scopes``
        carry the enclosing finally/handler coverage for judging
        throwing call sites."""
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            return  # nested scopes audit themselves
        if isinstance(n, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            # `[pc.join() for pc in pcs]`: draining a tracked container
            for gen in n.generators:
                if not (isinstance(gen.iter, ast.Name)
                        and isinstance(gen.target, ast.Name)):
                    continue
                cb = state.get(gen.iter.id)
                if cb is None or cb.members is None or not cb.members:
                    continue
                rel = set().union(*(releases_of(k) for k in cb.members))
                rel |= {"cancel"}
                for leaf in ast.walk(n.elt):
                    if isinstance(leaf, ast.Call) and \
                            isinstance(leaf.func, ast.Attribute) and \
                            isinstance(leaf.func.value, ast.Name) and \
                            leaf.func.value.id == gen.target.id and \
                            leaf.func.attr in rel:
                        cb.released = True
        if isinstance(n, ast.Call):
            f = n.func
            # x.close() / x.join() — release of an owned local
            if isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name):
                b = state.get(f.value.id)
                if b is not None and b.members is None and \
                        f.attr in releases_of(b.kind):
                    b.released = True
            # container.append(x) / registry.add(x): ownership moves
            # into a container.  A LOCAL container binding tracks the
            # obligation as a may-leak set; anything else (module
            # global, attr, parameter) is an escape the check cannot
            # follow.
            if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
                recv = state.get(f.value.id) \
                    if isinstance(f.value, ast.Name) else None
                if recv is not None and recv.members is not None and \
                        f.attr in {"append", "add", "appendleft",
                                   "insert"}:
                    deliberate = allow(n.lineno)
                    for arg in n.args:
                        if isinstance(arg, ast.Call) and \
                                id(arg) not in consumed:
                            pk2 = kind_of(arg)
                            if pk2 is not None and not deliberate:
                                recv.members.add(pk2[0])
                        for leaf in ast.walk(arg):
                            if isinstance(leaf, ast.Name) and \
                                    leaf.id in state and \
                                    state[leaf.id].live and \
                                    state[leaf.id].members is None:
                                if not deliberate:
                                    recv.members.add(state[leaf.id].kind)
                                state[leaf.id].released = True
                else:
                    for arg in n.args:
                        for leaf in ast.walk(arg):
                            if isinstance(leaf, ast.Name) and \
                                    leaf.id in state and \
                                    state[leaf.id].live and \
                                    state[leaf.id].members is None:
                                report(n.lineno,
                                       f"{display}: owned "
                                       f"{state[leaf.id].kind} "
                                       f"'{leaf.id}' escapes into a "
                                       f"container via .{f.attr}() — "
                                       f"the static check cannot see "
                                       f"its release; mark a "
                                       f"deliberate registry with "
                                       f"`# {_ALLOW_HANDLE_ESCAPE}`")
                                state[leaf.id].released = True
            # threading.Thread(target=..., args=(x,)): the handle's
            # lifetime now belongs to a thread this walk can't follow
            if _last_name(f) == "Thread":
                for arg in list(n.args) + [kw.value for kw in n.keywords]:
                    for leaf in ast.walk(arg):
                        if isinstance(leaf, ast.Name) and \
                                leaf.id in state and \
                                not state[leaf.id].released:
                            report(n.lineno,
                                   f"{display}: owned "
                                   f"{state[leaf.id].kind} '{leaf.id}' "
                                   f"escapes into a thread target — "
                                   f"release moves off every path this "
                                   f"check walks; mark deliberate "
                                   f"hand-off with "
                                   f"`# {_ALLOW_HANDLE_ESCAPE}`")
                            state[leaf.id].released = True
            pk = kind_of(n) if id(n) not in consumed else None
            if pk is not None:
                if transfer:
                    pass  # returned to the caller: its obligation now
                else:
                    # a fresh handle with no binding in a non-transfer
                    # context: argument passing transfers ownership to
                    # the callee (under-approximation); everything else
                    # is a drop, reported by the statement dispatch
                    pass
            # a PROVEN-throwing callee unwinds through here: every live
            # handle not covered by finally/with or a catching handler
            # leaks on that edge (releases above ran first, so a
            # release call never flags its own receiver)
            maybe_report_throw(n, state, fin_rel, scopes)
        if isinstance(n, ast.Name) and transfer:
            release_name(state, n.id)
        for child in ast.iter_child_nodes(n):
            scan_expr(child, state, transfer, fin_rel, scopes)

    def container_producers(value: ast.AST) -> List[ast.Call]:
        """Producer calls nested under a non-call expression (list/tuple/
        dict literals, comprehensions, conditionals)."""
        out = []
        for leaf in ast.walk(value):
            if isinstance(leaf, ast.Call) and id(leaf) not in consumed:
                if kind_of(leaf) is not None:
                    out.append(leaf)
        return out

    def finally_releases(finalbody: List[ast.AST]) -> Set[str]:
        """Names a finally block releases (context-insensitively: any
        `x.<release>()` or transfer anywhere inside it counts — finally
        runs on every exit, which is the whole point of the idiom)."""
        names: Set[str] = set()
        for stmt in finalbody:
            for n in ast.walk(stmt):
                if isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute) and \
                        isinstance(n.func.value, ast.Name) and \
                        n.func.attr in {m for rel in _HANDLE_OWNERS.values()
                                        for m in rel} | {"cancel"}:
                    names.add(n.func.value.id)
        return names

    def report_exit(state: Dict[str, _HBinding], line: int,
                    finally_rel: Set[str], where: str,
                    scopes: Tuple = (),
                    raised: Tuple = ()) -> None:
        """``raised`` is the tuple of thrown type names (None = unknown)
        when this exit is an exception edge; a handler scope covers a
        name only if it catches EVERY thrown type and releases the
        name.  Empty ``raised`` (return/fall-through) means handler
        coverage does not apply."""
        for name, b in sorted(state.items()):
            if not b.live or name in finally_rel:
                continue
            if allow(b.line):
                continue
            if raised and all(handler_covers(name, t, scopes)
                              for t in raised):
                continue
            if b.members is not None:
                report(line,
                       f"{display}: local container '{name}' still "
                       f"holds owned {'/'.join(sorted(b.members))} "
                       f"handle(s) (filled since line {b.line}) at this "
                       f"{where} — the may-leak set was never drained; "
                       f"release every element, return the container, "
                       f"or store it on an owner whose close drains it")
            else:
                report(line,
                       f"{display}: {b.kind} '{name}' (created line "
                       f"{b.line}{b.origin}) is still live at this "
                       f"{where} — this path leaks the native handle; "
                       f"release it "
                       f"({'/'.join(sorted(releases_of(b.kind)))}), "
                       f"return it, or store it on an owner whose close "
                       f"releases it")

    def exec_block(stmts: List[ast.AST], state: Dict[str, _HBinding],
                   finally_rel: Set[str], exc_scopes: Tuple
                   ) -> Tuple[Dict[str, _HBinding], bool]:
        """Returns (state after the block, terminated-by-return/raise).
        ``exc_scopes`` holds one ``(catch-set, released-names)`` entry
        per handler of every ``try`` lexically enclosing this block —
        coverage is judged per site and per thrown type, so a handler is
        trusted only for raises it both encloses and catches."""
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, ast.Return):
                if stmt.value is not None:
                    scan_expr(stmt.value, state, transfer=True,
                              fin_rel=finally_rel, scopes=exc_scopes)
                report_exit(state, stmt.lineno, finally_rel,
                            "early return" if stmt is not stmts[-1]
                            or stmt.value is None else "return")
                return state, True
            if isinstance(stmt, ast.Raise):
                # the exception path IS a function exit: anything still
                # live here leaks unless a finally or an enclosing
                # handler that CATCHES this raise releases it
                scan_expr(stmt, state, transfer=False,
                          fin_rel=finally_rel, scopes=exc_scopes)
                report_exit(state, stmt.lineno, finally_rel,
                            "raise (exception path)", scopes=exc_scopes,
                            raised=(graph.raised_type_name(stmt),))
                return state, True
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                _exec_assign(stmt, state, finally_rel, exc_scopes)
                continue
            if isinstance(stmt, ast.Expr):
                _exec_expr_stmt(stmt, state, finally_rel, exc_scopes)
                continue
            if isinstance(stmt, ast.If):
                scan_expr(stmt.test, state, transfer=False,
                          fin_rel=finally_rel, scopes=exc_scopes)
                s1, t1 = exec_block(list(stmt.body), dict(state),
                                    finally_rel, exc_scopes)
                s2, t2 = exec_block(list(stmt.orelse), dict(state),
                                    finally_rel, exc_scopes)
                if t1 and t2:
                    return state, True
                merged: Dict[str, _HBinding] = {}
                for s in ([s1] if not t1 else []) + \
                         ([s2] if not t2 else []):
                    for name, b in s.items():
                        if name not in merged or (merged[name].released
                                                  and not b.released):
                            merged[name] = b
                state = merged
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                scan_expr(getattr(stmt, "iter", None) or stmt.test,
                          state, transfer=False, fin_rel=finally_rel,
                          scopes=exc_scopes)
                # `for pc in pcs: pc.join()` — draining a tracked
                # container releases its may-leak set
                it = getattr(stmt, "iter", None)
                if isinstance(it, ast.Name) and \
                        isinstance(getattr(stmt, "target", None),
                                   ast.Name):
                    cb = state.get(it.id)
                    if cb is not None and cb.members:
                        rel = set().union(*(releases_of(k)
                                            for k in cb.members))
                        rel |= {"cancel"}
                        for bstmt in stmt.body:
                            for leaf in ast.walk(bstmt):
                                if isinstance(leaf, ast.Call) and \
                                        isinstance(leaf.func,
                                                   ast.Attribute) and \
                                        isinstance(leaf.func.value,
                                                   ast.Name) and \
                                        leaf.func.value.id == \
                                        stmt.target.id and \
                                        leaf.func.attr in rel:
                                    cb.released = True
                body_state, _t = exec_block(list(stmt.body), dict(state),
                                            finally_rel, exc_scopes)
                for name, b in body_state.items():
                    if name not in state:
                        state[name] = b
                exec_block(list(stmt.orelse), state, finally_rel,
                           exc_scopes)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                with_names: List[str] = []
                for item in stmt.items:
                    pk = kind_of(item.context_expr) \
                        if isinstance(item.context_expr, ast.Call) else None
                    if pk is not None and \
                            isinstance(item.optional_vars, ast.Name):
                        state[item.optional_vars.id] = _HBinding(
                            pk[0], stmt.lineno, pk[1])
                        with_names.append(item.optional_vars.id)
                    else:
                        # `with ch:` / `with closing(ch):` over an owned
                        # binding — __exit__ releases on every edge
                        for leaf in ast.walk(item.context_expr):
                            if isinstance(leaf, ast.Name) and \
                                    leaf.id in state:
                                with_names.append(leaf.id)
                        scan_expr(item.context_expr, state,
                                  transfer=False, fin_rel=finally_rel,
                                  scopes=exc_scopes)
                # inside the block the context manager guarantees
                # release on any unwind; after it, the handle is done
                state, t = exec_block(list(stmt.body), state,
                                      finally_rel | set(with_names),
                                      exc_scopes)
                for nm in with_names:
                    release_name(state, nm)
                if t:
                    return state, True
                continue
            if isinstance(stmt, ast.Try):
                fin_rel = finally_rel | finally_releases(
                    list(stmt.finalbody))
                # handler trust is SCOPED: each handler contributes a
                # (catch-set, released-names) entry that covers only
                # sites inside THIS try's body, and only for raises its
                # clause can actually catch
                scopes_for_body = exc_scopes
                if stmt.handlers:
                    scopes_for_body = exc_scopes + tuple(
                        (graph.handler_catch_names(h),
                         frozenset(finally_releases(list(h.body))))
                        for h in stmt.handlers)
                body_state, body_t = exec_block(list(stmt.body),
                                                dict(state), fin_rel,
                                                scopes_for_body)
                branch_states = [] if body_t else [body_state]
                if not body_t and stmt.orelse:
                    # else runs only after the body completed and is NOT
                    # covered by this try's handlers
                    body_state, t2 = exec_block(list(stmt.orelse),
                                                body_state, fin_rel,
                                                exc_scopes)
                    branch_states = [] if t2 else [body_state]
                for handler in stmt.handlers:
                    # forked bindings: a release inside the handler is
                    # trusted for this try's exception edges (the scope
                    # entry built above) but never for the code AFTER
                    # the try — the normal path never ran the handler
                    h_state, h_t = exec_block(list(handler.body),
                                              fork_state(state), fin_rel,
                                              exc_scopes)
                    if not h_t:
                        branch_states.append(h_state)
                merged = {}
                for s in branch_states:
                    for name, b in s.items():
                        if name not in merged or (merged[name].released
                                                  and not b.released):
                            merged[name] = b
                merged, fin_t = exec_block(list(stmt.finalbody), merged,
                                           finally_rel, exc_scopes)
                if not branch_states or fin_t:
                    return merged, True
                state = merged
                continue
            # anything else: scan its expressions generically
            for child in ast.iter_child_nodes(stmt):
                scan_expr(child, state, transfer=False,
                          fin_rel=finally_rel, scopes=exc_scopes)
        return state, False

    def _exec_assign(stmt, state: Dict[str, _HBinding],
                     fin_rel: Set[str], scopes: Tuple) -> None:
        targets = stmt.targets if isinstance(stmt, ast.Assign) \
            else [stmt.target]
        value = stmt.value
        if value is None:
            return
        pk = kind_of(value) if isinstance(value, ast.Call) and \
            id(value) not in consumed else None
        name_tgts = [t for t in targets if isinstance(t, ast.Name)]
        attr_tgts = [a for a in (_self_attr_of(t) for t in targets)
                     if a is not None]
        sub_local_tgts = [t for t in targets
                          if isinstance(t, ast.Subscript)
                          and _self_attr_of(t) is None]
        # rebinding a live handle's only name drops its obligation —
        # unless the value still mentions the name (`ch = ch or ...`)
        value_names = {leaf.id for leaf in ast.walk(value)
                       if isinstance(leaf, ast.Name)}
        for t in name_tgts:
            old = state.get(t.id)
            if old is not None and old.live and old.members is None and \
                    t.id not in value_names:
                report(stmt.lineno,
                       f"{display}: rebinding '{t.id}' discards the "
                       f"un-released {old.kind} created line {old.line}"
                       f"{old.origin} — the old handle leaks with no "
                       f"name left to release it; release it before "
                       f"rebinding")
                old.released = True
        if pk is not None:
            kind, origin = pk
            # the producer call itself can throw while other handles
            # are live (second-constructor leak)
            maybe_report_throw(value, state, fin_rel, scopes)
            if attr_tgts:
                for attr in attr_tgts:
                    if node.cls is not None:
                        attr_stores.append((node.module, node.cls, attr,
                                            kind, stmt.lineno, sc.path))
                if name_tgts:  # exe = self._cache[k] = producer(): both
                    for t in name_tgts:
                        state[t.id] = _HBinding(kind, stmt.lineno, origin)
                        state[t.id].released = True  # the attr owns it
                return
            if sub_local_tgts:
                report(stmt.lineno,
                       f"{display}: fresh {kind} stored straight into a "
                       f"container — its release is invisible to the "
                       f"static check; mark a deliberate registry with "
                       f"`# {_ALLOW_HANDLE_ESCAPE}`")
                return
            if name_tgts:
                for t in name_tgts:
                    state[t.id] = _HBinding(kind, stmt.lineno, origin)
                return
        # a fresh EMPTY local container: tracked so appended handles
        # become a may-leak set instead of an opaque escape
        if name_tgts and not attr_tgts and not sub_local_tgts and (
                (isinstance(value, (ast.List, ast.Set))
                 and not value.elts)
                or (isinstance(value, ast.Dict) and not value.keys)
                or (isinstance(value, ast.Call)
                    and isinstance(value.func, ast.Name)
                    and value.func.id in {"list", "set", "deque"}
                    and not value.args and not value.keywords)):
            for t in name_tgts:
                state[t.id] = _HBinding("container", stmt.lineno,
                                        members=set())
            return
        # owned name moved onto self.<attr> / into a container
        if isinstance(value, ast.Name) and value.id in state:
            b = state[value.id]
            if attr_tgts and not b.released:
                kinds = sorted(b.members) if b.members is not None \
                    else [b.kind]
                for attr in attr_tgts:
                    if node.cls is not None:
                        for k in kinds:
                            attr_stores.append((node.module, node.cls,
                                                attr, k, stmt.lineno,
                                                sc.path))
                b.released = True
                return
            if sub_local_tgts and b.live and b.members is None:
                report(stmt.lineno,
                       f"{display}: owned {b.kind} '{value.id}' escapes "
                       f"into a container — mark a deliberate registry "
                       f"with `# {_ALLOW_HANDLE_ESCAPE}`")
                b.released = True
                return
        # producers nested deeper (container literals, comprehensions,
        # conditionals) assigned somewhere
        nested = container_producers(value)
        if nested:
            if attr_tgts:
                for call in nested:
                    k = kind_of(call)[0]
                    for attr in attr_tgts:
                        if node.cls is not None:
                            attr_stores.append((node.module, node.cls,
                                                attr, k, stmt.lineno,
                                                sc.path))
            else:
                for call in nested:
                    k = kind_of(call)[0]
                    report(call.lineno,
                           f"{display}: fresh {k} constructed inside a "
                           f"local container/expression — no name owns "
                           f"it, so no release path exists; bind it "
                           f"first or mark a deliberate registry with "
                           f"`# {_ALLOW_HANDLE_ESCAPE}`")
        scan_expr(value, state, transfer=False, fin_rel=fin_rel,
                  scopes=scopes)

    def _exec_expr_stmt(stmt: ast.Expr, state: Dict[str, _HBinding],
                        fin_rel: Set[str], scopes: Tuple) -> None:
        value = stmt.value
        if isinstance(value, ast.Call) and id(value) not in consumed:
            pk = kind_of(value)
            if pk is not None:
                kind, origin = pk
                report(stmt.lineno,
                       f"{display}: result of this call is a fresh "
                       f"{kind}{origin} and is DROPPED — the native "
                       f"handle leaks immediately; bind it and release "
                       f"it ({'/'.join(sorted(releases_of(kind)))})")
                return
        scan_expr(value, state, transfer=False, fin_rel=fin_rel,
                  scopes=scopes)

    end_state, terminated = exec_block(list(node.fn.body), {}, set(), ())
    if not terminated:
        last = node.fn.body[-1]
        report_exit(end_state, getattr(last, "lineno", node.fn.lineno),
                    set(), "fall-through function exit")


def _audit_attr_stores(
        attr_stores: List[Tuple[str, str, str, str, int, str]],
        graph: CallGraph,
        sc_by_path: Dict[str, _FileScan]) -> List[Finding]:
    """Ownership-transfer audit: a handle stored on ``self.<attr>`` is
    properly owned only if its class has a release-ish method whose body
    touches that attr (``close`` iterating ``self.channels``, etc.)."""
    findings: List[Finding] = []
    seen: Set[Tuple[str, str, str]] = set()
    for module, cls, attr, kind, line, path in attr_stores:
        key = (module, cls, attr)
        if key in seen:
            continue
        seen.add(key)
        mi = graph.modules.get(module)
        ci = mi.classes.get(cls) if mi is not None else None
        if ci is None:
            continue
        released = False
        for meth_name, node_id in ci.methods.items():
            if meth_name not in _RELEASEISH_METHODS:
                continue
            meth = graph.nodes.get(node_id)
            if meth is None:
                continue
            for n in ast.walk(meth.fn):
                if isinstance(n, ast.Attribute) and n.attr == attr and \
                        isinstance(n.value, ast.Name) and \
                        n.value.id == "self":
                    released = True
                    break
            if released:
                break
        if released:
            continue
        sc = sc_by_path.get(path)
        if sc is not None and sc.line_has(line, _ALLOW_HANDLE_ESCAPE):
            continue
        findings.append(Finding(
            "handle-lifecycle", path, line,
            f"owning {kind} stored on {cls}.{attr}, but {cls} has no "
            f"close/stop/shutdown-style method touching self.{attr} — "
            f"ownership was transferred to an object that never releases "
            f"it"))
    return findings


# ---------------------------------------------------------------------------
# check: wire-contract (frame-schema symmetry + parse-path bounds)
# ---------------------------------------------------------------------------

_PACK_DIRS = {"pack", "pack_into"}
_UNPACK_DIRS = {"unpack", "unpack_from"}
#: sanctioned bounds-validation calls: a count/length passed to one of
#: these (or to any *check*-named helper) counts as validated
_WIRE_VALIDATORS = {"need", "check_count", "check_span", "read"}
#: call names whose arguments are SIZE positions (an unvalidated wire
#: count reaching one of these drives an allocation or a loop)
_SIZE_SINKS = {"frombuffer", "range", "bytearray", "zeros", "empty",
               "ones", "full"}


def _flatten_fmt(fmt: str) -> str:
    """'<qqi' -> 'qqi': strip byte-order marks and repeat digits — the
    drift comparison cares about field order and width, not grouping."""
    return "".join(ch for ch in fmt if ch.isalpha())


def _struct_consts_of(sc: _FileScan) -> Dict[str, str]:
    """Module-level ``NAME = struct.Struct("<fmt")`` constants — their
    ``.pack_into``/``.unpack_from`` uses carry the constant's format."""
    out: Dict[str, str] = {}
    for stmt in sc.tree.body:
        if not isinstance(stmt, ast.Assign) or \
                not isinstance(stmt.value, ast.Call):
            continue
        call = stmt.value
        if _last_name(call.func) == "Struct" and call.args and \
                isinstance(call.args[0], ast.Constant) and \
                isinstance(call.args[0].value, str):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = call.args[0].value
    return out


def _call_wire_direction(call: ast.Call,
                         struct_consts: Dict[str, str]
                         ) -> Optional[Tuple[str, Optional[str], bool]]:
    """``(direction, fmt, explicit)`` for a struct-format call site:
    ``struct.pack/pack_into/unpack/unpack_from``, a struct-Struct
    constant's method, or ``wire.read`` (unpack direction).  ``fmt`` is
    None for non-constant formats; ``explicit`` is False for Struct
    constants (their endianness is checked at the constant)."""
    f = call.func
    if not isinstance(f, ast.Attribute):
        return None
    root = _root_name(f)
    if f.attr in _PACK_DIRS | _UNPACK_DIRS and root == "struct":
        direction = "pack" if f.attr in _PACK_DIRS else "unpack"
        fmt = None
        if call.args and isinstance(call.args[0], ast.Constant) and \
                isinstance(call.args[0].value, str):
            fmt = call.args[0].value
        return direction, fmt, True
    if f.attr == "read" and root == "wire":
        fmt = None
        if call.args and isinstance(call.args[0], ast.Constant) and \
                isinstance(call.args[0].value, str):
            fmt = call.args[0].value
        return "unpack", fmt, True
    if f.attr in _PACK_DIRS | _UNPACK_DIRS and \
            isinstance(f.value, ast.Name) and \
            f.value.id in struct_consts:
        direction = "pack" if f.attr in _PACK_DIRS else "unpack"
        return direction, struct_consts[f.value.id], False
    return None


def _fmt_stream(fn: ast.AST, struct_consts: Dict[str, str],
                direction: str) -> str:
    """The ordered, flattened struct-format characters ``fn`` moves in
    ``direction`` — what gets matched against a schema's scalar
    sequence."""
    events: List[Tuple[int, int, str]] = []
    seq = 0
    for n in ast.walk(fn):
        if not isinstance(n, ast.Call):
            continue
        hit = _call_wire_direction(n, struct_consts)
        if hit is None or hit[0] != direction or hit[1] is None:
            continue
        seq += 1
        events.append((n.lineno, seq, _flatten_fmt(hit[1])))
    events.sort()
    return "".join(e[2] for e in events)


def _is_subsequence(needle: str, hay: str) -> bool:
    it = iter(hay)
    return all(ch in it for ch in needle)


def _segment_streams(fn: ast.AST, struct_consts: Dict[str, str],
                     direction: str, key: str) -> Optional[str]:
    """The ``direction`` format stream of the dispatch branch keyed on
    string constant ``key`` — the bodies of every ``if <x> == "key"``
    (or reversed) inside ``fn``, concatenated in line order.  ``None``
    when no such branch exists (a stale segment declaration)."""
    streams: List[Tuple[int, str]] = []
    for n in ast.walk(fn):
        if not isinstance(n, ast.If):
            continue
        test = n.test
        if not (isinstance(test, ast.Compare) and len(test.ops) == 1
                and isinstance(test.ops[0], ast.Eq)):
            continue
        operands = [test.left] + list(test.comparators)
        if not any(isinstance(c, ast.Constant) and c.value == key
                   for c in operands):
            continue
        body = ast.Module(body=n.body, type_ignores=[])
        streams.append((n.lineno,
                        _fmt_stream(body, struct_consts, direction)))
    if not streams:
        return None
    streams.sort()
    return "".join(s for _ln, s in streams)


def _prebranch_stream(fn: ast.AST, struct_consts: Dict[str, str],
                      direction: str) -> str:
    """The ``direction`` format stream OUTSIDE every string-keyed
    dispatch branch of ``fn`` — the shared header a multi-frame handler
    moves before branching on the discriminant.  Matched against a
    schema's ``prebranch`` declaration."""
    excluded: Set[int] = set()
    for n in ast.walk(fn):
        if not isinstance(n, ast.If):
            continue
        test = n.test
        if not (isinstance(test, ast.Compare) and len(test.ops) == 1
                and isinstance(test.ops[0], ast.Eq)):
            continue
        operands = [test.left] + list(test.comparators)
        if not any(isinstance(c, ast.Constant)
                   and isinstance(c.value, str) for c in operands):
            continue
        for stmt in n.body:
            for sub in ast.walk(stmt):
                excluded.add(id(sub))
    events: List[Tuple[int, int, str]] = []
    seq = 0
    for n in ast.walk(fn):
        if not isinstance(n, ast.Call) or id(n) in excluded:
            continue
        hit = _call_wire_direction(n, struct_consts)
        if hit is None or hit[0] != direction or hit[1] is None:
            continue
        seq += 1
        events.append((n.lineno, seq, _flatten_fmt(hit[1])))
    events.sort()
    return "".join(e[2] for e in events)


def _wire_site_index(scans: List[_FileScan], graph: CallGraph
                     ) -> Dict[str, FuncNode]:
    """``"<module-basename>.<Class>.<fn>"`` / ``"<module-basename>.<fn>"``
    -> FuncNode, the resolution table for schema site qualnames."""
    out: Dict[str, FuncNode] = {}
    for node in graph.nodes.values():
        if not isinstance(node.fn, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
            continue
        base = node.module.split(".")[-1]
        out[f"{base}.{_node_display(node)}"] = node
    return out


def _norm_frame_stem(name: str) -> str:
    """'_pack_apply_id_req' / '_unpack_apply_id' -> 'apply_id': the
    name-pairing key for hand-rolled framing functions."""
    for prefix in ("_pack_", "_unpack_"):
        if name.startswith(prefix):
            name = name[len(prefix):]
            break
    for suffix in ("_req", "_rsp"):
        if name.endswith(suffix):
            name = name[:-len(suffix)]
    return name


def _load_wire_registry():
    """The schema registry + fuzz coverage table, imported lazily so the
    linter stays usable on trees that aren't this package."""
    try:
        from brpc_tpu import wire as wire_mod
    except Exception:  # pragma: no cover - package not importable
        return None, None
    covers = None
    try:
        from brpc_tpu.analysis import fuzz as fuzz_mod
        covers = fuzz_mod.coverage_map()
    except Exception:
        covers = None
    return wire_mod, covers


def _check_wire_contract(scans: List[_FileScan],
                         graph: CallGraph) -> List[Finding]:
    findings: List[Finding] = []
    sc_by_path = {sc.path: sc for sc in scans}
    consts_by_path = {sc.path: _struct_consts_of(sc) for sc in scans}
    site_index = _wire_site_index(scans, graph)
    scanned_modules = {mi.name.split(".")[-1]
                       for mi in graph.modules.values()}
    wire_mod, covers = _load_wire_registry()
    # the registry half only applies when the scan actually contains the
    # real package (a tmp-dir fixture scan must not fail stale-site
    # checks for modules it never included)
    in_package_scan = any(
        _stable_path(sc.path).startswith("brpc_tpu/") for sc in scans)

    # -- endianness: every constant struct format must be explicit
    # little-endian (this fabric's wire order); a bare "qqq" silently
    # follows host order AND host padding
    for sc in scans:
        consts = consts_by_path[sc.path]
        for stmt in sc.tree.body:
            if isinstance(stmt, ast.Assign) and \
                    isinstance(stmt.value, ast.Call) and \
                    _last_name(stmt.value.func) == "Struct" and \
                    stmt.value.args and \
                    isinstance(stmt.value.args[0], ast.Constant) and \
                    isinstance(stmt.value.args[0].value, str) and \
                    not stmt.value.args[0].value.startswith("<"):
                findings.append(Finding(
                    "wire-contract", sc.path, stmt.lineno,
                    f"struct.Struct format "
                    f"'{stmt.value.args[0].value}' is not explicit "
                    f"little-endian — native byte order AND padding "
                    f"silently differ across hosts; prefix it with '<'"))
        for n in ast.walk(sc.tree):
            if not isinstance(n, ast.Call):
                continue
            hit = _call_wire_direction(n, consts)
            if hit is None or hit[1] is None or not hit[2]:
                continue
            if not hit[1].startswith("<"):
                findings.append(Finding(
                    "wire-contract", sc.path, n.lineno,
                    f"struct format '{hit[1]}' is not explicit "
                    f"little-endian — native byte order AND padding "
                    f"silently differ across hosts; prefix it with '<'"))

    # -- hand-rolled framing functions: collect and name-pair
    frame_fns: Dict[str, FuncNode] = {}   # site key -> node
    for key, node in site_index.items():
        if node.name.startswith(("_pack_", "_unpack_")):
            consts = consts_by_path.get(node.path, {})
            if _fmt_stream(node.fn, consts, "pack") or \
                    _fmt_stream(node.fn, consts, "unpack"):
                frame_fns[key] = node

    registry_claimed: Set[str] = set()
    schemas = dict(wire_mod.REGISTRY) if wire_mod is not None else {}
    for sch in schemas.values():
        registry_claimed.update(sch.pack_sites)
        registry_claimed.update(sch.unpack_sites)

    by_stem: Dict[Tuple[str, str], Dict[str, FuncNode]] = {}
    for key, node in frame_fns.items():
        mod = key.split(".")[0]
        stem = _norm_frame_stem(node.name)
        side = "pack" if node.name.startswith("_pack_") else "unpack"
        by_stem.setdefault((mod, stem), {})[side] = node
    for (mod, stem), sides in sorted(by_stem.items()):
        pack_node = sides.get("pack")
        unpack_node = sides.get("unpack")
        if pack_node is not None and unpack_node is not None:
            p_stream = _fmt_stream(pack_node.fn,
                                   consts_by_path[pack_node.path],
                                   "pack")
            u_stream = _fmt_stream(unpack_node.fn,
                                   consts_by_path[unpack_node.path],
                                   "unpack")
            if p_stream != u_stream:
                findings.append(Finding(
                    "wire-contract", unpack_node.path,
                    unpack_node.fn.lineno,
                    f"pack/unpack drift for frame '{stem}': "
                    f"{pack_node.name} writes field stream "
                    f"'{p_stream}' but {unpack_node.name} reads "
                    f"'{u_stream}' — the two sides disagree on field "
                    f"order or width"))
            continue
        lone = pack_node or unpack_node
        key = f"{mod}.{_node_display(lone)}"
        if key in registry_claimed:
            continue  # one-sided by declared design (native consumer,
            #           response frame) — the registry is the explanation
        findings.append(Finding(
            "wire-contract", lone.path, lone.fn.lineno,
            f"unpaired framing function {lone.name}: no "
            f"{'_unpack_' if pack_node else '_pack_'}{stem}* "
            f"counterpart in the scanned tree and no wire.REGISTRY "
            f"schema claims it — undeclared one-sided framings drift "
            f"silently; declare it in brpc_tpu/wire.py"))

    # -- registry conformance: every declared site exists and its format
    # stream matches the schema
    if wire_mod is not None and in_package_scan:
        for sch in sorted(schemas.values(), key=lambda s: s.name):
            expected = "".join(
                _flatten_fmt(f) for f in sch.scalar_formats())
            for direction, sites in (("pack", sch.pack_sites),
                                     ("unpack", sch.unpack_sites)):
                for site in sites:
                    node = site_index.get(site)
                    if node is None:
                        if site.split(".")[0] in scanned_modules:
                            findings.append(Finding(
                                "wire-contract",
                                "brpc_tpu/wire.py", 1,
                                f"schema '{sch.name}' names "
                                f"{direction} site '{site}' which does "
                                f"not exist in the scanned tree — the "
                                f"registry is stale"))
                        continue
                    consts = consts_by_path.get(node.path, {})
                    stream = _fmt_stream(node.fn, consts, direction)
                    seg_keys = dict(sch.segments).get(site)
                    if site in sch.exact_sites:
                        if stream != expected:
                            findings.append(Finding(
                                "wire-contract", node.path,
                                node.fn.lineno,
                                f"schema '{sch.name}' {direction} site "
                                f"{site} has field stream '{stream}', "
                                f"schema declares '{expected}' — the "
                                f"hand-rolled site drifted from the "
                                f"declared frame"))
                    elif seg_keys is not None:
                        # shared multi-frame handler with a declared
                        # dispatch discriminant: the keyed branch must
                        # carry this schema EXACTLY — subsequence can
                        # hide a reordered or restretched frame behind
                        # a sibling branch's fields.  A declared
                        # pre-branch header (shared reads outside the
                        # dispatch) prepends to the branch stream and
                        # is itself held to the actual shared reads.
                        head = dict(sch.prebranch).get(site, "")
                        if head:
                            pre = _prebranch_stream(node.fn, consts,
                                                    direction)
                            if pre != head:
                                findings.append(Finding(
                                    "wire-contract", node.path,
                                    node.fn.lineno,
                                    f"schema '{sch.name}' declares "
                                    f"pre-branch stream '{head}' for "
                                    f"{direction} site {site} but the "
                                    f"shared reads outside its "
                                    f"dispatch branches move '{pre}' "
                                    f"— the pre-branch declaration is "
                                    f"stale"))
                        for key in seg_keys:
                            seg = _segment_streams(node.fn, consts,
                                                   direction, key)
                            if seg is None:
                                findings.append(Finding(
                                    "wire-contract", node.path,
                                    node.fn.lineno,
                                    f"schema '{sch.name}' declares "
                                    f"segment '{key}' of {direction} "
                                    f"site {site} but the site has no "
                                    f"branch dispatching on '{key}' — "
                                    f"the segment declaration is "
                                    f"stale"))
                            elif head + seg != expected:
                                got = (f"'{head + seg}' (pre-branch "
                                       f"'{head}' ++ branch '{seg}')"
                                       if head else f"'{seg}'")
                                findings.append(Finding(
                                    "wire-contract", node.path,
                                    node.fn.lineno,
                                    f"schema '{sch.name}' segment "
                                    f"'{key}' of {direction} site "
                                    f"{site} has field stream {got}, "
                                    f"schema declares '{expected}' — "
                                    f"exact segmented match failed for "
                                    f"the dispatch branch"))
                    elif expected and not _is_subsequence(expected,
                                                          stream):
                        findings.append(Finding(
                            "wire-contract", node.path, node.fn.lineno,
                            f"schema '{sch.name}' {direction} site "
                            f"{site}: declared field sequence "
                            f"'{expected}' does not appear in the "
                            f"site's {direction} stream '{stream}' — "
                            f"the site drifted from the declared "
                            f"frame"))
            seg_sites = {s for s, _keys in sch.segments}
            for psite, _stream in sch.prebranch:
                if psite not in seg_sites:
                    findings.append(Finding(
                        "wire-contract", "brpc_tpu/wire.py", 1,
                        f"schema '{sch.name}' declares a pre-branch "
                        f"stream for site '{psite}' with no segments "
                        f"entry for that site — an unanchored "
                        f"pre-branch declaration checks nothing; add "
                        f"the segment key or drop it"))
            if not sch.pack_sites and not sch.response:
                findings.append(Finding(
                    "wire-contract", "brpc_tpu/wire.py", 1,
                    f"schema '{sch.name}' declares no pack site — an "
                    f"unproduced frame, or an undeclared producer"))
            if not sch.unpack_sites and not sch.native_sites and \
                    not sch.response:
                findings.append(Finding(
                    "wire-contract", "brpc_tpu/wire.py", 1,
                    f"schema '{sch.name}' declares no unpack site and "
                    f"no native consumer — an unparsed frame, or an "
                    f"undeclared parser"))
        # text parsers must exist...
        for qual in wire_mod.TEXT_PARSERS:
            if qual not in site_index and \
                    qual.split(".")[0] in scanned_modules:
                findings.append(Finding(
                    "wire-contract", "brpc_tpu/wire.py", 1,
                    f"TEXT_PARSERS names '{qual}' which does not exist "
                    f"in the scanned tree — the registry is stale"))
        # ...and every declared parser must have a fuzz target (the
        # "fuzzers for every parser" gate, SURVEY §4)
        if covers is not None:
            covered = {c for cs in covers.values() for c in cs}
            for sch in sorted(schemas.values(), key=lambda s: s.name):
                if sch.name not in covered:
                    findings.append(Finding(
                        "wire-contract", "brpc_tpu/wire.py", 1,
                        f"schema '{sch.name}' has no fuzz target in "
                        f"brpc_tpu.analysis.fuzz — every declared "
                        f"framing must be fuzzed"))
            for qual in wire_mod.TEXT_PARSERS:
                if qual not in covered:
                    findings.append(Finding(
                        "wire-contract", "brpc_tpu/wire.py", 1,
                        f"text parser '{qual}' has no fuzz target in "
                        f"brpc_tpu.analysis.fuzz — every parser must "
                        f"be fuzzed"))

    # -- unvalidated counts on parse paths
    scope: Dict[str, FuncNode] = {}
    for key, node in frame_fns.items():
        if node.name.startswith("_unpack_"):
            scope[node.node_id] = node
    if wire_mod is not None:
        for sch in schemas.values():
            for site in sch.unpack_sites:
                node = site_index.get(site)
                if node is not None:
                    scope[node.node_id] = node
    mi_by_path = {mi.path: mi for mi in graph.modules.values()}
    reach_roots: List[str] = []
    for sc in scans:
        mi = mi_by_path.get(sc.path)
        top = graph.nodes.get(f"{mi.name}:<module>") if mi else None
        reach_roots.extend(_find_handler_roots(
            sc, graph, top,
            register_names=("add_service", "add_async_service",
                            "add_ps_service", "add_stream_handler")))
    seen: Set[str] = set()
    queue = list(reach_roots)
    while queue:
        node_id = queue.pop()
        if node_id in seen:
            continue
        seen.add(node_id)
        node = graph.nodes.get(node_id)
        if node is None or node.path not in sc_by_path:
            continue
        scope.setdefault(node_id, node)
        for n in ast.walk(node.fn):
            if isinstance(n, ast.Call):
                tgt = graph.call_target(n)
                if tgt is not None:
                    queue.append(tgt)
    for node in sorted(scope.values(), key=lambda n: (n.path,
                                                      n.fn.lineno)):
        sc = sc_by_path.get(node.path)
        if sc is None:
            continue
        _scan_count_validation(sc, node,
                               consts_by_path.get(node.path, {}),
                               findings)
    return findings


def _scan_count_validation(sc: _FileScan, node: FuncNode,
                           struct_consts: Dict[str, str],
                           findings: List[Finding]) -> None:
    """Flag integer fields read off the wire that drive a SIZE (an
    allocation, a loop bound, a slice) without ever reaching a bounds
    check — the unvalidated-count hazard class (`_unpack_windows`'s
    pre-hardening loop, numpy's count=-1 re-interpretation)."""
    fn = node.fn
    display = _node_display(node)
    unpacked: Dict[str, int] = {}
    for n in ast.walk(fn):
        if not isinstance(n, ast.Assign) or \
                not isinstance(n.value, ast.Call):
            continue
        hit = _call_wire_direction(n.value, struct_consts)
        if hit is None or hit[0] != "unpack":
            continue
        for tgt in n.targets:
            leaves = [tgt] if isinstance(tgt, ast.Name) else [
                leaf for leaf in ast.walk(tgt)
                if isinstance(leaf, ast.Name)
            ] if isinstance(tgt, (ast.Tuple, ast.List, ast.Starred)) \
                else []
            for leaf in leaves:
                unpacked.setdefault(leaf.id, n.lineno)
    if not unpacked:
        return
    size_used: Dict[str, int] = {}
    validated: Set[str] = set()

    def mark_size(exprs, line: int) -> None:
        for e in exprs:
            if e is None:
                continue
            for leaf in ast.walk(e):
                if isinstance(leaf, ast.Name) and leaf.id in unpacked:
                    size_used.setdefault(leaf.id, line)

    for n in ast.walk(fn):
        if isinstance(n, ast.Call):
            fl = _last_name(n.func)
            args = list(n.args) + [kw.value for kw in n.keywords]
            if fl in _WIRE_VALIDATORS or (fl is not None
                                          and "check" in fl.lower()):
                for a in args:
                    for leaf in ast.walk(a):
                        if isinstance(leaf, ast.Name):
                            validated.add(leaf.id)
            elif fl == "frombuffer":
                mark_size(args[1:], n.lineno)
            elif fl in _SIZE_SINKS:
                mark_size(args, n.lineno)
        elif isinstance(n, ast.Subscript) and \
                isinstance(n.slice, ast.Slice):
            mark_size([n.slice.lower, n.slice.upper, n.slice.step],
                      n.lineno)
        elif isinstance(n, ast.Compare):
            for leaf in ast.walk(n):
                if isinstance(leaf, ast.Name):
                    validated.add(leaf.id)
    for name in sorted(size_used):
        if name in validated:
            continue
        findings.append(Finding(
            "wire-contract", sc.path, size_used[name],
            f"{display}: '{name}' is read off the wire (line "
            f"{unpacked[name]}) and used as a size/loop bound with no "
            f"bounds validation on any path — a hostile count drives "
            f"unbounded allocation or numpy's count=-1 whole-buffer "
            f"re-interpretation; guard it with wire.check_count / "
            f"wire.need"))


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def _iter_py_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git", "build")]
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
    return out


def lint_files(files: Iterable[str],
               checks: Optional[Sequence[str]] = None) -> List[Finding]:
    active = set(checks or ALL_CHECKS)
    unknown = active - set(ALL_CHECKS)
    if unknown:
        raise ValueError(
            f"unknown checks: {sorted(unknown)}; "
            f"valid checks: {', '.join(ALL_CHECKS)}")
    scans: List[_FileScan] = []
    findings: List[Finding] = []
    for path in files:
        try:
            with open(path, "r", encoding="utf-8") as f:
                src = f.read()
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            findings.append(Finding(
                "syntax", path, e.lineno or 0, f"does not parse: {e.msg}"))
            continue
        scans.append(_FileScan(path, tree, src.splitlines()))
    graph: Optional[CallGraph] = None
    if active & _GRAPH_CHECKS:
        graph = build_callgraph((sc.path, sc.tree) for sc in scans)
    for sc in scans:
        if "obs-guard" in active:
            findings.extend(_check_obs_guard(sc))
    if graph is not None:
        if "fiber-shared-state" in active:
            findings.extend(_check_fiber_shared_state(scans, graph))
        if "trace-purity" in active:
            findings.extend(_check_trace_purity(scans, graph))
        if "lock-order" in active:
            findings.extend(_check_lock_order(scans, graph))
        if "fiber-blocking-sleep" in active:
            findings.extend(_check_fiber_blocking_sleep(scans, graph))
        if active & {"handle-lifecycle", "exception-flow"}:
            findings.extend(_check_handle_lifecycle(scans, graph,
                                                    active))
        if "lock-exception-safety" in active:
            findings.extend(_check_lock_exception_safety(scans, graph))
        if "wire-contract" in active:
            findings.extend(_check_wire_contract(scans, graph))
    if "ctypes-contract" in active:
        findings.extend(_check_ctypes_contract(scans))
    if active & set(_NATIVE_CHECKS):
        # the cross-language tier lives in its own module (its own
        # parsing stack); import lazily so Python-only lint runs don't
        # pay for it
        from brpc_tpu.analysis import native as _native
        findings.extend(_native.check_scans(
            [sc.path for sc in scans], active & set(_NATIVE_CHECKS)))
    # dedup (a nested def can be reached both inside its parent's subtree
    # and as its own call-graph node), then stable order
    seen: Set[Tuple[str, str, int, str]] = set()
    unique: List[Finding] = []
    for f in findings:
        key = (f.check, f.path, f.line, f.message)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    unique.sort(key=lambda f: (f.path, f.line, f.check))
    return unique


def run_lint(paths: Sequence[str],
             checks: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint every ``.py`` file under ``paths`` (files or directories)."""
    return lint_files(_iter_py_files(paths), checks)


def load_baseline(path: str) -> Set[str]:
    """Accepted finding ids from a baseline file: either the
    ``--format=json`` / ``--write-baseline`` output or a plain list of
    ids."""
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    items: Iterable = ()
    if isinstance(data, dict):
        items = data.get("ids") or data.get("findings") or ()
    elif isinstance(data, list):
        items = data
    ids: Set[str] = set()
    for item in items:
        if isinstance(item, str):
            ids.add(item)
        elif isinstance(item, dict) and "id" in item:
            ids.add(str(item["id"]))
    return ids


def apply_baseline(findings: Sequence[Finding], baseline_ids: Set[str]
                   ) -> Tuple[List[Finding], List[Finding]]:
    """Split findings into (new, suppressed-by-baseline)."""
    new = [f for f in findings if f.id not in baseline_ids]
    old = [f for f in findings if f.id in baseline_ids]
    return new, old


def _default_target() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m brpc_tpu.analysis",
        description="Framework-invariant linter for the brpc_tpu fabric")
    parser.add_argument("paths", nargs="*",
                        help="files/directories to lint "
                             "(default: the brpc_tpu package)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--check", action="append", metavar="NAME",
                        help=f"run only the named check(s); "
                             f"known: {', '.join(ALL_CHECKS)}")
    parser.add_argument("--baseline", metavar="FILE",
                        help="suppress findings whose stable id appears in "
                             "FILE (json: --write-baseline output, "
                             "--format=json output, or a list of ids)")
    parser.add_argument("--write-baseline", metavar="FILE",
                        help="write the current findings as an accepted "
                             "baseline and exit 0")
    args = parser.parse_args(argv)
    try:
        findings = run_lint(args.paths or [_default_target()], args.check)
    except ValueError as e:
        parser.error(str(e))  # exit 2, lists the valid check set
    if args.write_baseline:
        with open(args.write_baseline, "w", encoding="utf-8") as f:
            json.dump({"ids": sorted({x.id for x in findings}),
                       "findings": [x.to_dict() for x in findings]},
                      f, indent=2)
            f.write("\n")
        print(f"baseline: {len(findings)} finding(s) -> "
              f"{args.write_baseline}", file=sys.stderr)
        return 0
    suppressed: List[Finding] = []
    if args.baseline:
        try:
            baseline_ids = load_baseline(args.baseline)
        except (OSError, json.JSONDecodeError) as e:
            parser.error(f"cannot read baseline {args.baseline}: {e}")
        findings, suppressed = apply_baseline(findings, baseline_ids)
    if args.format == "json":
        payload = {
            "count": len(findings),
            "checks": list(args.check or ALL_CHECKS),
            "findings": [f.to_dict() for f in findings],
        }
        if args.baseline:
            payload["suppressed_count"] = len(suppressed)
        print(json.dumps(payload, indent=2))
    else:
        for f in findings:
            print(f.format())
        tail = f", {len(suppressed)} suppressed by baseline" \
            if suppressed else ""
        print((f"{len(findings)} finding(s){tail}" if findings
               else f"clean: no findings{tail}"), file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
