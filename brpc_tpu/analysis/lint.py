"""Framework-invariant AST linter for the Python tier.

The reference enforces its concurrency contracts with purpose-built
tooling (contention profiler, bthread diagnostics, builtin hazard pages);
this is the equivalent static pass for the hazards our fabric creates.
Four checks, each encoding an invariant the runtime cannot enforce:

- ``ctypes-contract`` — every ``*.brt_*`` symbol used anywhere must have
  BOTH ``argtypes`` and ``restype`` declared somewhere in the scanned
  tree (``rpc._load()`` is the canonical site).  ctypes defaults an
  undeclared restype to c_int, which silently truncates 64-bit handles
  on the way out of the native core.  Also: a ``CFUNCTYPE`` callback
  passed inline to a ``brt_*`` call is owned by nobody — the native core
  keeps the raw function pointer while Python GCs the closure.
- ``fiber-shared-state`` — methods reachable from a handler registered
  via ``add_service``/``add_async_service`` run concurrently on fiber
  workers (the trampoline releases the GIL across ctypes); any mutation
  of ``self``/module state they perform must sit inside a
  ``with self._mu``-style block.
- ``obs-guard`` — instrumentation outside ``brpc_tpu/obs`` must go
  through the no-op-able helpers (``obs.counter``/``obs.recorder``/
  ``obs.record_span``); constructing reducers or touching the Registry
  directly bypasses the ``enabled()`` gate.
- ``trace-purity`` — no wall-clock reads, ``print``, lock traffic, or
  ``obs`` calls inside functions handed to ``jax.jit``/``shard_map``;
  they run once at trace time and vanish from the compiled program.

Entry points: :func:`run_lint` (in-process, returns findings) and
:func:`main` (the ``python -m brpc_tpu.analysis`` CLI; exit 0 = clean,
1 = findings, 2 = usage error).
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import os
import sys
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = ["Finding", "run_lint", "lint_files", "main", "ALL_CHECKS"]

ALL_CHECKS = ("ctypes-contract", "fiber-shared-state", "obs-guard",
              "trace-purity")

#: attribute names that look like a lock on self / a module
_LOCKISH = ("mu", "lock", "mutex")
#: container methods that mutate their receiver in place
_MUTATORS = {
    "append", "appendleft", "extend", "insert", "remove", "pop", "popleft",
    "clear", "update", "setdefault", "add", "discard", "sort", "reverse",
}
#: obs surface that hot paths must NOT touch directly (the no-op-able
#: helpers counter/recorder/record_span/span/enabled stay allowed)
_OBS_GUARDED = {
    "Registry", "default_registry", "expose", "Adder", "Maxer", "Miner",
    "LatencyRecorder", "Window", "PerSecond", "PassiveStatus",
}
_TRACERS = {"jit", "shard_map", "pjit"}
_TIME_FNS = {"time", "time_ns", "monotonic", "monotonic_ns",
             "perf_counter", "perf_counter_ns", "sleep"}


@dataclasses.dataclass
class Finding:
    check: str
    path: str
    line: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.check}] {self.message}"

    def to_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# small AST helpers
# ---------------------------------------------------------------------------

def _last_name(expr: ast.AST) -> Optional[str]:
    """'jax.jit' -> 'jit', 'jit' -> 'jit', else None."""
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _root_name(expr: ast.AST) -> Optional[str]:
    """'a.b.c' -> 'a' (the base Name of a dotted chain)."""
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        expr = expr.value
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _is_self_rooted(expr: ast.AST) -> bool:
    return _root_name(expr) == "self"


def _is_lockish_ctx(expr: ast.AST) -> bool:
    """True for `with self._mu:` / `with _load_mu:` style context exprs."""
    name = None
    if isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Name):
        name = expr.id
    elif isinstance(expr, ast.Call):
        # with self._mu.acquire_timeout(...) style — treat lock method
        # calls on a lockish receiver as lock context too
        return _is_lockish_ctx(expr.func)
    if name is None:
        return False
    low = name.lower()
    return any(part in low for part in _LOCKISH)


def _describe(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse of synthetic nodes
        return "<expr>"


# ---------------------------------------------------------------------------
# per-file scan state
# ---------------------------------------------------------------------------

class _FileScan:
    """One parsed file plus everything the checks extract from it."""

    def __init__(self, path: str, tree: ast.Module):
        self.path = path
        self.tree = tree
        # ctypes-contract
        self.native_decls: Dict[str, Set[str]] = {}  # brt_x -> declared kinds
        self.native_uses: List[Tuple[str, int]] = []  # (brt_x, line)
        self.cfunctype_protos: Set[str] = set()
        # obs-guard bookkeeping: names bound to obs modules / obs imports
        self.obs_module_aliases: Set[str] = set()
        self.obs_imported_names: Set[str] = set()
        self._collect()

    def _collect(self) -> None:
        decl_nodes: Set[int] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    self._note_decl(tgt, decl_nodes)
                if isinstance(node.value, ast.Call) and \
                        _last_name(node.value.func) == "CFUNCTYPE":
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            self.cfunctype_protos.add(tgt.id)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.endswith(".obs") or ".obs." in alias.name:
                        self.obs_module_aliases.add(
                            alias.asname or alias.name.split(".")[-1])
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod == "brpc_tpu" or mod.endswith(".obs"):
                    for alias in node.names:
                        if alias.name == "obs" or mod.endswith(".obs"):
                            tgt = alias.asname or alias.name
                            if alias.name == "obs":
                                self.obs_module_aliases.add(tgt)
                            else:
                                self.obs_imported_names.add(tgt)
                elif ".obs." in mod or mod.startswith("obs."):
                    for alias in node.names:
                        self.obs_imported_names.add(alias.asname or alias.name)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Attribute) and \
                    node.attr.startswith("brt_") and id(node) not in decl_nodes:
                self.native_uses.append((node.attr, node.lineno))

    def _note_decl(self, tgt: ast.AST, decl_nodes: Set[int]) -> None:
        if isinstance(tgt, ast.Attribute) and \
                tgt.attr in ("argtypes", "restype") and \
                isinstance(tgt.value, ast.Attribute) and \
                tgt.value.attr.startswith("brt_"):
            self.native_decls.setdefault(tgt.value.attr, set()).add(tgt.attr)
            decl_nodes.add(id(tgt.value))


# ---------------------------------------------------------------------------
# check: ctypes-contract
# ---------------------------------------------------------------------------

def _check_ctypes_contract(scans: List[_FileScan]) -> List[Finding]:
    findings: List[Finding] = []
    decls: Dict[str, Set[str]] = {}
    for sc in scans:
        for name, kinds in sc.native_decls.items():
            decls.setdefault(name, set()).update(kinds)
    reported: Set[Tuple[str, str]] = set()
    for sc in scans:
        for name, line in sc.native_uses:
            have = decls.get(name, set())
            missing = [k for k in ("argtypes", "restype") if k not in have]
            if not missing or (name, sc.path) in reported:
                continue
            reported.add((name, sc.path))
            findings.append(Finding(
                "ctypes-contract", sc.path, line,
                f"native symbol '{name}' used without "
                f"{' and '.join(missing)} declared anywhere in the scanned "
                f"tree (ctypes defaults restype to c_int — 64-bit handles "
                f"truncate); declare it in rpc._load()"))
    for sc in scans:
        findings.extend(_check_cfunctype_pinning(sc))
    return findings


def _check_cfunctype_pinning(sc: _FileScan) -> List[Finding]:
    protos = sc.cfunctype_protos
    if not protos:
        return []
    findings: List[Finding] = []
    # 1) inline construction passed straight to the native core (one walk
    #    over the whole tree so each call site reports exactly once)
    for node in ast.walk(sc.tree):
        if not isinstance(node, ast.Call):
            continue
        fn_last = _last_name(node.func)
        if fn_last is None or not fn_last.startswith("brt_"):
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Call) and _last_name(arg.func) in protos:
                findings.append(Finding(
                    "ctypes-contract", sc.path, arg.lineno,
                    f"CFUNCTYPE callback constructed inline in a "
                    f"'{fn_last}' call — nothing owns it and the GC frees "
                    f"it under the native core's feet; store it on the "
                    f"owner object first"))
    # 2) named callbacks passed to the native core but never pinned.
    #    Callbacks are attributed to the scope that DIRECTLY defines them;
    #    pinning/passing is searched through that whole scope subtree.
    scopes: List[ast.AST] = [sc.tree] + [
        n for n in ast.walk(sc.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for scope in scopes:
        callbacks = _callback_locals_shallow(scope, protos)
        if not callbacks:
            continue
        passed_to_native: Dict[str, int] = {}
        pinned: Set[str] = set()
        for node in ast.walk(scope):
            if isinstance(node, ast.Call):
                fn_last = _last_name(node.func)
                is_native = fn_last is not None and fn_last.startswith("brt_")
                for arg in list(node.args) + \
                        [kw.value for kw in node.keywords]:
                    if isinstance(arg, ast.Name) and arg.id in callbacks:
                        if is_native:
                            passed_to_native.setdefault(arg.id, arg.lineno)
                        else:
                            # arg of append()/add()/...: the owner keeps it
                            pinned.add(arg.id)
            elif isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id in callbacks:
                for tgt in node.targets:
                    if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                        pinned.add(node.value.id)
        for name, line in sorted(passed_to_native.items()):
            if name not in pinned:
                findings.append(Finding(
                    "ctypes-contract", sc.path, line,
                    f"CFUNCTYPE callback '{name}' is passed to the native "
                    f"core but never pinned on an owner object "
                    f"(self.<attr> = {name} or self.<list>.append({name})) "
                    f"— it is GC'd while the core still holds the pointer"))
    return findings


def _callback_locals_shallow(scope: ast.AST, protos: Set[str]
                             ) -> Dict[str, int]:
    """Like :func:`_callback_locals` but only DIRECT children of the scope
    (nested function scopes audit their own callbacks)."""
    out: Dict[str, int] = {}
    body = scope.body if hasattr(scope, "body") else []
    for node in body:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                and _last_name(node.value.func) in protos:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = node.lineno
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _last_name(dec) in protos:
                    out[node.name] = node.lineno
    return out


# ---------------------------------------------------------------------------
# check: fiber-shared-state
# ---------------------------------------------------------------------------

def _check_fiber_shared_state(sc: _FileScan) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(sc.tree):
        if isinstance(node, ast.ClassDef):
            findings.extend(_scan_handler_class(sc, node))
    return findings


def _handler_roots(cls: ast.ClassDef, methods: Dict[str, ast.AST]
                   ) -> Set[str]:
    roots: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Call):
            continue
        if _last_name(node.func) not in ("add_service", "add_async_service"):
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Attribute) and \
                    isinstance(arg.value, ast.Name) and \
                    arg.value.id == "self" and arg.attr in methods:
                roots.add(arg.attr)
    return roots


def _scan_handler_class(sc: _FileScan, cls: ast.ClassDef) -> List[Finding]:
    methods: Dict[str, ast.AST] = {
        n.name: n for n in cls.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    roots = _handler_roots(cls, methods)
    if not roots:
        return []
    findings: List[Finding] = []
    visited: Set[Tuple[str, bool]] = set()

    def mutation(node: ast.AST, meth: str, what: str) -> None:
        findings.append(Finding(
            "fiber-shared-state", sc.path, node.lineno,
            f"handler-reachable {cls.name}.{meth} mutates {what} outside a "
            f"`with self._mu` block — handlers run concurrently on fiber "
            f"workers (the ctypes trampoline releases the GIL)"))

    def scan(node: ast.AST, meth: str, locked: bool,
             global_names: Set[str]) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            now_locked = locked or any(
                _is_lockish_ctx(item.context_expr) for item in node.items)
            for item in node.items:
                scan(item.context_expr, meth, locked, global_names)
            for child in node.body:
                scan(child, meth, now_locked, global_names)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return  # nested defs get their own audit when reachable
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in targets:
                if isinstance(tgt, (ast.Attribute, ast.Subscript)) and \
                        _is_self_rooted(tgt) and not locked:
                    mutation(tgt, meth, _describe(tgt))
                elif isinstance(tgt, ast.Name) and tgt.id in global_names \
                        and not locked:
                    mutation(tgt, meth, f"module global '{tgt.id}'")
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute):
                if fn.attr == "at" and node.args and \
                        _is_self_rooted(node.args[0]) and not locked:
                    # np.<ufunc>.at(self.table, ...) mutates in place
                    mutation(node, meth, _describe(node.args[0]))
                elif fn.attr in _MUTATORS and _is_self_rooted(fn.value) \
                        and not locked:
                    mutation(node, meth,
                             f"{_describe(fn.value)} (via .{fn.attr}())")
                elif isinstance(fn.value, ast.Name) and \
                        fn.value.id == "self" and fn.attr in methods:
                    visit(fn.attr, locked)
        for child in ast.iter_child_nodes(node):
            scan(child, meth, locked, global_names)

    def visit(meth: str, locked: bool) -> None:
        if (meth, locked) in visited:
            return
        visited.add((meth, locked))
        fn = methods[meth]
        global_names = {
            name for n in ast.walk(fn) if isinstance(n, ast.Global)
            for name in n.names}
        for child in fn.body:
            scan(child, meth, locked, global_names)

    for root in sorted(roots):
        visit(root, False)
    return findings


# ---------------------------------------------------------------------------
# check: obs-guard
# ---------------------------------------------------------------------------

def _in_pkg_dir(path: str, dirname: str) -> bool:
    parts = os.path.normpath(path).split(os.sep)
    return dirname in parts


def _check_obs_guard(sc: _FileScan) -> List[Finding]:
    if _in_pkg_dir(sc.path, "obs"):
        return []  # the obs package itself owns the Registry
    findings: List[Finding] = []
    for node in ast.walk(sc.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        hit: Optional[str] = None
        if isinstance(fn, ast.Name) and fn.id in _OBS_GUARDED and \
                fn.id in sc.obs_imported_names:
            hit = fn.id
        elif isinstance(fn, ast.Attribute) and fn.attr in _OBS_GUARDED:
            root = _root_name(fn)
            if root in sc.obs_module_aliases:
                hit = f"{root}.{fn.attr}"
            elif fn.attr == "expose" and isinstance(fn.value, ast.Call) and \
                    _last_name(fn.value.func) in _OBS_GUARDED:
                hit = f"{_describe(fn.value.func)}().expose"
        if hit:
            findings.append(Finding(
                "obs-guard", sc.path, node.lineno,
                f"direct obs call '{hit}' outside brpc_tpu/obs — hot-path "
                f"instrumentation must use the no-op-able helpers "
                f"(obs.counter / obs.recorder / obs.record_span) so "
                f"disabling observability disables the cost"))
    return findings


# ---------------------------------------------------------------------------
# check: trace-purity
# ---------------------------------------------------------------------------

def _is_tracer_expr(expr: ast.AST) -> bool:
    return _last_name(expr) in _TRACERS


def _is_tracing_decorator(dec: ast.AST) -> bool:
    if _is_tracer_expr(dec):
        return True
    if isinstance(dec, ast.Call):
        if _is_tracer_expr(dec.func):
            return True  # @jax.jit(...) / @shard_map(mesh=...)
        if _last_name(dec.func) == "partial" and dec.args and \
                _is_tracer_expr(dec.args[0]):
            return True  # @partial(jax.jit, ...) / @partial(shard_map, ...)
    return False


def _traced_functions(tree: ast.Module) -> List[ast.AST]:
    traced: List[ast.AST] = []
    by_name: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name[node.name] = node
            if any(_is_tracing_decorator(d) for d in node.decorator_list):
                traced.append(node)
        elif isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Lambda):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    by_name[tgt.id] = node.value
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_tracer_expr(node.func) \
                and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Lambda):
                traced.append(arg)
            elif isinstance(arg, ast.Name) and arg.id in by_name:
                traced.append(by_name[arg.id])
    # dedup while keeping order
    seen: Set[int] = set()
    out = []
    for fn in traced:
        if id(fn) not in seen:
            seen.add(id(fn))
            out.append(fn)
    return out


def _check_trace_purity(sc: _FileScan) -> List[Finding]:
    findings: List[Finding] = []

    def impure(node: ast.AST, fn_name: str, what: str) -> None:
        findings.append(Finding(
            "trace-purity", sc.path, node.lineno,
            f"{what} inside '{fn_name}' which is traced by "
            f"jax.jit/shard_map — it runs once at trace time and vanishes "
            f"from the compiled program"))

    for fn in _traced_functions(sc.tree):
        fn_name = getattr(fn, "name", "<lambda>")
        for node in ast.walk(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if _is_lockish_ctx(item.context_expr):
                        impure(node, fn_name,
                               f"lock acquisition "
                               f"'{_describe(item.context_expr)}'")
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name) and f.id == "print":
                impure(node, fn_name, "print()")
            elif isinstance(f, ast.Attribute):
                root = _root_name(f)
                if root == "time" and f.attr in _TIME_FNS:
                    impure(node, fn_name, f"wall-clock call time.{f.attr}()")
                elif f.attr in ("acquire", "release") and \
                        _is_lockish_ctx(f.value):
                    impure(node, fn_name,
                           f"lock call '{_describe(f)}()'")
                elif root == "obs" or root in sc.obs_module_aliases:
                    impure(node, fn_name,
                           f"obs instrumentation '{_describe(f)}()'")
                elif root == "threading" and f.attr in ("Lock", "RLock"):
                    impure(node, fn_name, "lock construction")
    return findings


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def _iter_py_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git", "build")]
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
    return out


def lint_files(files: Iterable[str],
               checks: Optional[Sequence[str]] = None) -> List[Finding]:
    active = set(checks or ALL_CHECKS)
    unknown = active - set(ALL_CHECKS)
    if unknown:
        raise ValueError(f"unknown checks: {sorted(unknown)}")
    scans: List[_FileScan] = []
    findings: List[Finding] = []
    for path in files:
        try:
            with open(path, "r", encoding="utf-8") as f:
                src = f.read()
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            findings.append(Finding(
                "syntax", path, e.lineno or 0, f"does not parse: {e.msg}"))
            continue
        scans.append(_FileScan(path, tree))
    for sc in scans:
        if "fiber-shared-state" in active:
            findings.extend(_check_fiber_shared_state(sc))
        if "obs-guard" in active:
            findings.extend(_check_obs_guard(sc))
        if "trace-purity" in active:
            findings.extend(_check_trace_purity(sc))
    if "ctypes-contract" in active:
        findings.extend(_check_ctypes_contract(scans))
    findings.sort(key=lambda f: (f.path, f.line, f.check))
    return findings


def run_lint(paths: Sequence[str],
             checks: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint every ``.py`` file under ``paths`` (files or directories)."""
    return lint_files(_iter_py_files(paths), checks)


def _default_target() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m brpc_tpu.analysis",
        description="Framework-invariant linter for the brpc_tpu fabric")
    parser.add_argument("paths", nargs="*",
                        help="files/directories to lint "
                             "(default: the brpc_tpu package)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--check", action="append", metavar="NAME",
                        help=f"run only the named check(s); "
                             f"known: {', '.join(ALL_CHECKS)}")
    args = parser.parse_args(argv)
    try:
        findings = run_lint(args.paths or [_default_target()], args.check)
    except ValueError as e:
        parser.error(str(e))
    if args.format == "json":
        print(json.dumps({
            "count": len(findings),
            "checks": list(args.check or ALL_CHECKS),
            "findings": [f.to_dict() for f in findings],
        }, indent=2))
    else:
        for f in findings:
            print(f.format())
        print(f"{len(findings)} finding(s)" if findings
              else "clean: no findings", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
