"""CLI entry: ``python -m brpc_tpu.analysis [paths...] [--format=json]
[--check NAME] [--baseline FILE] [--write-baseline FILE]``.

Exit 0 when clean (or every finding is suppressed by the baseline),
1 when any new check fires, 2 on usage errors (unknown ``--check``
names list the valid set) — suitable as a CI gate
(``tests/test_lint_clean.py`` runs the same pass in-process against
``tests/lint_baseline.json``).
"""

import sys

from brpc_tpu.analysis.lint import main

if __name__ == "__main__":
    sys.exit(main())
