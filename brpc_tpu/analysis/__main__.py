"""CLI entry: ``python -m brpc_tpu.analysis [paths...] [--format=json]``.

Exit 0 when clean, 1 when any check fires, 2 on usage errors — suitable
as a CI gate (``tests/test_lint_clean.py`` runs the same pass
in-process).
"""

import sys

from brpc_tpu.analysis.lint import main

if __name__ == "__main__":
    sys.exit(main())
